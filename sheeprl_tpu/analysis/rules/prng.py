"""SA002 — PRNG key reuse.

Consuming the same key twice produces **correlated randomness**: two dropout
masks that agree, two exploration streams in lockstep — statistically wrong
results with no crash. The discipline everywhere in this repo is
"split-before-use": every consumption gets a fresh key from
``jax.random.split`` / ``fold_in``. This rule tracks key-typed names through
each function body and flags (a) a second consumption without an intervening
reassignment and (b) consumption inside a loop of a key minted outside it.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from sheeprl_tpu.analysis.engine import Context, Finding, Module, Rule
from sheeprl_tpu.analysis.pyutil import (
    FUNCTION_NODES,
    call_name,
    last_segment,
    stmt_assigned_names,
)

# calls that MINT keys: assigning from them makes the target a key name
_KEY_SOURCES = {"PRNGKey", "split", "fold_in", "key", "clone"}
# passing a key here neither consumes nor invalidates it
_NEUTRAL_SINKS = {
    "split",
    "fold_in",
    "PRNGKey",
    "key_data",
    "device_put",
    "device_get",
    "to_mesh",
    "spec_like",
    "specs_of",
    "block_until_ready",
    "append",
    "isinstance",
    "len",
    "type",
    "repr",
    "str",
    "id",
}


@dataclass
class _KeyState:
    minted_line: int
    minted_loops: Tuple[int, ...]  # id-stack of enclosing loops at mint time
    consumed_at: Optional[int] = None
    flagged: bool = False
    loop_flagged: Set[int] = field(default_factory=set)


class PrngKeyReuseRule(Rule):
    id = "SA002"
    name = "prng-key-reuse"
    severity = "error"
    hint = (
        "split before every consumption: `key, sub = jax.random.split(key)` (or "
        "fold_in a loop/shard index) so each use sees an independent stream"
    )

    def run(self, ctx: Context) -> Iterator[Finding]:
        for module in ctx.modules:
            yield from self._check_tree(module, module.tree)

    def _check_tree(self, module: Module, tree: ast.AST) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if isinstance(node, FUNCTION_NODES):
                yield from self._check_function(module, node)

    # ----- per-function linear scan ----------------------------------------
    def _check_function(self, module: Module, fn: ast.AST) -> Iterator[Finding]:
        keys: Dict[str, _KeyState] = {}
        findings: List[Finding] = []

        def mint(name: str, line: int, loops: Tuple[int, ...]) -> None:
            keys[name] = _KeyState(minted_line=line, minted_loops=loops)

        def visit_expr(
            expr: ast.AST, loops: Tuple[int, ...], rebinding: Set[str] = frozenset()
        ) -> None:
            """Find key consumptions in an expression (calls taking a key arg)."""
            for node in ast.walk(expr):
                if not isinstance(node, ast.Call):
                    continue
                seg = last_segment(call_name(node)) or ""
                neutral = seg in _NEUTRAL_SINKS
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if not isinstance(arg, ast.Name) or arg.id not in keys:
                        continue
                    state = keys[arg.id]
                    if neutral:
                        continue
                    if arg.id in rebinding:
                        # key threading: `out, key = f(obs, key)` — the callee
                        # returns the split successor, no reuse possible
                        continue
                    line = getattr(node, "lineno", getattr(fn, "lineno", 1))
                    # (b) consumption in a loop the key was minted outside of
                    inner = [l for l in loops if l not in state.minted_loops]
                    if inner and inner[-1] not in state.loop_flagged:
                        state.loop_flagged.add(inner[-1])
                        findings.append(
                            self.finding(
                                module,
                                node,
                                f"key '{arg.id}' (minted at line {state.minted_line}) is "
                                f"consumed inside a loop without a per-iteration split — "
                                "every iteration sees the SAME randomness",
                                scope=self._qualname(fn),
                            )
                        )
                        continue
                    # (a) second consumption without reassignment
                    if state.consumed_at is not None and not state.flagged:
                        state.flagged = True
                        findings.append(
                            self.finding(
                                module,
                                node,
                                f"key '{arg.id}' already consumed at line "
                                f"{state.consumed_at} is consumed again without an "
                                "intervening split/fold_in — correlated randomness",
                                scope=self._qualname(fn),
                            )
                        )
                    elif state.consumed_at is None:
                        state.consumed_at = line

        def clone_state(s: _KeyState) -> _KeyState:
            return _KeyState(
                minted_line=s.minted_line,
                minted_loops=s.minted_loops,
                consumed_at=s.consumed_at,
                flagged=s.flagged,
                loop_flagged=set(s.loop_flagged),
            )

        def visit_block(body, loops: Tuple[int, ...]) -> None:
            for stmt in body:
                if isinstance(stmt, FUNCTION_NODES + (ast.ClassDef,)):
                    continue
                # scan the statement's own expressions (not its nested blocks)
                if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Expr, ast.Return)):
                    if getattr(stmt, "value", None) is not None:
                        visit_expr(stmt.value, loops, stmt_assigned_names(stmt))
                elif isinstance(stmt, (ast.If, ast.While)):
                    visit_expr(stmt.test, loops)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    visit_expr(stmt.iter, loops)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        visit_expr(item.context_expr, loops)
                elif isinstance(stmt, ast.Assert):
                    visit_expr(stmt.test, loops)
                elif isinstance(stmt, ast.Raise) and stmt.exc is not None:
                    visit_expr(stmt.exc, loops)
                # (re)bindings AFTER the RHS was scanned: `k, sub = split(k)`
                bound = stmt_assigned_names(stmt)
                if bound:
                    minted = self._is_key_mint(stmt)
                    for name in bound:
                        if minted:
                            mint(name, getattr(stmt, "lineno", 1), loops)
                        elif name in keys:
                            del keys[name]  # rebound to something else: not a key anymore
                # recurse into nested blocks
                if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                    visit_block(stmt.body, loops + (id(stmt),))
                    visit_block(stmt.orelse, loops)
                elif isinstance(stmt, ast.If):
                    # branches are mutually exclusive: each starts from the
                    # pre-if key state, and a key consumed in only ONE branch
                    # is NOT consumed after the if (the k_rep-in-if/else
                    # pattern in dreamer agents is legal)
                    snapshot = {n: clone_state(s) for n, s in keys.items()}
                    visit_block(stmt.body, loops)
                    body_keys = dict(keys)
                    keys.clear()
                    keys.update({n: clone_state(s) for n, s in snapshot.items()})
                    visit_block(stmt.orelse, loops)
                    merged: Dict[str, _KeyState] = {}
                    for n in set(body_keys) & set(keys):
                        b, o = body_keys[n], keys[n]
                        m = clone_state(b)
                        m.consumed_at = (
                            b.consumed_at
                            if (b.consumed_at is not None and o.consumed_at is not None)
                            else None
                        )
                        m.flagged = b.flagged or o.flagged
                        m.loop_flagged = b.loop_flagged | o.loop_flagged
                        merged[n] = m
                    keys.clear()
                    keys.update(merged)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    visit_block(stmt.body, loops)
                elif isinstance(stmt, ast.Try):
                    visit_block(stmt.body, loops)
                    for handler in stmt.handlers:
                        visit_block(handler.body, loops)
                    visit_block(stmt.orelse, loops)
                    visit_block(stmt.finalbody, loops)

        visit_block(fn.body, ())
        yield from findings

    @staticmethod
    def _is_key_mint(stmt: ast.stmt) -> bool:
        value = getattr(stmt, "value", None)
        if value is None:
            return False
        # direct call, or subscript of a split result: split(key)[0]
        node = value
        if isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Call):
            seg = last_segment(call_name(node))
            return seg in _KEY_SOURCES
        return False

    @staticmethod
    def _qualname(fn: ast.AST) -> str:
        return getattr(fn, "name", "<lambda>")
