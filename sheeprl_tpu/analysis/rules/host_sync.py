"""SA001 — host synchronization inside jit-traced code.

A single ``.item()``, ``float(tracer)``, ``np.asarray(tracer)``,
``jax.device_get`` or ``print`` inside a jit-reachable function either fails
at trace time (array conversion of a tracer) or — worse — silently runs at
trace time only / forces a device round-trip per call, costing the order of
magnitude the fused paths exist to save. The dynamic counterpart is the
``jax.transfer_guard`` tests; this rule catches the pattern at the source.
"""

from __future__ import annotations

import ast
from typing import Iterator

from sheeprl_tpu.analysis.engine import Context, Finding, Rule
from sheeprl_tpu.analysis.pyutil import (
    call_name,
    last_segment,
    names_in,
    tainted_names,
    walk_own,
)

# device->host pulls regardless of the argument (the receiver is device data
# by construction, or the call itself is the sync)
_ALWAYS_HOST_SYNC_ATTRS = {"item", "block_until_ready", "tolist"}
_ALWAYS_HOST_SYNC_CALLS = {"jax.device_get", "device_get", "jax.block_until_ready"}
# host-materializing constructors: a pull when fed a tracer-tainted value
_NUMPY_MATERIALIZERS = {"asarray", "array", "ascontiguousarray"}
_NUMPY_MODULES = {"np", "numpy", "onp"}
_CAST_BUILTINS = {"float", "int", "bool", "complex"}


class HostSyncRule(Rule):
    id = "SA001"
    name = "host-sync-in-traced-code"
    severity = "error"
    hint = (
        "keep the value on device (jnp ops), move the pull outside the jitted "
        "function, or use jax.debug.print / jax.debug.callback for tracing-safe output"
    )

    def run(self, ctx: Context) -> Iterator[Finding]:
        for module in ctx.modules:
            for fi in ctx.callgraph.traced_functions(module.rel):
                taint = tainted_names(fi.node)
                for node in walk_own(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    name = call_name(node)
                    seg = last_segment(name)
                    if seg in _ALWAYS_HOST_SYNC_ATTRS and isinstance(node.func, ast.Attribute):
                        yield self.finding(
                            module,
                            node,
                            f".{seg}() in jit-traced '{fi.qualname}' forces a device->host sync",
                            scope=fi.qualname,
                        )
                    elif name in _ALWAYS_HOST_SYNC_CALLS:
                        yield self.finding(
                            module,
                            node,
                            f"{name}() in jit-traced '{fi.qualname}' pulls device data to host",
                            scope=fi.qualname,
                        )
                    elif name == "print":
                        yield self.finding(
                            module,
                            node,
                            f"print() in jit-traced '{fi.qualname}' runs at trace time only "
                            "(and never per step)",
                            scope=fi.qualname,
                        )
                    elif (
                        seg in _NUMPY_MATERIALIZERS
                        and name is not None
                        and name.split(".", 1)[0] in _NUMPY_MODULES
                        and self._args_tainted(node, taint)
                    ):
                        yield self.finding(
                            module,
                            node,
                            f"{name}() on a traced value in '{fi.qualname}' materializes a "
                            "tracer on host (TracerArrayConversionError or a silent pull)",
                            scope=fi.qualname,
                        )
                    elif (
                        name in _CAST_BUILTINS
                        and node.args
                        and self._args_tainted(node, taint)
                    ):
                        yield self.finding(
                            module,
                            node,
                            f"{name}() on a traced value in '{fi.qualname}' concretizes the "
                            "tracer (TracerBoolConversionError / host sync)",
                            scope=fi.qualname,
                        )

    @staticmethod
    def _args_tainted(call: ast.Call, taint: set) -> bool:
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if names_in(arg) & taint:
                return True
        return False
