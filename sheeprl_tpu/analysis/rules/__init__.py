"""Rule registry for :mod:`sheeprl_tpu.analysis`.

Each rule is an :class:`~sheeprl_tpu.analysis.engine.Rule` subclass with a
stable id (``SA00x``). ``default_rules()`` returns one fresh instance of each —
rules are stateless between runs by construction, but fresh instances keep any
future per-run caching honest.
"""

from __future__ import annotations

from typing import Dict, List, Type

from sheeprl_tpu.analysis.engine import Rule
from sheeprl_tpu.analysis.rules.config_keys import ConfigKeyRule
from sheeprl_tpu.analysis.rules.donation import UseAfterDonateRule
from sheeprl_tpu.analysis.rules.failpoint_names import FailpointNameRule
from sheeprl_tpu.analysis.rules.host_sync import HostSyncRule
from sheeprl_tpu.analysis.rules.prng import PrngKeyReuseRule
from sheeprl_tpu.analysis.rules.retrace import RetraceHazardRule

RULE_CLASSES: List[Type[Rule]] = [
    HostSyncRule,
    PrngKeyReuseRule,
    UseAfterDonateRule,
    RetraceHazardRule,
    FailpointNameRule,
    ConfigKeyRule,
]

RULES_BY_ID: Dict[str, Type[Rule]] = {cls.id: cls for cls in RULE_CLASSES}


def default_rules() -> List[Rule]:
    return [cls() for cls in RULE_CLASSES]


__all__ = [
    "RULE_CLASSES",
    "RULES_BY_ID",
    "default_rules",
    "HostSyncRule",
    "PrngKeyReuseRule",
    "UseAfterDonateRule",
    "RetraceHazardRule",
    "FailpointNameRule",
    "ConfigKeyRule",
]
