"""SA006 — config key drift.

``cfg`` is a :class:`dotdict`: ``cfg.algo.rolout_steps`` (typo) raises
``AttributeError`` only when that exact line runs — usually ten minutes into a
TPU job, after compile. This rule resolves every ``cfg.<dotted>`` chain in the
training/serving/orchestration planes against the **union** of the Hydra-style
config tree under ``sheeprl_tpu/configs/``:

* every ``<group>/<option>.yaml`` body is unioned into the group's subtree;
* ``defaults:`` mounts (``- /optim@world_model.optimizer: adam``) graft the
  source group's union at the mount path, so ``cfg.algo.critic.optimizer.lr``
  resolves;
* ``# @package _global_`` files (``exp/``) merge at the root;
* the root also carries ``config.yaml``'s own keys.

Chains are validated left-to-right while the tree has something to say: a leaf
(scalar in every yaml), an *open* node (leaf in one file, mapping in another —
shape varies by option), a ``_``-prefixed segment, or a dict-method segment
(``get``/``items``/...) all end validation without a finding. Only a segment
missing from a node that is a mapping in **every** contributing file flags.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Any, Dict, Iterator, List, Mapping, Optional, Set, Tuple

from sheeprl_tpu.analysis.engine import Context, Finding, Module, Rule

try:
    import yaml
except ImportError:  # pragma: no cover - yaml ships with the image
    yaml = None

# chain bases that denote the composed root config
_CFG_BASES = {"cfg", "config"}
# only these planes receive the fully-composed cfg; helpers elsewhere get subtrees
_CHECKED_PREFIXES = ("sheeprl_tpu/algos/", "sheeprl_tpu/serve/", "sheeprl_tpu/orchestrate/")
# dict/dotdict API — a chain continuing through these is method access, not keys
_METHOD_SEGMENTS = {
    "get",
    "pop",
    "setdefault",
    "update",
    "copy",
    "items",
    "keys",
    "values",
    "as_dict",
    "to_dict",
    "to_container",
    "lower",
    "upper",
    "startswith",
    "endswith",
    "split",
    "strip",
    "format",
    "join",
}

# tree node values: dict (mapping), None (scalar leaf), _OPEN (shape varies)
_OPEN = object()

_MOUNT_RE = re.compile(r"^(?:override\s+)?/?(?P<group>[\w.-]+)@(?P<path>[\w.]+)$")


def _merge_yaml(dst: Dict[str, Any], src: Mapping) -> None:
    for key, value in src.items():
        if not isinstance(key, str):
            continue
        if isinstance(value, Mapping):
            cur = dst.get(key)
            if isinstance(cur, dict):
                _merge_yaml(cur, value)
            elif key in dst and cur is not _OPEN:
                dst[key] = _OPEN  # leaf in one file, mapping in another
            else:
                node: Dict[str, Any] = {}
                _merge_yaml(node, value)
                dst[key] = node
        else:
            cur = dst.get(key)
            if isinstance(cur, dict):
                dst[key] = _OPEN
            elif key not in dst:
                dst[key] = None


def _mount(tree: Dict[str, Any], path: List[str], subtree: Dict[str, Any]) -> None:
    cur = tree
    for seg in path[:-1]:
        nxt = cur.get(seg)
        if not isinstance(nxt, dict):
            nxt = {}
            cur[seg] = nxt
        cur = nxt
    leaf = cur.get(path[-1])
    if isinstance(leaf, dict):
        for k, v in subtree.items():
            leaf.setdefault(k, v)
    else:
        cur[path[-1]] = dict(subtree)


def build_config_tree(configs_dir: str) -> Optional[Dict[str, Any]]:
    """Union all yaml option files into one permissive key tree. ``None`` when
    the configs dir (or yaml itself) is unavailable — the rule then no-ops."""
    if yaml is None or not os.path.isdir(configs_dir):
        return None

    groups: Dict[str, Dict[str, Any]] = {}
    group_defaults: Dict[str, List[Mapping]] = {}
    global_bodies: List[Mapping] = []
    root_body: Dict[str, Any] = {}

    for entry in sorted(os.listdir(configs_dir)):
        path = os.path.join(configs_dir, entry)
        if os.path.isdir(path):
            union: Dict[str, Any] = {}
            defaults: List[Mapping] = []
            for fname in sorted(os.listdir(path)):
                if not fname.endswith((".yaml", ".yml")):
                    continue
                fpath = os.path.join(path, fname)
                try:
                    with open(fpath, "r", encoding="utf-8") as f:
                        raw = f.read()
                    data = yaml.safe_load(raw)
                except Exception:
                    continue
                if not isinstance(data, Mapping):
                    continue
                body = {k: v for k, v in data.items() if k != "defaults"}
                if "@package _global_" in "\n".join(raw.splitlines()[:3]):
                    global_bodies.append(body)
                else:
                    _merge_yaml(union, body)
                dlist = data.get("defaults")
                if isinstance(dlist, list):
                    defaults.extend(d for d in dlist if isinstance(d, Mapping))
            groups[entry] = union
            group_defaults[entry] = defaults
        elif entry.endswith((".yaml", ".yml")):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    data = yaml.safe_load(f.read())
            except Exception:
                continue
            if isinstance(data, Mapping):
                _merge_yaml(root_body, {k: v for k, v in data.items() if k != "defaults"})

    # graft defaults-list mounts: "- /optim@world_model.optimizer: adam" in an
    # algo file mounts the optim union under algo.world_model.optimizer
    for group, defaults in group_defaults.items():
        for d in defaults:
            for key in d:
                if not isinstance(key, str):
                    continue
                m = _MOUNT_RE.match(key.strip())
                if not m:
                    continue
                src = groups.get(m.group("group"))
                if src is None:
                    continue
                _mount(groups[group], m.group("path").split("."), src)

    tree: Dict[str, Any] = dict(root_body)
    for group, union in groups.items():
        cur = tree.get(group)
        if isinstance(cur, dict):
            for k, v in union.items():
                cur.setdefault(k, v)
        else:
            tree[group] = union
    for body in global_bodies:
        _merge_yaml(tree, body)
    return tree


class ConfigKeyRule(Rule):
    id = "SA006"
    name = "config-key-drift"
    severity = "warning"
    hint = (
        "check the key against sheeprl_tpu/configs/<group>/*.yaml — add it to the "
        "yaml if it is new, or fix the access if it drifted"
    )

    def run(self, ctx: Context) -> Iterator[Finding]:
        tree = ctx.extras.get("config_tree")
        if tree is None:
            tree = build_config_tree(os.path.join(ctx.package_dir, "configs"))
            ctx.extras["config_tree"] = tree if tree is not None else False
        if not tree:
            return
        for module in ctx.modules:
            rel = module.rel.replace(os.sep, "/")
            if not rel.startswith(_CHECKED_PREFIXES):
                continue
            yield from self._check_module(module, tree)

    def _check_module(self, module: Module, tree: Dict[str, Any]) -> Iterator[Finding]:
        consumed: Set[int] = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Attribute) or id(node) in consumed:
                continue
            segments, base = self._unwind(node, consumed)
            if base not in _CFG_BASES or not segments:
                continue
            # only chains rooted at a known top-level key are checkable: the
            # codebase also passes *sub*-configs around under the name `cfg`
            if segments[0] not in tree:
                continue
            bad = self._validate(segments, tree)
            if bad is not None:
                prefix, seg = bad
                yield self.finding(
                    module,
                    node,
                    f"config key '{'.'.join(prefix + [seg])}' not found in any yaml under "
                    f"configs/ (chain cfg.{'.'.join(segments)})",
                    scope="<module>",
                )

    @staticmethod
    def _unwind(node: ast.Attribute, consumed: Set[int]) -> Tuple[List[str], Optional[str]]:
        """cfg.a.b.c -> (["a","b","c"], "cfg"); marks inner nodes consumed."""
        segments: List[str] = []
        cur: ast.AST = node
        while isinstance(cur, ast.Attribute):
            consumed.add(id(cur))
            segments.append(cur.attr)
            cur = cur.value
        segments.reverse()
        if isinstance(cur, ast.Name):
            # self.cfg.algo...: the loop swallowed "cfg" into segments; re-root
            if cur.id == "self" and segments and segments[0] in _CFG_BASES:
                return segments[1:], segments[0]
            return segments, cur.id
        return segments, None

    @staticmethod
    def _validate(
        segments: List[str], tree: Dict[str, Any]
    ) -> Optional[Tuple[List[str], str]]:
        cur: Any = tree
        prefix: List[str] = []
        for seg in segments:
            if seg.startswith("_") or seg in _METHOD_SEGMENTS:
                return None
            if not isinstance(cur, dict):
                return None  # leaf or open: shape unknown past here
            if seg not in cur:
                return (prefix, seg)
            cur = cur[seg]
            prefix.append(seg)
        return None
