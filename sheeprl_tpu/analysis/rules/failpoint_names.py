"""SA005 — failpoint name drift.

A chaos drill that injects ``ckpt.pre_fsnyc`` (typo) instead of
``ckpt.pre_fsync`` silently tests nothing: :func:`failpoint` sites that nobody
configured fire zero actions and the smoke "passes". The canonical name list
lives in ``core/failpoints.py``'s ``KNOWN_FAILPOINTS`` registry; this rule
resolves every literal failpoint reference in the tree against it:

* ``failpoint("name")`` / ``failpoints.has("name")`` call sites,
* spec strings handed to ``configure()`` / ``active()`` / the
  ``SHEEPRL_TPU_FAILPOINTS`` env var (``"name:action[:arg][:trigger]"``,
  comma-separated; f-strings are checked up to their first ``{``),
* action tokens in those specs against the runtime's ``_ACTIONS`` tuple.

The registry is read **statically** — the analyzer never imports the runtime —
and test files are exempt (unit tests mint throwaway names on purpose).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from sheeprl_tpu.analysis.engine import Context, Finding, Module, Rule
from sheeprl_tpu.analysis.pyutil import (
    call_name,
    fstring_prefix,
    last_segment,
    literal_str,
)

_ENV_VAR = "SHEEPRL_TPU_FAILPOINTS"
# spec-consuming callables: every str literal argument is a spec string
_SPEC_SINKS = {"configure", "active"}
# name-consuming callables: the first str literal argument is a bare name
_NAME_SINKS = {"failpoint", "has", "spec_entry"}


def load_registry(package_dir: str) -> Tuple[Set[str], Set[str]]:
    """Statically read ``KNOWN_FAILPOINTS`` keys and ``_ACTIONS`` from
    ``core/failpoints.py``. Empty sets disable the corresponding check (the
    rule degrades to a no-op on trees without the registry)."""
    path = os.path.join(package_dir, "core", "failpoints.py")
    names: Set[str] = set()
    actions: Set[str] = set()
    try:
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return names, actions
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        for target in targets:
            if not isinstance(target, ast.Name):
                continue
            if target.id == "KNOWN_FAILPOINTS" and isinstance(node.value, ast.Dict):
                for key in node.value.keys:
                    lit = literal_str(key)
                    if lit is not None:
                        names.add(lit)
            elif target.id == "_ACTIONS":
                try:
                    actions.update(str(a) for a in ast.literal_eval(node.value))
                except (ValueError, SyntaxError):
                    pass
    return names, actions


class FailpointNameRule(Rule):
    id = "SA005"
    name = "failpoint-name-drift"
    severity = "error"
    hint = (
        "use a name from core/failpoints.py KNOWN_FAILPOINTS (or register the new "
        "site there); build specs with failpoints.spec_entry() to get this check at runtime"
    )

    def run(self, ctx: Context) -> Iterator[Finding]:
        known, actions = load_registry(ctx.package_dir)
        if not known:
            return
        for module in ctx.modules:
            if self._is_test_file(module.rel):
                continue
            if module.rel.replace(os.sep, "/").endswith("core/failpoints.py"):
                continue  # the registry itself
            yield from self._check_module(module, known, actions)

    @staticmethod
    def _is_test_file(rel: str) -> bool:
        parts = rel.replace(os.sep, "/").split("/")
        return any(p in ("tests", "test_analysis", "fixtures") for p in parts) or parts[
            -1
        ].startswith("test_")

    # -----------------------------------------------------------------------
    def _check_module(
        self, module: Module, known: Set[str], actions: Set[str]
    ) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                dotted = call_name(node) or ""
                seg = last_segment(dotted) or ""
                # `configure`/`active` are common method names (trace.configure,
                # logging handlers, ...): only the failpoints module's — or a
                # bare `from ... import configure` — consumes spec strings
                qualifier = dotted.rsplit(".", 2)[-2] if "." in dotted else "failpoints"
                if seg in _NAME_SINKS and node.args:
                    name = literal_str(node.args[0])
                    if name is not None and name not in known:
                        yield self._unknown_name(module, node.args[0], name, known, seg)
                elif seg in _SPEC_SINKS and qualifier == "failpoints":
                    for arg in list(node.args) + [kw.value for kw in node.keywords]:
                        yield from self._check_spec_expr(module, arg, known, actions)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                # FOO = "spec" where the env-var name appears in the statement,
                # and env dict writes: env["SHEEPRL_TPU_FAILPOINTS"] = "spec"
                if self._mentions_env_var(node):
                    value = getattr(node, "value", None)
                    # a value equal to the env-var name is its constant
                    # definition (`_ENV_VAR = "SHEEPRL_TPU_FAILPOINTS"`), not a spec
                    if value is not None and literal_str(value) != _ENV_VAR:
                        yield from self._check_spec_expr(module, value, known, actions)
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if k is not None and literal_str(k) == _ENV_VAR and v is not None:
                        yield from self._check_spec_expr(module, v, known, actions)

    @staticmethod
    def _mentions_env_var(stmt: ast.stmt) -> bool:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Constant) and node.value == _ENV_VAR:
                return True
            if isinstance(node, ast.Name) and node.id == _ENV_VAR:
                return True
        return False

    def _check_spec_expr(
        self, module: Module, expr: ast.AST, known: Set[str], actions: Set[str]
    ) -> Iterator[Finding]:
        spec = literal_str(expr)
        if spec is None and isinstance(expr, ast.JoinedStr):
            # f-string: only the constant prefix before the first placeholder is
            # checkable; its trailing entry may be cut mid-name, so keep it only
            # when the name field visibly completed (a ':' follows it)
            spec = fstring_prefix(expr)
            entries = spec.split(",") if spec else []
            if entries and ":" not in entries[-1]:
                entries = entries[:-1]
            spec = ",".join(entries)
        if not spec:
            return
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            fields = entry.split(":")
            name = fields[0].strip()
            if name and name not in known:
                yield self._unknown_name(module, expr, name, known, "spec")
            if len(fields) >= 2:
                action = fields[1].strip()
                if action and actions and action not in actions:
                    yield self.finding(
                        module,
                        expr,
                        f"unknown failpoint action '{action}' in spec entry '{entry}' "
                        f"(known: {', '.join(sorted(actions))})",
                        scope="<module>",
                    )

    def _unknown_name(
        self, module: Module, node: ast.AST, name: str, known: Set[str], via: str
    ) -> Finding:
        hint_names = ", ".join(sorted(n for n in known if n.split(".")[0] == name.split(".")[0]))
        extra = f" — nearby registered: {hint_names}" if hint_names else ""
        return self.finding(
            module,
            node,
            f"failpoint name '{name}' (via {via}) is not in KNOWN_FAILPOINTS{extra}",
            scope="<module>",
        )
