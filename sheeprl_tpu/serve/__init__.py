"""Robust policy-serving runtime.

Composes the pieces earlier rounds built for training into an inference path
whose headline property is robustness under load and failure, not just
throughput:

- :mod:`sheeprl_tpu.serve.batcher` — micro-batcher coalescing concurrent
  observation requests onto fixed :func:`~sheeprl_tpu.core.compile.pow2_bucket`
  batch shapes (no request mix ever retraces), with bounded-queue admission
  control (reject-with-retry-after vs shed-oldest) and per-request deadline
  budgets that drop work already past its deadline.
- :mod:`sheeprl_tpu.serve.engine` — rebuilds the agent from a checkpoint's
  sidecar config, AOT-warms every bucket it may route to, and runs the fused
  raw-obs act path pinned to an immutable weight :class:`Generation`.
- :mod:`sheeprl_tpu.serve.reload` — certified hot-reload: poll
  ``latest_certified``, warm + canary the new params off the serving path, and
  atomically swap generations without dropping in-flight requests (rollback on
  a failed post-swap canary).
- :mod:`sheeprl_tpu.serve.server` — the TCP frontend: JSON-lines protocol,
  ``Serve/*`` stats, readiness/liveness surface, graceful SIGTERM drain under
  :class:`~sheeprl_tpu.core.resilience.PreemptionGuard`.
- :mod:`sheeprl_tpu.serve.fleet` — the replica-fleet supervisor: N serve
  subprocesses with ready-file handshakes, control-plane heartbeat liveness,
  epoch-stamped membership, budgeted restart backoff and rolling certified
  deploys (canary + fleet-wide rollback).
- :mod:`sheeprl_tpu.serve.router` — the failover frontend: same JSON-lines
  protocol outward, health-probed epoch-fenced membership, least-outstanding
  replica pick, bounded deadline-aware retry to a different replica, and
  request priority classes threaded down to the batcher's shed policy.

Config group: ``sheeprl_tpu/configs/serve/default.yaml``; :func:`resolve`
fills defaults so sidecar configs recorded before this subsystem existed still
serve.
"""

from __future__ import annotations

from typing import Any, Dict

_DEFAULTS: Dict[str, Dict[str, Any]] = {
    "server": {"host": "127.0.0.1", "port": 0, "ready_file": None, "latency_window": 4096},
    "policy": {"greedy": True},
    "batch": {"max_size": 16, "max_wait_ms": 5.0},
    "queue": {
        "max_depth": 128,
        "admission": "reject",
        "retry_after_ms": 25.0,
        "deadline_ms": 1000.0,
    },
    "reload": {"enabled": True, "poll_s": 1.0, "canary": True, "degraded_after": 3},
    # replica-fleet supervisor (serve/fleet.py): spawn/heartbeat/restart/deploy
    # knobs. Replicas run with reload DISABLED — the supervisor owns weight
    # changes via rolling certified deploys, so every replica's generation is
    # an explicit, epoch-stamped supervisor decision.
    "fleet": {
        "replicas": 3,
        "heartbeat_s": 0.25,
        "heartbeat_timeout_s": 10.0,
        "restart_backoff_s": 0.25,
        "restart_backoff_max_s": 2.0,
        "max_restarts": 8,
        "drain_timeout_s": 45.0,
        "deploy_poll_s": 0.5,
        "deploy_retry_s": 1.0,
    },
    # failover router (serve/router.py): the outward-facing frontend
    "router": {
        "host": "127.0.0.1",
        "port": 0,
        "retry_budget": 3,
        "retry_backoff_ms": 25.0,
        "membership_poll_s": 0.1,
        "dial_timeout_s": 5.0,
        "default_priority": 1,
        "max_workers": 64,
    },
}


class _View:
    """Attribute view over a plain dict (so code reads ``sv.queue.admission``)."""

    def __init__(self, d: Dict[str, Any]):
        self._d = d

    def __getattr__(self, name: str) -> Any:
        try:
            v = self._d[name]
        except KeyError:
            raise AttributeError(name) from None
        return _View(v) if isinstance(v, dict) else v


def resolve(cfg: Any) -> _View:
    """Defaults-filled view of ``cfg.serve``.

    Tolerates a MISSING group entirely: serving boots from the checkpoint's
    sidecar config, and runs recorded before this subsystem existed have no
    ``serve`` section (same contract as ``resilience.resolve``).
    """
    try:
        group = cfg.get("serve") if hasattr(cfg, "get") else None
    except Exception:
        group = None
    merged: Dict[str, Any] = {}
    for section, defaults in _DEFAULTS.items():
        got = None
        if group is not None:
            got = group.get(section) if hasattr(group, "get") else getattr(group, section, None)
        merged[section] = dict(defaults)
        if got is not None:
            for k in defaults:
                v = got.get(k, defaults[k]) if hasattr(got, "get") else getattr(got, k, defaults[k])
                merged[section][k] = v
    return _View(merged)


class ServeError(RuntimeError):
    """Unrecoverable serving misconfiguration (unsupported algorithm, invalid
    bucket ladder, no loadable checkpoint)."""
