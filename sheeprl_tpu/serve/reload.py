"""Certified hot-reload: watch -> load -> warm -> swap -> canary -> (rollback).

The watcher polls :func:`~sheeprl_tpu.utils.checkpoint.latest_certified` over
the run's checkpoint dir. Only CERTIFIED artifacts are ever considered — a
half-written checkpoint, a sidecar whose checkpoint was deleted, or a
same-size overwrite all fail certification and are invisible here, so the
trainer can keep writing into the dir the server watches.

A successful scan builds the next :class:`Generation` entirely OFF the serving
path (load, device placement, AOT warm), swaps the store reference atomically
(in-flight batches hold their own generation and finish on the old weights),
then runs a post-swap canary through the real serving path. A canary failure
swaps the PREVIOUS generation back (``Serve/reload_rollbacks``); a failure
anywhere earlier leaves the current generation untouched
(``Serve/reload_failures``). ``reload.degraded_after`` consecutive failures
latch the degraded gauge: the server keeps answering from the last-known-good
generation and says so in its health surface. The latch is NOT forever — the
next successful reload clears it and emits a ``serve_reload_recovered`` event
row, so the incident that raised the gauge has an explicit close.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from sheeprl_tpu.core import failpoints
from sheeprl_tpu.core.health import append_event
from sheeprl_tpu.serve.engine import PolicyEngine, GenerationStore
from sheeprl_tpu.serve.stats import ServeStats
from sheeprl_tpu.telemetry import trace
from sheeprl_tpu.utils.checkpoint import artifact_bootable, certified_info, latest_certified, load_state

_logger = logging.getLogger(__name__)


class HotReloader(threading.Thread):
    def __init__(
        self,
        engine: PolicyEngine,
        store: GenerationStore,
        ckpt_dir: str,
        stats: ServeStats,
        *,
        poll_s: float = 1.0,
        canary: bool = True,
        degraded_after: int = 3,
    ):
        super().__init__(name="sheeprl-serve-reload", daemon=True)
        self.engine = engine
        self.store = store
        self.ckpt_dir = ckpt_dir
        self.stats = stats
        self.poll_s = float(poll_s)
        self.canary = bool(canary)
        self.degraded_after = int(degraded_after)
        self.consecutive_failures = 0
        # reload incidents (swap, canary rollback) land in the run's shared
        # operational event stream — the same health/events.jsonl the train
        # sentinel writes, trace-id-stamped by append_event, so a canary
        # failure is joinable with the serve trace that tripped it
        self.events_dir = os.path.join(os.path.dirname(os.path.abspath(ckpt_dir)), "health")
        self._stop = threading.Event()
        # identity of the artifact the CURRENT generation came from: path alone
        # is not enough (the trainer may legitimately re-certify new bytes
        # under the same filename), so track (path, crc) together
        boot = store.get()
        self._loaded: tuple = (boot.source if boot else None, boot.crc32 if boot else None)

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.scan_once()
            except Exception:  # scan_once accounts its own failures; belt and braces
                _logger.exception("[serve] reload scan crashed")

    def scan_once(self) -> Optional[int]:
        """One watch tick. Returns the new generation id on swap, else None."""
        path = latest_certified(self.ckpt_dir)
        if path is None:
            return None
        # certified_info re-validates size+CRC: a sidecar appearing mid-scan
        # for a checkpoint that has since been deleted or overwritten reads as
        # not-certified and is skipped, not crashed on
        info = certified_info(path)
        if info is None:
            return None
        if (path, info.get("crc32")) == self._loaded:
            return None
        # Artifact-compat gate (sidecar format/topology stamp + shard-file
        # presence): an artifact this replica can't boot — unsupported shard
        # format version, sharded dir missing shard files — is rejected as a
        # recorded reload failure BEFORE any load work, never a replica crash.
        ok, why = artifact_bootable(path, info)
        if not ok:
            self._record_failure(path, RuntimeError(f"artifact not bootable: {why}"))
            return None
        cur = self.store.get()
        with trace.span("serve/reload", plane="serve", path=path) as sp:
            try:
                state = load_state(path, fallback_to_older=False)
                gen = self.engine.make_generation(state, (cur.gen_id if cur else 0) + 1, path, info)
                self.engine.warm_sync()  # no-op unless a bucket lost its executable
            except Exception as e:
                self._record_failure(path, e)
                return None
            prev = self.store.swap(gen)
            if self.canary:
                try:
                    # Drill site: `reload.canary:raise` exercises the full
                    # swap -> canary-fail -> rollback path on a healthy artifact.
                    failpoints.failpoint("reload.canary", path=path, gen_id=gen.gen_id)
                    self.engine.canary(gen.params)
                except Exception as e:
                    # post-swap canary failed: put the last-known-good generation
                    # back before anything beyond the canary touched the new one
                    self.store.swap(prev)
                    self.stats.inc("reload_rollbacks")
                    sp.set(rollback=True)
                    append_event(
                        self.events_dir,
                        "serve_reload_rollback",
                        int(gen.step or 0),
                        path=path,
                        gen_id=gen.gen_id,
                        error=f"{type(e).__name__}: {e}",
                    )
                    self._record_failure(path, e)
                    return None
            self._loaded = (path, info.get("crc32"))
            # a success after the degraded gauge latched is an INCIDENT
            # RECOVERY, not just another reload: clear the latch and say so in
            # the event stream (operators page on the latch — the recovery row
            # is what closes the incident)
            was_degraded = self.consecutive_failures >= self.degraded_after
            failures_cleared = self.consecutive_failures
            self.consecutive_failures = 0
            self.stats.inc("reload_generations")
            self.stats.set_gauge("generation", gen.gen_id)
            self.stats.set_gauge("degraded", 0)
            sp.set(gen_id=gen.gen_id)
        if was_degraded:
            append_event(
                self.events_dir,
                "serve_reload_recovered",
                int(gen.step or 0),
                path=path,
                gen_id=gen.gen_id,
                failures_cleared=failures_cleared,
            )
        append_event(
            self.events_dir, "serve_reload", int(gen.step or 0), path=path, gen_id=gen.gen_id
        )
        _logger.info(
            "[serve] hot-reloaded generation %d from %s (step=%s)", gen.gen_id, path, gen.step
        )
        return gen.gen_id

    def _record_failure(self, path: str, err: BaseException) -> None:
        self.stats.inc("reload_failures")
        self.consecutive_failures += 1
        if self.consecutive_failures >= self.degraded_after:
            # the swap path is wedged: keep serving last-known-good, say so
            self.stats.set_gauge("degraded", 1)
        _logger.warning(
            "[serve] reload of %s failed (%s: %s); serving generation %s unchanged",
            path,
            type(err).__name__,
            err,
            self.store.gen_id,
        )
