"""Policy engine: checkpoint -> warmed, generation-pinned batched inference.

Rebuilds the agent exactly the way evaluation does (sidecar config + env
spaces + ``build_agent``), then serves through the player's fused raw-obs act
path: observation normalization, sampling/argmax and the env-facing concat all
run inside ONE AOT-compiled dispatch per batch.

Weight swaps are modelled as immutable :class:`Generation` objects held by a
:class:`GenerationStore`. A batch reads the store ONCE and computes against
that generation's params for its whole lifetime, so a concurrent hot-reload
can never produce a torn read (half-old, half-new weights); swapping is a
single reference assignment under a lock. Because every generation shares the
agent's abstract signature, one AOT executable per bucket serves all of them —
reloading never recompiles, let alone retraces.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.core.runtime import Runtime
from sheeprl_tpu.serve import ServeError, resolve
from sheeprl_tpu.utils.env import make_env

_logger = logging.getLogger(__name__)

# Algorithms sharing the PPO agent/player act surface. Recurrent and
# model-based players carry per-request latent state, which needs a session
# protocol — out of scope for the stateless request/response frontend.
SUPPORTED_ALGOS = ("ppo", "ppo_decoupled", "a2c")


@dataclass(frozen=True)
class Generation:
    """One immutable serving artifact: params + provenance."""

    gen_id: int
    params: Any = field(repr=False)
    source: str
    step: Optional[int] = None
    crc32: Optional[int] = None
    loaded_at: float = 0.0


class GenerationStore:
    """Atomic holder of the CURRENT serving generation.

    ``get`` returns one self-consistent Generation object; ``swap`` replaces
    the reference and returns the previous generation (the reloader's rollback
    target). Readers never block writers and vice versa beyond the reference
    assignment itself.
    """

    def __init__(self, gen: Optional[Generation] = None):
        self._lock = threading.Lock()
        self._gen = gen

    def get(self) -> Optional[Generation]:
        with self._lock:
            return self._gen

    def swap(self, gen: Generation) -> Optional[Generation]:
        with self._lock:
            prev, self._gen = self._gen, gen
            return prev

    @property
    def gen_id(self) -> int:
        g = self.get()
        return 0 if g is None else g.gen_id


def spaces_from_config(cfg: Any) -> Tuple[gym.spaces.Dict, Tuple[int, ...], bool]:
    """Instantiate one throwaway env (exactly like evaluate_ppo) to recover
    ``(obs_space, actions_dim, is_continuous)`` for ``build_agent``."""
    cfg.env.num_envs = 1
    cfg.env.capture_video = False
    env = make_env(cfg, cfg.seed, 0, None, "serve", vector_env_idx=0)()
    try:
        obs_space = env.observation_space
        if not isinstance(obs_space, gym.spaces.Dict):
            raise ServeError(f"expected Dict observation space, got: {obs_space}")
        is_continuous = isinstance(env.action_space, gym.spaces.Box)
        is_multidiscrete = isinstance(env.action_space, gym.spaces.MultiDiscrete)
        actions_dim = tuple(
            env.action_space.shape
            if is_continuous
            else (env.action_space.nvec.tolist() if is_multidiscrete else [env.action_space.n])
        )
    finally:
        env.close()
    return obs_space, actions_dim, is_continuous


def init_agent_state(cfg: Any) -> Dict[str, Any]:
    """Freshly-initialised agent params in checkpoint-state form
    (``{"agent": host_params}``) — the fixture path for smoke tests and the
    serve benchmark, which need a servable checkpoint without training."""
    obs_space, actions_dim, is_continuous = spaces_from_config(cfg)
    runtime = Runtime(
        accelerator=cfg.fabric.get("accelerator", "auto"), devices=1, precision=cfg.fabric.precision
    )
    from sheeprl_tpu.algos.ppo.agent import build_agent

    _, params, _ = build_agent(runtime, actions_dim, is_continuous, cfg, obs_space, None)
    return {"agent": jax.device_get(params)}


class PolicyEngine:
    def __init__(
        self,
        cfg: Any,
        state: Dict[str, Any],
        *,
        source: str = "boot",
        boot_info: Optional[Dict[str, Any]] = None,
    ):
        if cfg.algo.name not in SUPPORTED_ALGOS:
            raise ServeError(
                f"serving is implemented for {SUPPORTED_ALGOS}, not '{cfg.algo.name}' "
                "(recurrent/model-based players need per-session state)"
            )
        if "agent" not in state:
            raise ServeError("checkpoint state carries no 'agent' params")
        self.cfg = cfg
        self.sv = resolve(cfg)
        max_batch = int(self.sv.batch.max_size)
        if jax_compile.pow2_bucket(max_batch) != max_batch:
            raise ServeError(f"serve.batch.max_size must be a power of two, got {max_batch}")
        self.max_batch = max_batch
        self.buckets: List[int] = []
        b = 1
        while b <= max_batch:
            self.buckets.append(b)
            b *= 2
        self.greedy = bool(self.sv.policy.greedy)

        self.runtime = Runtime(
            accelerator=cfg.fabric.get("accelerator", "auto"), devices=1, precision=cfg.fabric.precision
        )
        obs_space, actions_dim, is_continuous = spaces_from_config(cfg)

        from sheeprl_tpu.algos.ppo.agent import build_agent

        _, _, self.player = build_agent(
            self.runtime, actions_dim, is_continuous, cfg, obs_space, state["agent"]
        )
        self.actions_dim = actions_dim
        self.is_continuous = is_continuous
        self.obs_shapes: Dict[str, Tuple[int, ...]] = {
            k: tuple(obs_space[k].shape)
            for k in list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)
        }
        self._gfn = self.player._greedy_raw if self.greedy else self.player._act_raw
        self._key = jax.random.PRNGKey(cfg.seed)
        self._key_lock = threading.Lock()
        # boot_info is the certified sidecar of the boot checkpoint (when there
        # is one): stamping its crc here lets the hot-reloader recognise the
        # already-serving artifact instead of re-loading it as generation 2
        boot_info = boot_info or {}
        self.boot_generation = Generation(
            gen_id=1,
            params=self.player.params,
            source=source,
            step=boot_info.get("policy_step", boot_info.get("step")),
            crc32=boot_info.get("crc32"),
            loaded_at=time.time(),
        )

    # ----- generations ---------------------------------------------------------------
    def make_generation(
        self, state: Dict[str, Any], gen_id: int, source: str, info: Optional[Dict[str, Any]] = None
    ) -> Generation:
        """Place a checkpoint's agent params on the player device as a fresh
        immutable generation (same placement as build_agent's player copy)."""
        if "agent" not in state:
            raise ServeError(f"checkpoint state from '{source}' carries no 'agent' params")
        params = jax.tree_util.tree_map(jnp.asarray, state["agent"])
        params = self.runtime.to_player(params)
        info = info or {}
        return Generation(
            gen_id=gen_id,
            params=params,
            source=source,
            step=info.get("policy_step", info.get("step")),
            crc32=info.get("crc32"),
            loaded_at=time.time(),
        )

    # ----- obs handling --------------------------------------------------------------
    def coerce_obs(self, obs: Any) -> Dict[str, np.ndarray]:
        """Validate + convert one request's obs payload to the canonical f32
        layout. Raising HERE (pre-admission) keeps a malformed request from
        poisoning the whole batch it would have ridden in."""
        if not isinstance(obs, dict):
            raise ValueError(f"obs must be a dict of per-key arrays, got {type(obs).__name__}")
        out: Dict[str, np.ndarray] = {}
        for k, shape in self.obs_shapes.items():
            if k not in obs:
                raise ValueError(f"obs is missing key '{k}'")
            arr = np.asarray(obs[k], dtype=np.float32)
            if arr.shape != shape:
                try:
                    arr = arr.reshape(shape)
                except ValueError:
                    raise ValueError(f"obs['{k}'] has shape {arr.shape}, expected {shape}") from None
            out[k] = arr
        return out

    def _batch_specs(self, bucket: int) -> Tuple[Any, Dict[str, Any], Any]:
        obs_spec = {
            k: jax.ShapeDtypeStruct((bucket, *shape), np.float32) for k, shape in self.obs_shapes.items()
        }
        params_spec = jax_compile.specs_of(self.player.params)
        key_spec = jax_compile.spec_like(jax.random.PRNGKey(0))
        return params_spec, obs_spec, key_spec

    # ----- warmup / readiness --------------------------------------------------------
    def register_warmup(self, warmup: jax_compile.AOTWarmup) -> None:
        """Queue one AOT compile per bucket (signature is generation-invariant,
        so warming once at boot covers every future hot-reload)."""
        for b in self.buckets:
            warmup.add(self._gfn, *self._batch_specs(b))

    def warm_boot(self, wait_s: float = 600.0) -> None:
        """Foreground bucket warmup + steady-state watermark: after this, any
        retrace is a bug the guard reports (``Compile/retraces``)."""
        warmup = jax_compile.AOTWarmup(enabled=True)
        self.register_warmup(warmup)
        warmup.start()
        if not warmup.wait(wait_s):
            raise ServeError(f"AOT warmup did not finish within {wait_s}s")
        if warmup.errors:
            name, err = warmup.errors[0]
            raise ServeError(f"AOT warmup of '{name}' failed: {type(err).__name__}: {err}")
        jax_compile.mark_steady()

    def warm_sync(self) -> None:
        """Compile any bucket not yet AOT-ready (reload path; normally a no-op
        because generations share one abstract signature)."""
        for b in self.buckets:
            specs = self._batch_specs(b)
            if not self._gfn.aot_ready(*specs):
                self._gfn.aot_compile(*specs)

    def ready(self) -> bool:
        return all(self._gfn.aot_ready(*self._batch_specs(b)) for b in self.buckets)

    def program_footprint(self) -> Dict[str, Any]:
        """Compiled-program ledger summary for THIS engine's act programs: how
        many bucket executables exist and the worst-case peak-HBM / compile
        cost among them (the ``stats`` op surfaces it per server)."""
        from sheeprl_tpu.telemetry import programs as tel_programs

        # every bucket compiles under the same GuardedFn name, so dedupe by
        # HLO fingerprint (one entry per bucket executable) from the run
        # ledger when one is configured; the in-memory newest-per-name
        # snapshot is the fallback
        path = tel_programs.ledger_path()
        try:
            source = tel_programs.read_ledger(path) if path else tel_programs.snapshot()
        except OSError:
            source = tel_programs.snapshot()
        by_fp: Dict[Any, Dict[str, Any]] = {}
        for r in source:
            if r.get("name") == self._gfn.name:
                by_fp[r.get("fingerprint")] = r
        rows = list(by_fp.values())
        peaks = [r["memory"]["peak_bytes"] for r in rows if r.get("memory")]
        secs = [r["compile_seconds"] for r in rows if r.get("compile_seconds") is not None]
        return {
            "programs": len(rows),
            "peak_hbm_bytes_max": max(peaks) if peaks else None,
            "compile_seconds_total": sum(secs) if secs else 0.0,
        }

    # ----- inference -----------------------------------------------------------------
    def act(self, params: Any, obs_rows: List[Dict[str, np.ndarray]]) -> np.ndarray:
        """Batched act: stack rows, pad to the pow-2 bucket, one fused dispatch,
        slice the padding back off. Returns ``[n, act_dim]`` host actions."""
        n = len(obs_rows)
        if n == 0:
            return np.zeros((0, len(self.actions_dim)), dtype=np.float32)
        if n > self.max_batch:
            raise ValueError(f"batch of {n} exceeds serve.batch.max_size={self.max_batch}")
        bucket = jax_compile.pow2_bucket(n)
        batch = {k: np.zeros((bucket, *shape), dtype=np.float32) for k, shape in self.obs_shapes.items()}
        for i, row in enumerate(obs_rows):
            for k in self.obs_shapes:
                batch[k][i] = row[k]
        with self._key_lock:
            key, self._key = jax.random.split(self._key)
        if self.greedy:
            env_actions, _ = self._gfn(params, batch, key)
        else:
            _, env_actions, _, _, _ = self._gfn(params, batch, key)
        return np.asarray(env_actions)[:n]

    def canary(self, params: Any) -> Dict[str, Any]:
        """One zero-obs batch through the REAL serving path: catches params
        whose executable dispatch wedges or whose outputs are non-finite
        before (or just after) they start answering traffic."""
        zeros = [{k: np.zeros(shape, dtype=np.float32) for k, shape in self.obs_shapes.items()}]
        actions = self.act(params, zeros)
        if actions.shape[0] != 1:
            raise ServeError(f"canary returned {actions.shape[0]} rows for 1 request")
        if not np.all(np.isfinite(actions)):
            raise ServeError(f"canary produced non-finite actions: {actions.tolist()}")
        return {"action_dim": int(actions.shape[-1])}
