"""`python -m sheeprl_tpu.serve checkpoint_path=...` — same surface as the
root sheeprl_serve.py shim."""

from sheeprl_tpu.cli import serve

if __name__ == "__main__":
    serve()
