"""Replica-fleet supervisor: N serve subprocesses behind one failover router.

Composes the subsystems earlier rounds built into the fleet layer ROADMAP
item 4 names:

- **Supervision** (`orchestrate/`-style): each replica is a slot. Spawn goes
  through a ready-file handshake (the replica's own ``serve.server.ready_file``
  contract), exits are classified with the orchestrator's precedence — kill
  intent (supervisor-initiated drain/deploy) > preemption flag file (the
  replica's :class:`~sheeprl_tpu.core.resilience.PreemptionGuard` wrote it on
  SIGTERM) > returncode — and unexpected exits are respawned under a budgeted
  :func:`~sheeprl_tpu.core.resilience.jittered_backoff` schedule.
- **Liveness** (control-plane primitives): the supervisor runs an in-process
  :class:`~sheeprl_tpu.parallel.control.KVServer` and one
  :class:`~sheeprl_tpu.parallel.control.ControlPlane` per slot. A successful
  health probe of a replica beats that slot's heartbeat key;
  ``peer_liveness`` then gives staleness-based liveness, so a wedged replica
  (process alive, frontend dead) is killed and respawned, not just mourned.
- **Epoch fencing**: every (re)spawn bumps the slot's fenced session epoch via
  ``ControlPlane.begin_session`` — the same primitive that fences zombie
  trainers — and the epoch is stamped into the membership file the router
  consumes. A stale incarnation (or a forged membership write) carries a
  lower epoch than the slot's high-water mark and the router refuses to route
  to it: a fenced zombie replica never answers anything.
- **Rolling certified deploys**: the supervisor (not the replicas — they run
  with hot-reload disabled) watches ``latest_certified`` over the checkpoint
  dir. A new certified artifact is deployed one replica at a time: drain the
  slot out of the membership, SIGTERM it (zero-loss drain), respawn on the new
  checkpoint, wait ready. The FIRST replica is the canary — the
  ``fleet.deploy`` failpoint plus a post-boot health verification gate the
  rest of the fleet, and a canary failure rolls the slot back to the previous
  artifact fleet-wide (``Fleet/deploy_rollbacks``).

``python -m sheeprl_tpu.serve.fleet checkpoint_path=<ckpt> ...`` runs the
supervisor + router until SIGTERM, with
``PreemptionGuard(forward_to_children=True)`` fanning the signal out so every
replica drains itself to rc 0 — the fleet-wide version of the single-server
shutdown contract: every request that ever reached the fleet gets exactly one
answer.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from sheeprl_tpu.core import failpoints
from sheeprl_tpu.core.health import append_event
from sheeprl_tpu.core.resilience import (
    FLAG_FILE_ENV_VAR,
    PreemptionGuard,
    jittered_backoff,
)
from sheeprl_tpu.parallel.control import ControlPlane, KVServer, SocketKV
from sheeprl_tpu.serve.router import FailoverRouter
from sheeprl_tpu.serve.stats import FleetStats
from sheeprl_tpu.telemetry import registry as tel_registry
from sheeprl_tpu.telemetry import trace
from sheeprl_tpu.utils.checkpoint import artifact_bootable, certified_info, latest_certified

_logger = logging.getLogger(__name__)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# tests point replicas at a stub entry the same way orchestrate tests do
ENTRY_ENV_VAR = "SHEEPRL_TPU_SERVE_ENTRY"


def _entry_point() -> str:
    return os.environ.get(ENTRY_ENV_VAR) or os.path.join(REPO_ROOT, "sheeprl_serve.py")


def _rpc(addr: Tuple[str, int], payload: Dict[str, Any], timeout: float = 5.0) -> Dict[str, Any]:
    with socket.create_connection(addr, timeout=timeout) as sock:
        f = sock.makefile("rwb")
        f.write((json.dumps(payload) + "\n").encode())
        f.flush()
        line = f.readline()
    if not line:
        raise ConnectionError("replica closed connection")
    return json.loads(line)


class ReplicaHandle:
    """One slot's current incarnation (process, epoch, handshake paths)."""

    def __init__(self, slot: int, epoch: int, ckpt: str, step: Optional[int], workdir: str):
        self.slot = slot
        self.epoch = epoch
        self.ckpt = ckpt
        self.step = step
        self.dir = os.path.join(workdir, f"replica{slot}")
        tag = f"e{epoch}"
        self.ready_file = os.path.join(self.dir, f"ready_{tag}.json")
        self.flag_file = os.path.join(self.dir, f"preempt_{tag}.flag")
        self.stats_file = os.path.join(self.dir, f"stats_{tag}.json")
        self.log_file = os.path.join(self.dir, "replica.log")
        self.proc: Optional[subprocess.Popen] = None
        self.log_f: Any = None
        self.addr: Optional[Tuple[str, int]] = None
        self.pid: Optional[int] = None
        self.restarts = 0
        self.heartbeats = 0
        self.spawned_at = 0.0


class FleetSupervisor:
    def __init__(
        self,
        checkpoint_path: str,
        workdir: str,
        *,
        replicas: int = 3,
        serve_overrides: Tuple[str, ...] = (),
        replica_env: Optional[Dict[str, str]] = None,
        heartbeat_s: float = 0.25,
        heartbeat_timeout_s: float = 10.0,
        restart_backoff_s: float = 0.25,
        restart_backoff_max_s: float = 2.0,
        max_restarts: int = 8,
        drain_timeout_s: float = 45.0,
        ready_timeout_s: float = 240.0,
        deploy_poll_s: float = 0.5,
        deploy_retry_s: float = 1.0,
        router_opts: Optional[Dict[str, Any]] = None,
    ):
        self.checkpoint_path = os.path.abspath(checkpoint_path)
        self.ckpt_dir = os.path.dirname(self.checkpoint_path)
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.replicas = int(replicas)
        self.serve_overrides = tuple(serve_overrides)
        self.replica_env = dict(replica_env or {})
        self.heartbeat_s = float(heartbeat_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.restart_backoff_s = float(restart_backoff_s)
        self.restart_backoff_max_s = float(restart_backoff_max_s)
        self.max_restarts = int(max_restarts)
        self.drain_timeout_s = float(drain_timeout_s)
        self.ready_timeout_s = float(ready_timeout_s)
        self.deploy_poll_s = float(deploy_poll_s)
        self.deploy_retry_s = float(deploy_retry_s)

        self.stats = FleetStats()
        self.events_dir = os.path.join(self.workdir, "health")
        self.membership_file = os.path.join(self.workdir, "membership.json")
        # liveness + epoch fencing ride the existing control plane: one KV
        # server in-process, one plane per slot (rank == slot)
        self._kv = KVServer()
        self._kv.start()
        self._planes = [
            ControlPlane(
                SocketKV(self._kv.address),
                rank=slot,
                world=self.replicas,
                scope="fleet",
                timeout_ms=10_000,
            )
            for slot in range(self.replicas)
        ]
        self.router = FailoverRouter(
            self.membership_file, self.stats, **dict(router_opts or {})
        )
        self._handles: Dict[int, ReplicaHandle] = {}
        self._intents: Dict[int, str] = {}
        self._respawn_at: Dict[int, float] = {}
        self._dead_slots: set = set()
        self._last_membership: Optional[str] = None
        self._last_probe = 0.0
        self._last_deploy_check = 0.0
        self._deploy_retry_at = 0.0
        self._replica_reports: List[Dict[str, Any]] = []
        self.guard: Optional[PreemptionGuard] = None
        info = certified_info(self.checkpoint_path) or {}
        self._current_ckpt = self.checkpoint_path
        self._current_ident: Tuple[Any, Any] = (self.checkpoint_path, info.get("crc32"))
        self._current_step = info.get("policy_step")
        tel_registry.register("fleet", self.stats.snapshot)

    # ----- spawn / handshake ----------------------------------------------------
    def _spawn(self, slot: int, ckpt: str, step: Optional[int]) -> ReplicaHandle:
        # Drill site: `fleet.spawn:raise:...:hit=N` fails a replica launch —
        # the budgeted-backoff respawn path must absorb it.
        failpoints.failpoint("fleet.spawn", slot=slot)
        # the fenced session epoch IS the replica generation stamp: a zombie of
        # the previous incarnation keeps the old epoch and the router fences it
        epoch = self._planes[slot].begin_session(role=f"slot{slot}")
        handle = ReplicaHandle(slot, epoch, ckpt, step, self.workdir)
        os.makedirs(handle.dir, exist_ok=True)
        for path in (handle.ready_file, handle.flag_file):
            try:
                os.remove(path)
            except OSError:
                pass
        cmd = [
            sys.executable,
            _entry_point(),
            f"checkpoint_path={ckpt}",
            f"serve.server.ready_file={handle.ready_file}",
            f"stats_file={handle.stats_file}",
            # the supervisor owns weight changes (rolling deploys); a replica
            # hot-reloading on its own would race the deploy's epoch stamps
            "serve.reload.enabled=false",
            *self.serve_overrides,
        ]
        env = dict(
            os.environ,
            JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
            **{FLAG_FILE_ENV_VAR: handle.flag_file},
        )
        # the supervisor's own drill failpoints must not leak into replicas;
        # per-replica injection opts in through replica_env
        env.pop("SHEEPRL_TPU_FAILPOINTS", None)
        env.update(self.replica_env)
        handle.log_f = open(handle.log_file, "ab")
        handle.proc = subprocess.Popen(
            cmd, cwd=handle.dir, env=env, stdout=handle.log_f, stderr=subprocess.STDOUT
        )
        handle.pid = handle.proc.pid
        handle.spawned_at = time.monotonic()
        if self.guard is not None:
            self.guard.register_child(handle.pid)
        prev = self._handles.get(slot)
        if prev is not None:
            handle.restarts = prev.restarts
        self._handles[slot] = handle
        trace.instant("fleet/spawn", slot=slot, epoch=epoch, pid=handle.pid)
        append_event(self.events_dir, "fleet_replica_spawn", int(step or 0), slot=slot, epoch=epoch, pid=handle.pid)
        _logger.info("[fleet] spawn slot=%d epoch=%d pid=%d ckpt=%s", slot, epoch, handle.pid, ckpt)
        return handle

    def _wait_ready(self, slots: List[int], timeout: Optional[float] = None) -> None:
        """Block until every slot's replica wrote its ready file (host/port),
        then add them to the membership. A replica dying pre-ready raises."""
        budget = timeout if timeout is not None else self.ready_timeout_s
        deadline = time.monotonic() + budget
        pending = set(slots)
        while pending:
            if time.monotonic() > deadline:
                raise TimeoutError(f"replicas {sorted(pending)} not ready within {budget}s")
            for slot in list(pending):
                h = self._handles[slot]
                if h.proc is not None and h.proc.poll() is not None:
                    tail = ""
                    try:
                        with open(h.log_file) as f:
                            tail = f.read()[-2000:]
                    except OSError:
                        pass
                    raise RuntimeError(
                        f"replica slot={slot} exited rc={h.proc.returncode} before ready; log tail:\n{tail}"
                    )
                if os.path.isfile(h.ready_file):
                    try:
                        with open(h.ready_file) as f:
                            info = json.load(f)
                    except ValueError:
                        continue  # mid-replace; retry
                    h.addr = (info["host"], int(info["port"]))
                    pending.discard(slot)
            time.sleep(0.05)
        self._write_membership()
        self.stats.set_gauge("replicas_live", len(self._live_slots()))

    def _live_slots(self) -> List[int]:
        return sorted(
            s
            for s, h in self._handles.items()
            if h.proc is not None and h.proc.poll() is None and h.addr is not None
        )

    # ----- membership -----------------------------------------------------------
    def _write_membership(self) -> None:
        members = []
        for slot in self._live_slots():
            h = self._handles[slot]
            members.append(
                {
                    "slot": slot,
                    "epoch": h.epoch,
                    "host": h.addr[0],
                    "port": h.addr[1],
                    "pid": h.pid,
                    "ckpt": h.ckpt,
                    "step": h.step,
                }
            )
        doc = json.dumps({"members": members}, sort_keys=True)
        # write ONLY on change: the membership file is the router's (and the
        # chaos drill's) observation surface, and an unconditional rewrite
        # every tick would race the drill's forged-zombie-write window
        if doc == self._last_membership:
            return
        tmp = f"{self.membership_file}.tmp"
        with open(tmp, "w") as f:
            f.write(doc)
        os.replace(tmp, self.membership_file)
        self._last_membership = doc

    def _remove_member(self, slot: int) -> None:
        h = self._handles.get(slot)
        if h is not None:
            h.addr = None
        self._write_membership()
        self.stats.set_gauge("replicas_live", len(self._live_slots()))

    # ----- exit classification ---------------------------------------------------
    def _reap(self, handle: ReplicaHandle, rc: int) -> Dict[str, Any]:
        if self.guard is not None and handle.pid is not None:
            self.guard.unregister_child(handle.pid)
        if handle.log_f is not None:
            try:
                handle.log_f.close()
            except OSError:
                pass
            handle.log_f = None
        report = {
            "slot": handle.slot,
            "epoch": handle.epoch,
            "rc": rc,
            "stats_file": handle.stats_file,
        }
        self._replica_reports.append(report)
        handle.proc = None
        return report

    def _classify_exit(self, handle: ReplicaHandle, rc: int, now: float) -> None:
        slot = handle.slot
        # precedence mirrors orchestrate: supervisor intent > preemption flag
        # (the replica's guard wrote it when an EXTERNAL signal landed) > rc
        intent = self._intents.pop(slot, None)
        if intent is None and self.guard is not None and self.guard.should_stop:
            # the guard already forwarded our own shutdown signal to this
            # replica; its exit is the drain we asked for, not a failure
            intent = "shutdown"
        flagged = os.path.exists(handle.flag_file)
        self._remove_member(slot)
        self._reap(handle, rc)
        if intent in ("deploy", "shutdown"):
            cause = intent  # expected: the supervisor asked for this exit
        elif intent == "liveness":
            cause = "liveness_kill"
            self.stats.inc("replica_kills")
        elif flagged:
            cause = "preempted"
            self.stats.inc("replica_preemptions")
        else:
            cause = "failed"
            self.stats.inc("replica_failures")
        trace.instant("fleet/exit", slot=slot, rc=rc, cause=cause)
        append_event(self.events_dir, "fleet_replica_exit", 0, slot=slot, rc=rc, cause=cause, epoch=handle.epoch)
        _logger.info("[fleet] exit slot=%d rc=%s cause=%s", slot, rc, cause)
        if cause in ("deploy", "shutdown"):
            return
        handle.restarts += 1
        if handle.restarts > self.max_restarts:
            self._dead_slots.add(slot)
            append_event(self.events_dir, "fleet_slot_abandoned", 0, slot=slot, restarts=handle.restarts)
            _logger.warning("[fleet] slot %d exhausted its restart budget (%d)", slot, self.max_restarts)
            return
        delay = jittered_backoff(self.restart_backoff_s, handle.restarts, self.restart_backoff_max_s)
        self._respawn_at[slot] = now + delay

    def _poll_exits(self, now: float) -> None:
        for slot, h in list(self._handles.items()):
            if h.proc is None:
                continue
            rc = h.proc.poll()
            if rc is not None:
                self._classify_exit(h, rc, now)

    def _respawn_due(self, now: float) -> None:
        for slot, at in list(self._respawn_at.items()):
            if now < at or slot in self._dead_slots:
                continue
            del self._respawn_at[slot]
            h = self._handles[slot]
            try:
                self._spawn(slot, h.ckpt, h.step)
                self._wait_ready([slot])
            except (RuntimeError, TimeoutError, OSError) as e:
                _logger.warning("[fleet] respawn of slot %d failed: %s", slot, e)
                nh = self._handles[slot]
                if nh.proc is not None:  # launched but died/never-readied
                    if nh.proc.poll() is None:
                        nh.proc.kill()
                        try:
                            nh.proc.wait(timeout=10.0)
                        except subprocess.TimeoutExpired:
                            pass
                    self._reap(nh, nh.proc.returncode if nh.proc else -1)
                self.stats.inc("replica_failures")
                nh.restarts += 1
                if nh.restarts > self.max_restarts:
                    self._dead_slots.add(slot)
                    append_event(
                        self.events_dir, "fleet_slot_abandoned", 0, slot=slot, restarts=nh.restarts
                    )
                else:
                    self._respawn_at[slot] = time.monotonic() + jittered_backoff(
                        self.restart_backoff_s, nh.restarts, self.restart_backoff_max_s
                    )
                continue
            self.stats.inc("replica_restarts")
            append_event(self.events_dir, "fleet_replica_restart", 0, slot=slot, epoch=self._handles[slot].epoch)

    # ----- heartbeat liveness -----------------------------------------------------
    def _probe_health(self, now: float) -> None:
        if now - self._last_probe < self.heartbeat_s:
            return
        self._last_probe = now
        for slot in self._live_slots():
            h = self._handles[slot]
            try:
                # Drill site: `fleet.heartbeat:raise` makes the probe miss
                # (liveness decays); `fleet.heartbeat:signal:SIGTERM:hit=N`
                # delivers the fan-out drill's preemption at a DETERMINISTIC
                # supervision tick instead of a wall-clock race.
                failpoints.failpoint("fleet.heartbeat", slot=slot)
                health = _rpc(h.addr, {"op": "health"}, timeout=2.0)
            except (OSError, ValueError, ConnectionError, RuntimeError):
                continue  # missed beat; staleness accumulates
            if health.get("live"):
                h.heartbeats += 1
                self._planes[slot].heartbeat({"pid": h.pid, "slot_epoch": h.epoch})
                self.stats.inc("heartbeats")
        # staleness-based liveness over the control-plane heartbeat keys: a
        # wedged replica (process alive, frontend dead) stops beating and gets
        # killed + respawned
        liveness = self._planes[0].peer_liveness(max_age_s=self.heartbeat_timeout_s)
        for slot in self._live_slots():
            h = self._handles[slot]
            if h.heartbeats == 0:
                continue  # never beat yet: the boot grace window
            beat = liveness.get(slot, {})
            if beat.get("alive"):
                continue
            _logger.warning("[fleet] slot %d heartbeat stale (age=%s): killing", slot, beat.get("age_s"))
            self._intents[slot] = "liveness"
            try:
                h.proc.kill()
            except (ProcessLookupError, OSError):
                pass

    # ----- rolling deploys --------------------------------------------------------
    def _redeploy_slot(self, slot: int, ckpt: str, step: Optional[int]) -> ReplicaHandle:
        """Drain one replica out of the fleet and respawn it on ``ckpt``."""
        if self.guard is not None and self.guard.should_stop:
            # a deploy must never outlive the shutdown signal: a replica
            # spawned now would miss the guard's already-forwarded SIGTERM
            raise RuntimeError("fleet is shutting down; aborting the rollout")
        h = self._handles[slot]
        if h.proc is not None and h.proc.poll() is None:
            self._intents[slot] = "deploy"
            self._remove_member(slot)  # router stops routing here first
            time.sleep(max(self.router.membership_poll_s * 2, 0.1))
            h.proc.send_signal(signal.SIGTERM)
            try:
                rc = h.proc.wait(timeout=self.drain_timeout_s)
            except subprocess.TimeoutExpired:
                h.proc.kill()
                rc = h.proc.wait(timeout=10.0)
            self._intents.pop(slot, None)
            self._reap(h, rc)
            if rc != 0:
                raise RuntimeError(f"slot {slot} did not drain cleanly for deploy (rc={rc})")
        new = self._spawn(slot, ckpt, step)
        self._wait_ready([slot])
        return new

    def _rolling_deploy(self, path: str, info: Dict[str, Any]) -> bool:
        step = info.get("policy_step")
        order = self._live_slots()
        if not order:
            return False
        canary = order[0]
        trace.instant("fleet/deploy_start", path=path, canary=canary)
        append_event(self.events_dir, "fleet_deploy_start", int(step or 0), path=path, canary=canary)
        try:
            handle = self._redeploy_slot(canary, path, step)
            # Drill site: `fleet.deploy:raise:...:hit=1` fails the canary
            # verification on a healthy artifact — the whole fleet must stay
            # on the previous generation and the canary slot roll back.
            failpoints.failpoint("fleet.deploy", path=path, slot=canary)
            health = _rpc(handle.addr, {"op": "health"}, timeout=5.0)
            if not health.get("ready"):
                raise RuntimeError(f"canary replica not ready: {health}")
        except Exception as e:
            self.stats.inc("deploy_rollbacks")
            append_event(
                self.events_dir,
                "fleet_deploy_rollback",
                int(step or 0),
                path=path,
                canary=canary,
                error=f"{type(e).__name__}: {e}",
            )
            _logger.warning("[fleet] deploy canary failed (%s); rolling back to %s", e, self._current_ckpt)
            try:
                self._redeploy_slot(canary, self._current_ckpt, self._current_step)
            except Exception:
                _logger.exception("[fleet] canary rollback failed; slot will respawn via budget")
            self._deploy_retry_at = time.monotonic() + self.deploy_retry_s
            return False
        for slot in order[1:]:
            if slot not in self._live_slots():
                continue  # died mid-deploy; its respawn will use the NEW ckpt
            try:
                self._redeploy_slot(slot, path, step)
            except Exception:
                _logger.exception("[fleet] redeploy of slot %d failed; continuing the rollout", slot)
        self._current_ckpt, self._current_ident, self._current_step = (
            path,
            (path, info.get("crc32")),
            step,
        )
        self.stats.inc("deploys")
        append_event(self.events_dir, "fleet_deploy", int(step or 0), path=path)
        _logger.info("[fleet] rolling deploy of %s complete", path)
        return True

    def _check_deploy(self, now: float) -> None:
        if now - self._last_deploy_check < self.deploy_poll_s or now < self._deploy_retry_at:
            return
        self._last_deploy_check = now
        path = latest_certified(self.ckpt_dir)
        if path is None:
            return
        info = certified_info(path)
        if info is None:
            return
        if (path, info.get("crc32")) == self._current_ident:
            return
        # Artifact-compat gate (sidecar format/topology stamp + shard-file
        # presence): never start a rolling deploy onto an artifact the
        # replicas can't boot — e.g. a sharded dir with a missing shard file
        # or a format version from a newer build. Recorded, retried later.
        ok, why = artifact_bootable(path, info)
        if not ok:
            self.stats.inc("deploy_rejected")
            append_event(
                self.events_dir,
                "fleet_deploy_rejected",
                int(info.get("policy_step") or 0),
                path=path,
                reason=why,
            )
            _logger.warning("[fleet] deploy of %s rejected: %s", path, why)
            self._deploy_retry_at = now + self.deploy_retry_s
            return
        self._rolling_deploy(path, info)

    # ----- lifecycle --------------------------------------------------------------
    def start(self) -> "FleetSupervisor":
        for slot in range(self.replicas):
            self._spawn(slot, self._current_ckpt, self._current_step)
        self._wait_ready(list(range(self.replicas)))
        self.router.start()
        self.stats.set_gauge("ready", 1)
        return self

    def tick(self) -> None:
        if self.guard is not None and self.guard.should_stop:
            return  # shutdown owns the fleet now; no respawns/deploys past this
        now = time.monotonic()
        self._poll_exits(now)
        self._respawn_due(now)
        self._probe_health(now)
        self._check_deploy(now)
        self._write_membership()

    def run_until_stopped(self, stats_file: Optional[str] = None, ready_file: Optional[str] = None) -> bool:
        """Supervise until SIGTERM/SIGINT, then drain the whole fleet.

        The guard forwards the signal to every replica the moment it lands, so
        replicas drain their own admitted work concurrently while the router
        stops admitting — the fleet-wide zero-loss shutdown contract."""
        wake = threading.Event()
        with PreemptionGuard(
            enabled=True, forward_to_children=True, on_signal=lambda _s: wake.set()
        ) as guard:
            self.guard = guard
            self.start()
            if ready_file:
                tmp = f"{ready_file}.tmp"
                with open(tmp, "w") as f:
                    json.dump(
                        {"host": self.router.host, "port": self.router.port, "pid": os.getpid()}, f
                    )
                os.replace(tmp, ready_file)
            while not guard.should_stop:
                self.tick()
                wake.wait(min(self.heartbeat_s, 0.25))
            _logger.info("[fleet] %s: draining the fleet", guard.describe())
            return self.shutdown(stats_file=stats_file)

    def shutdown(self, stats_file: Optional[str] = None) -> bool:
        self.stats.set_gauge("ready", 0)
        self.stats.set_gauge("draining", 1)
        router_drained = self.router.drain(timeout=self.drain_timeout_s)
        replica_rcs: Dict[int, int] = {}
        final_ids: set = set()
        for slot, h in sorted(self._handles.items()):
            if h.proc is None:
                continue
            self._intents[slot] = "shutdown"
            # SIGTERM unconditionally: the guard forwarded the external signal
            # to children alive AT THAT MOMENT, but a replica spawned since
            # (mid-deploy race) never saw it; a second SIGTERM to a replica
            # already draining is a no-op in its own guard
            try:
                h.proc.send_signal(signal.SIGTERM)
            except (ProcessLookupError, OSError):
                pass
            try:
                rc = h.proc.wait(timeout=self.drain_timeout_s)
            except subprocess.TimeoutExpired:
                _logger.warning("[fleet] slot %d drain timed out; killing", slot)
                h.proc.kill()
                rc = h.proc.wait(timeout=10.0)
            replica_rcs[slot] = rc
            self._intents.pop(slot, None)
            final_ids.add(id(self._reap(h, rc)))
        self.router.close()
        try:
            self._kv.stop()
        except Exception:
            pass
        # the drain verdict audits only each slot's FINAL incarnation: earlier
        # incarnations (a chaos-killed replica, pre-deploy generations) were
        # already classified at exit time and have no stats file to offer
        replicas = []
        all_drained = router_drained
        for report in self._replica_reports:
            row = dict(report)
            row["final"] = id(report) in final_ids
            try:
                with open(report["stats_file"]) as f:
                    row["stats"] = json.load(f)
            except (OSError, ValueError):
                row["stats"] = None
            replicas.append(row)
        for row in replicas:
            if row["final"] and (row["rc"] != 0 or not (row.get("stats") or {}).get("drained")):
                all_drained = False
        if stats_file:
            payload: Dict[str, Any] = self.stats.snapshot()
            payload["drained"] = all_drained
            payload["replica_rcs"] = {str(k): v for k, v in replica_rcs.items()}
            payload["replicas"] = replicas
            tmp = f"{stats_file}.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=2)
            os.replace(tmp, stats_file)
        return all_drained


# --------------------------------------------------------------------------- CLI
def main(argv: Optional[List[str]] = None) -> int:
    """``python -m sheeprl_tpu.serve.fleet`` — key=value overrides, same
    grammar as the serve CLI. ``serve.*`` keys pass through to every replica;
    ``fleet.*`` / ``router.*`` keys configure the supervisor and the frontend."""
    import yaml

    args = list(sys.argv[1:] if argv is None else argv)
    kv: Dict[str, Any] = {}
    for ov in args:
        key, _, value = ov.partition("=")
        kv[key.strip()] = yaml.safe_load(value)
    ckpt = kv.pop("checkpoint_path", None)
    if not ckpt:
        print("fleet: checkpoint_path=<certified ckpt> is required", file=sys.stderr)
        return 2
    workdir = kv.pop("workdir", None) or os.path.join(os.getcwd(), "fleet")
    stats_file = kv.pop("stats_file", None)
    ready_file = kv.pop("ready_file", None)

    from sheeprl_tpu.serve import _DEFAULTS

    fleet_cfg = dict(_DEFAULTS["fleet"])
    router_cfg = dict(_DEFAULTS["router"])
    serve_overrides: List[str] = []
    for key, value in kv.items():
        if key.startswith("fleet."):
            name = key[len("fleet."):]
            if name not in fleet_cfg:
                print(f"fleet: unknown knob '{key}'", file=sys.stderr)
                return 2
            fleet_cfg[name] = value
        elif key.startswith("router."):
            name = key[len("router."):]
            if name not in router_cfg:
                print(f"fleet: unknown knob '{key}'", file=sys.stderr)
                return 2
            router_cfg[name] = value
        else:
            serve_overrides.append(f"{key}={value}")

    sup = FleetSupervisor(
        ckpt,
        workdir,
        replicas=int(fleet_cfg["replicas"]),
        serve_overrides=tuple(serve_overrides),
        heartbeat_s=float(fleet_cfg["heartbeat_s"]),
        heartbeat_timeout_s=float(fleet_cfg["heartbeat_timeout_s"]),
        restart_backoff_s=float(fleet_cfg["restart_backoff_s"]),
        restart_backoff_max_s=float(fleet_cfg["restart_backoff_max_s"]),
        max_restarts=int(fleet_cfg["max_restarts"]),
        drain_timeout_s=float(fleet_cfg["drain_timeout_s"]),
        deploy_poll_s=float(fleet_cfg["deploy_poll_s"]),
        deploy_retry_s=float(fleet_cfg["deploy_retry_s"]),
        router_opts={
            "host": str(router_cfg["host"]),
            "port": int(router_cfg["port"]),
            "retry_budget": int(router_cfg["retry_budget"]),
            "retry_backoff_ms": float(router_cfg["retry_backoff_ms"]),
            "membership_poll_s": float(router_cfg["membership_poll_s"]),
            "dial_timeout_s": float(router_cfg["dial_timeout_s"]),
            "default_priority": int(router_cfg["default_priority"]),
            "max_workers": int(router_cfg["max_workers"]),
        },
    )
    drained = sup.run_until_stopped(stats_file=stats_file, ready_file=ready_file)
    return 0 if drained else 1


if __name__ == "__main__":
    sys.exit(main())
