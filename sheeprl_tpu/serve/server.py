"""TCP frontend: JSON-lines protocol, graded degradation, graceful drain.

Protocol (one JSON object per line, either direction; responses carry the
request ``id`` and may arrive out of order on a pipelined connection):

- ``{"id": ..., "obs": {...}, "deadline_ms": 50, "priority": 1}`` ->
  ``{"id": ..., "status": "ok", "action": [...], "gen": 2}`` or a terminal
  backpressure answer: ``status`` in ``rejected`` (with ``retry_after_ms`` or
  ``reason: draining``), ``shed`` (with ``retry_after_ms``),
  ``deadline_expired``, ``error``. ``priority`` (optional, default 1; 0 =
  best-effort) selects the shed class under ``admission: shed_oldest`` —
  priority-0 traffic is shed before priority-1.
- ``{"op": "stats"}`` -> the ``Serve/*`` snapshot (plus compile totals).
- ``{"op": "health"}`` -> ``{"ready", "live", "degraded", "draining", "gen"}``.
- ``{"op": "metrics"}`` -> the whole metrics fabric as a Prometheus
  text-exposition body (``{"status": "ok", "text": ...}``) — scrape it off
  the same socket, no second listener.
- ``{"op": "profile", "action": "start|stop|toggle"}`` -> toggle an
  on-demand ``jax.profiler`` capture window on the live server
  (:mod:`sheeprl_tpu.telemetry.device`).

Shutdown contract (the chaos drill's core assertion): on SIGTERM the server
stops ADMITTING (new requests get ``rejected/draining`` — still a response),
drains everything already admitted, writes a final stats file, and only then
exits. Every request that ever reached the server gets exactly one answer.
"""

from __future__ import annotations

import json
import logging
import os
import socketserver
import threading
from typing import Any, Callable, Dict, Optional

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.core.resilience import PreemptionGuard
from sheeprl_tpu.serve import resolve
from sheeprl_tpu.serve.batcher import MicroBatcher
from sheeprl_tpu.serve.engine import GenerationStore, PolicyEngine
from sheeprl_tpu.serve.reload import HotReloader
from sheeprl_tpu.serve.stats import ServeStats
from sheeprl_tpu.telemetry import device as tel_device
from sheeprl_tpu.telemetry import export as tel_export
from sheeprl_tpu.telemetry import registry as tel_registry
from sheeprl_tpu.telemetry import trace

_logger = logging.getLogger(__name__)


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        server: "PolicyServer" = self.server.policy_server  # type: ignore[attr-defined]
        wlock = threading.Lock()

        def send(obj: Dict[str, Any]) -> None:
            data = (json.dumps(obj) + "\n").encode()
            with wlock:
                try:
                    self.wfile.write(data)
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass  # client went away; its request still resolved in the stats

        while True:
            try:
                line = self.rfile.readline()
            except (ConnectionResetError, OSError):
                return
            if not line:
                return
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except ValueError:
                send({"status": "error", "error": "malformed json"})
                continue
            op = msg.get("op", "infer")
            if op == "stats":
                send(server.stats_payload())
            elif op == "health":
                send(server.health_payload())
            elif op == "metrics":
                send(server.metrics_payload())
            elif op == "profile":
                send(server.profile_payload(msg))
            elif op == "infer":
                server.handle_infer(msg, send)
            else:
                send({"status": "error", "error": f"unknown op '{op}'"})


class _TCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class PolicyServer:
    def __init__(
        self,
        cfg: Any,
        state: Dict[str, Any],
        *,
        source: str = "boot",
        ckpt_dir: Optional[str] = None,
        boot_info: Optional[Dict[str, Any]] = None,
    ):
        self.sv = resolve(cfg)
        self.stats = ServeStats(latency_window=int(self.sv.server.latency_window))
        self.engine = PolicyEngine(cfg, state, source=source, boot_info=boot_info)
        self.store = GenerationStore(self.engine.boot_generation)
        self.stats.set_gauge("generation", self.store.gen_id)
        deadline_ms = float(self.sv.queue.deadline_ms)
        self.batcher = MicroBatcher(
            self._compute,
            max_batch=self.engine.max_batch,
            max_wait_s=float(self.sv.batch.max_wait_ms) / 1000.0,
            max_depth=int(self.sv.queue.max_depth),
            admission=str(self.sv.queue.admission),
            retry_after_ms=float(self.sv.queue.retry_after_ms),
            default_deadline_s=(deadline_ms / 1000.0) if deadline_ms > 0 else None,
            stats=self.stats,
        )
        self.reloader: Optional[HotReloader] = None
        if bool(self.sv.reload.enabled) and ckpt_dir and os.path.isdir(ckpt_dir):
            self.reloader = HotReloader(
                self.engine,
                self.store,
                ckpt_dir,
                self.stats,
                poll_s=float(self.sv.reload.poll_s),
                canary=bool(self.sv.reload.canary),
                degraded_after=int(self.sv.reload.degraded_after),
            )
        self._tcp: Optional[_TCPServer] = None
        self._tcp_thread: Optional[threading.Thread] = None
        self.host = str(self.sv.server.host)
        self.port = int(self.sv.server.port)
        # telemetry artifacts land beside the run's other outputs (the ckpt
        # dir's parent is the run dir when serving a recorded run; cwd-local
        # dirs otherwise)
        run_dir = os.path.dirname(os.path.abspath(ckpt_dir)) if ckpt_dir else os.getcwd()
        self.telemetry_dir = os.path.join(run_dir, "telemetry")
        self.profile_dir = os.path.join(self.telemetry_dir, "profiler")
        # plug this server's counters into the process-wide metrics fabric:
        # the `metrics` op (and any JsonlSink) sees Serve/Compile/Telemetry/
        # Device series in one snapshot
        tel_registry.register_default_providers()
        tel_registry.register("serve", self.stats.snapshot)

    # ----- lifecycle ------------------------------------------------------------------
    def start(self) -> "PolicyServer":
        """Warm every bucket, then open the listener. Ordering matters: the
        first request after 'ready' must dispatch AOT, not trace."""
        self.engine.warm_boot()
        self.batcher.start()
        if self.reloader is not None:
            self.reloader.start()
        self._tcp = _TCPServer((self.host, self.port), _Handler)
        self._tcp.policy_server = self  # type: ignore[attr-defined]
        self.port = self._tcp.server_address[1]
        self._tcp_thread = threading.Thread(
            target=self._tcp.serve_forever, name="sheeprl-serve-tcp", daemon=True
        )
        self._tcp_thread.start()
        self.stats.set_gauge("ready", 1.0 if self.engine.ready() else 0.0)
        self._write_ready_file()
        _logger.info("[serve] listening on %s:%d (gen %d)", self.host, self.port, self.store.gen_id)
        return self

    def _write_ready_file(self) -> None:
        ready_file = self.sv.server.ready_file
        if not ready_file:
            return
        tmp = f"{ready_file}.tmp"
        with open(tmp, "w") as f:
            json.dump({"host": self.host, "port": self.port, "pid": os.getpid()}, f)
        os.replace(tmp, ready_file)

    def serve_until_stopped(self, stats_file: Optional[str] = None, drain_timeout_s: float = 30.0) -> None:
        """Main-thread loop: block until SIGTERM/SIGINT, then drain + exit.
        The guard's ``on_signal`` wakes the wait instantly — a mid-drill kill
        should not cost up to a poll tick of extra in-flight exposure."""
        wake = threading.Event()
        with PreemptionGuard(enabled=True, on_signal=lambda _s: wake.set()) as guard:
            while not guard.should_stop:
                wake.wait(0.5)
            _logger.info("[serve] %s: draining", guard.describe())
            self.shutdown(stats_file=stats_file, drain_timeout_s=drain_timeout_s)

    def shutdown(self, stats_file: Optional[str] = None, drain_timeout_s: float = 30.0) -> bool:
        self.stats.set_gauge("ready", 0.0)
        drained = self.batcher.drain(timeout=drain_timeout_s)
        if not drained:
            _logger.warning("[serve] drain timed out after %.1fs", drain_timeout_s)
        if self.reloader is not None:
            self.reloader.stop()
        if self._tcp is not None:
            self._tcp.shutdown()
            self._tcp.server_close()
        self.batcher.close()
        tel_device.stop_capture()  # never leak an open profiler window across exit
        trace_path = None
        if trace.enabled():
            try:
                trace_path = trace.export(os.path.join(self.telemetry_dir, "trace.json"))
            except OSError:
                _logger.exception("[serve] trace export failed")
        if stats_file:
            payload = self.stats_payload()
            payload["drained"] = drained
            if trace_path:
                payload["trace_path"] = trace_path
                payload["trace_id"] = trace.current_trace_id()
            tmp = f"{stats_file}.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=2)
            os.replace(tmp, stats_file)
        return drained

    # ----- request path ---------------------------------------------------------------
    def _compute(self, requests) -> list:
        # ONE store read pins the whole batch to a single generation: a swap
        # landing mid-batch affects the NEXT batch, never this one (no torn
        # reads across a batch)
        gen = self.store.get()
        actions = self.engine.act(gen.params, [r.obs for r in requests])
        return [
            {"action": actions[i].tolist(), "gen": gen.gen_id, "step": gen.step}
            for i in range(len(requests))
        ]

    def handle_infer(self, msg: Dict[str, Any], send: Callable[[Dict[str, Any]], None]) -> None:
        rid = msg.get("id")
        try:
            obs = self.engine.coerce_obs(msg.get("obs"))
        except ValueError as e:
            self.stats.inc("requests_total")
            self.stats.inc("errors")
            send({"id": rid, "status": "error", "error": str(e)})
            return
        deadline_ms = msg.get("deadline_ms")
        deadline_s = None if deadline_ms is None else float(deadline_ms) / 1000.0
        try:
            priority = max(0, int(msg.get("priority", 1)))
        except (TypeError, ValueError):
            priority = 1  # a malformed class must not cost the request
        fut = self.batcher.submit(obs, deadline_s=deadline_s, rid=rid, priority=priority)
        fut.add_done_callback(lambda f: send(f.result()))

    # ----- observability --------------------------------------------------------------
    def stats_payload(self) -> Dict[str, Any]:
        payload = self.stats.snapshot()
        compile_totals = jax_compile.process_stats()
        payload["Compile/retraces"] = compile_totals["retraces"]
        payload["Compile/aot_compiles"] = compile_totals["aot_compiles"]
        try:
            fp = self.engine.program_footprint()
            payload["Programs/act_executables"] = fp["programs"]
            payload["Programs/act_peak_hbm_bytes_max"] = fp["peak_hbm_bytes_max"]
            payload["Programs/act_compile_seconds_total"] = fp["compile_seconds_total"]
        except Exception:  # the ledger is observability; stats must stay up
            pass
        return payload

    def metrics_payload(self) -> Dict[str, Any]:
        """The whole metrics fabric as Prometheus text (the ``metrics`` op)."""
        try:
            text = tel_export.to_prometheus()
        except Exception as e:  # the fabric must not crash the frontend
            return {"status": "error", "error": f"{type(e).__name__}: {e}"}
        return {
            "status": "ok",
            "content_type": "text/plain; version=0.0.4",
            "trace_id": trace.current_trace_id(),
            "text": text,
        }

    def profile_payload(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """On-demand jax.profiler window (the ``profile`` op): ``action`` in
        start | stop | toggle; ``dir`` overrides the capture directory."""
        action = str(msg.get("action", "toggle"))
        cap_dir = str(msg.get("dir") or self.profile_dir)
        try:
            if action == "start":
                state = "started" if tel_device.start_capture(cap_dir) else "busy"
            elif action == "stop":
                state = "stopped" if tel_device.stop_capture() else "idle"
            elif action == "toggle":
                state = tel_device.toggle_capture(cap_dir)
            else:
                return {"status": "error", "error": f"unknown profile action '{action}'"}
        except Exception as e:
            return {"status": "error", "error": f"{type(e).__name__}: {e}"}
        return {"status": "ok", "profile": state, "dir": cap_dir}

    def health_payload(self) -> Dict[str, Any]:
        snap = self.stats.snapshot()
        live = self.batcher._thread is not None and self.batcher._thread.is_alive()
        return {
            "ready": bool(snap["Serve/ready"]) and live,
            "live": live,
            "degraded": bool(snap["Serve/degraded"]),
            "draining": bool(snap["Serve/draining"]),
            "gen": self.store.gen_id,
        }
