"""Failover router: one JSON-lines frontend over N serve replicas.

Speaks the same protocol outward as a single :mod:`sheeprl_tpu.serve.server`
replica (``infer`` with optional ``priority``/``deadline_ms``, plus ``stats``
and ``health`` ops), so a client cannot tell a fleet from one server — except
that replicas dying under it stop mattering.

Membership is FILE-driven and epoch-fenced: the fleet supervisor publishes
``{"members": [{"slot", "epoch", "host", "port", ...}]}`` (atomic replace) and
a watcher thread folds it in. For every slot the router remembers the highest
epoch it has EVER seen; an entry carrying a lower epoch is a zombie write — a
stale incarnation (or a forged file) trying to re-join after the supervisor
fenced it — and is dropped with ``Fleet/fenced_writes`` instead of routed to.
A fenced zombie replica therefore never sees a single request, which is what
makes the supervisor's epoch stamp an actual guarantee about stale weights.

Request path: pick the healthy member with the fewest outstanding requests,
relay over a per-request connection, and on a dial or mid-flight transport
failure retry on a DIFFERENT replica with jittered backoff — bounded by
``retry_budget`` and by the request's own deadline, so the router never turns
a dead replica into an unbounded client stall. Exactly one terminal response
per request, end to end: transport failures that exhaust the budget resolve to
``status: error``; a deadline that expires between retries resolves to
``deadline_expired``; backpressure answers from the replica (``shed`` /
``rejected``, both carrying ``retry_after_ms``) pass through verbatim.

Every terminal bumps exactly one of the ``Fleet/*`` terminal counters, so
``requests_total == ok + shed + rejected + deadline_missed + errors`` holds at
the router exactly like it does at each replica — the fleet drill audits both.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import socketserver
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

from sheeprl_tpu.core import failpoints
from sheeprl_tpu.core.resilience import jittered_backoff
from sheeprl_tpu.serve.stats import FleetStats
from sheeprl_tpu.telemetry import trace

_logger = logging.getLogger(__name__)

# terminal status -> Fleet/* counter (same mapping as the replica batcher)
_STATUS_COUNTER = {
    "ok": "ok",
    "shed": "shed",
    "rejected": "rejected",
    "deadline_expired": "deadline_missed",
    "error": "errors",
}


class Member:
    """One live replica as the router sees it."""

    __slots__ = ("slot", "epoch", "host", "port", "outstanding", "meta")

    def __init__(self, slot: int, epoch: int, host: str, port: int, meta: Dict[str, Any]):
        self.slot = int(slot)
        self.epoch = int(epoch)
        self.host = str(host)
        self.port = int(port)
        self.outstanding = 0
        self.meta = meta

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.host, self.port)


def read_membership(path: str) -> Optional[List[Dict[str, Any]]]:
    """Best-effort read of a membership file (None on missing/torn)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    members = doc.get("members") if isinstance(doc, dict) else None
    return members if isinstance(members, list) else None


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        router: "FailoverRouter" = self.server.router  # type: ignore[attr-defined]
        wlock = threading.Lock()

        def send(obj: Dict[str, Any]) -> None:
            data = (json.dumps(obj) + "\n").encode()
            with wlock:
                try:
                    self.wfile.write(data)
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass  # client went away; the request still resolved in the stats

        while True:
            try:
                line = self.rfile.readline()
            except (ConnectionResetError, OSError):
                return
            if not line:
                return
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except ValueError:
                send({"status": "error", "error": "malformed json"})
                continue
            op = msg.get("op", "infer")
            if op == "stats":
                send(router.stats_payload())
            elif op == "health":
                send(router.health_payload())
            elif op == "infer":
                router.submit(msg, send)
            else:
                send({"status": "error", "error": f"unknown op '{op}'"})


class _TCPServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class FailoverRouter:
    def __init__(
        self,
        membership_file: str,
        stats: Optional[FleetStats] = None,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        retry_budget: int = 3,
        retry_backoff_ms: float = 25.0,
        membership_poll_s: float = 0.1,
        dial_timeout_s: float = 5.0,
        default_priority: int = 1,
        max_workers: int = 64,
    ):
        self.membership_file = membership_file
        self.stats = stats or FleetStats()
        self.host = str(host)
        self.port = int(port)
        self.retry_budget = int(retry_budget)
        self.retry_backoff_ms = float(retry_backoff_ms)
        self.membership_poll_s = float(membership_poll_s)
        self.dial_timeout_s = float(dial_timeout_s)
        self.default_priority = int(default_priority)
        self._members: Dict[int, Member] = {}
        # highest epoch ever seen per slot — the fence. Survives a member's
        # removal on purpose: a zombie re-appearing AFTER its replacement died
        # is still a zombie.
        self._fence: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._draining = False
        self._outstanding = 0
        self._pool = ThreadPoolExecutor(max_workers=int(max_workers), thread_name_prefix="sheeprl-router")
        self._tcp: Optional[_TCPServer] = None
        self._tcp_thread: Optional[threading.Thread] = None
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None

    # ----- lifecycle ------------------------------------------------------------
    def start(self) -> "FailoverRouter":
        self.refresh_membership()
        self._watch_thread = threading.Thread(
            target=self._watch_loop, name="sheeprl-router-membership", daemon=True
        )
        self._watch_thread.start()
        self._tcp = _TCPServer((self.host, self.port), _Handler)
        self._tcp.router = self  # type: ignore[attr-defined]
        self.port = self._tcp.server_address[1]
        self._tcp_thread = threading.Thread(
            target=self._tcp.serve_forever, name="sheeprl-router-tcp", daemon=True
        )
        self._tcp_thread.start()
        self.stats.set_gauge("ready", 1)
        _logger.info("[router] listening on %s:%d", self.host, self.port)
        return self

    def drain(self, timeout: float = 30.0) -> bool:
        """Refuse new work (still answered: ``rejected/draining``), then wait
        for every in-flight relay to resolve. True if it emptied in time."""
        with self._lock:
            self._draining = True
        self.stats.set_gauge("draining", 1)
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            with self._lock:
                if self._outstanding == 0:
                    return True
            time.sleep(0.02)
        with self._lock:
            return self._outstanding == 0

    def close(self) -> None:
        self.stats.set_gauge("ready", 0)
        self._watch_stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=2.0)
        if self._tcp is not None:
            self._tcp.shutdown()
            self._tcp.server_close()
        self._pool.shutdown(wait=False)

    # ----- membership -----------------------------------------------------------
    def _watch_loop(self) -> None:
        while not self._watch_stop.wait(self.membership_poll_s):
            try:
                self.refresh_membership()
            except Exception:  # membership churn must never kill the frontend
                _logger.exception("[router] membership refresh crashed")

    def refresh_membership(self) -> None:
        entries = read_membership(self.membership_file)
        if entries is None:
            return
        self.apply_membership(entries)

    def apply_membership(self, entries: List[Dict[str, Any]]) -> None:
        """Fold one membership view in: max-epoch-per-slot wins, anything
        below a slot's high-water epoch is a fenced zombie write."""
        best: Dict[int, Dict[str, Any]] = {}
        fenced = 0
        for e in entries:
            try:
                slot, epoch = int(e["slot"]), int(e["epoch"])
            except (KeyError, TypeError, ValueError):
                fenced += 1  # an unparseable entry routes nowhere either
                continue
            prev = best.get(slot)
            if prev is not None:
                fenced += 1  # duplicate slot: one of the two is stale
                if int(prev["epoch"]) >= epoch:
                    continue
            best[slot] = e
        with self._lock:
            changed = False
            for slot, e in best.items():
                epoch = int(e["epoch"])
                if epoch < self._fence.get(slot, 0):
                    fenced += 1
                    continue
                self._fence[slot] = epoch
                cur = self._members.get(slot)
                if cur is not None and cur.epoch == epoch and cur.addr == (e["host"], int(e["port"])):
                    cur.meta = e
                    continue
                self._members[slot] = Member(slot, epoch, e["host"], e["port"], dict(e))
                changed = True
            for slot in [s for s in self._members if s not in best]:
                del self._members[slot]  # absent from the authoritative view: drained/dead
                changed = True
            n = len(self._members)
            epoch_max = max(self._fence.values(), default=0)
        if fenced:
            self.stats.inc("fenced_writes", fenced)
            trace.instant("router/fenced_write", count=fenced)
        if changed:
            self.stats.inc("membership_updates")
        self.stats.set_gauge("members", n)
        self.stats.set_gauge("epoch_max", epoch_max)

    def members(self) -> List[Member]:
        with self._lock:
            return list(self._members.values())

    def _pick(self, exclude: Tuple[int, ...]) -> Optional[Member]:
        """Least-outstanding-requests pick among live members, preferring ones
        not already tried for this request; falls back to retried members when
        the fleet is smaller than the retry budget (one replica left is still
        a fleet)."""
        with self._lock:
            pool = [m for m in self._members.values() if m.slot not in exclude]
            if not pool:
                pool = list(self._members.values())
            if not pool:
                return None
            m = min(pool, key=lambda x: (x.outstanding, x.slot))
            m.outstanding += 1
            self._outstanding += 1
            self.stats.set_gauge("outstanding", self._outstanding)
            return m

    def _release(self, m: Member) -> None:
        with self._lock:
            m.outstanding = max(0, m.outstanding - 1)
            self._outstanding = max(0, self._outstanding - 1)
            self.stats.set_gauge("outstanding", self._outstanding)

    # ----- request path ---------------------------------------------------------
    def submit(self, msg: Dict[str, Any], send: Callable[[Dict[str, Any]], None]) -> None:
        """Admit one infer request; the relay (with retries) runs on the pool
        so one slow replica never serializes the frontend's read loop."""
        self.stats.inc("requests_total")
        rid = msg.get("id")
        with self._lock:
            draining = self._draining
        if draining:
            self._terminal(send, {"id": rid, "status": "rejected", "reason": "draining"})
            return
        try:
            self._pool.submit(self._relay_with_retries, dict(msg), send)
        except RuntimeError:  # pool shut down under us: still exactly one answer
            self._terminal(send, {"id": rid, "status": "rejected", "reason": "draining"})

    def _terminal(self, send: Callable[[Dict[str, Any]], None], resp: Dict[str, Any]) -> None:
        self.stats.inc(_STATUS_COUNTER.get(resp.get("status"), "errors"))
        send(resp)

    def _relay_once(self, member: Member, payload: bytes) -> Dict[str, Any]:
        # Drill sites: `router.dial:raise` = connect refused (replica just
        # died), `router.relay:raise` = connection torn mid-flight (replica
        # SIGKILLed with the request on its wire).
        failpoints.failpoint("router.dial", slot=member.slot)
        with socket.create_connection(member.addr, timeout=self.dial_timeout_s) as sock:
            f = sock.makefile("rwb")
            f.write(payload)
            f.flush()
            failpoints.failpoint("router.relay", slot=member.slot)
            line = f.readline()
        if not line:
            raise ConnectionError("replica closed the connection mid-flight")
        return json.loads(line)

    def _relay_with_retries(self, msg: Dict[str, Any], send: Callable[[Dict[str, Any]], None]) -> None:
        rid = msg.get("id")
        msg.setdefault("priority", self.default_priority)
        deadline_ms = msg.get("deadline_ms")
        t0 = time.monotonic()
        deadline_at = None if deadline_ms is None else t0 + float(deadline_ms) / 1000.0
        payload = (json.dumps(msg) + "\n").encode()
        tried: List[int] = []
        last_err = "no live replica in the fleet"
        with trace.span("router/request", plane="fleet", rid=str(rid)) as sp:
            for attempt in range(self.retry_budget + 1):
                if deadline_at is not None and time.monotonic() >= deadline_at:
                    sp.set(status="deadline_expired", attempts=attempt)
                    self._terminal(send, {"id": rid, "status": "deadline_expired"})
                    return
                member = self._pick(tuple(tried))
                if member is None:
                    break  # empty fleet: no point burning the backoff schedule
                if attempt:
                    self.stats.inc("retries")
                try:
                    try:
                        with trace.span("router/relay", plane="fleet", slot=member.slot, attempt=attempt):
                            resp = self._relay_once(member, payload)
                    finally:
                        self._release(member)
                except (OSError, ValueError, ConnectionError) as e:
                    # transport failure, not a replica answer: the request is
                    # retryable (inference is pure), on a different replica
                    tried.append(member.slot)
                    last_err = f"{type(e).__name__}: {e}"
                    self.stats.inc("dial_failures")
                    trace.instant("router/failover", slot=member.slot, attempt=attempt, error=last_err)
                    sleep_s = jittered_backoff(self.retry_backoff_ms / 1000.0, attempt + 1, 1.0)
                    if deadline_at is not None:
                        sleep_s = min(sleep_s, max(0.0, deadline_at - time.monotonic()))
                    time.sleep(sleep_s)
                    continue
                if attempt:
                    self.stats.inc("failovers")
                self.stats.observe_latency(time.monotonic() - t0)
                sp.set(status=str(resp.get("status")), slot=member.slot, attempts=attempt + 1)
                self._terminal(send, dict(resp, id=rid))
                return
            sp.set(status="error", attempts=len(tried))
            self._terminal(
                send,
                {
                    "id": rid,
                    "status": "error",
                    "error": f"no replica answered after {len(tried)} attempt(s): {last_err}",
                },
            )

    # ----- observability --------------------------------------------------------
    def stats_payload(self) -> Dict[str, Any]:
        payload = self.stats.snapshot()
        with self._lock:
            payload["Fleet/member_outstanding"] = {
                str(m.slot): m.outstanding for m in self._members.values()
            }
            payload["Fleet/member_epochs"] = {str(s): e for s, e in self._fence.items()}
        return payload

    def health_payload(self) -> Dict[str, Any]:
        with self._lock:
            n = len(self._members)
            draining = self._draining
        return {
            "ready": n > 0 and not draining,
            "draining": draining,
            "members": n,
            "pid": os.getpid(),
        }
