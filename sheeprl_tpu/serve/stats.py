"""``Serve/*`` observability: counters, gauges and a latency window.

One lock-guarded object shared by the frontend, the batcher and the reloader.
The snapshot is the single source of truth for the accounting invariant the
chaos drill asserts: every admitted request resolves to exactly one of
``ok | shed | rejected | deadline_missed | error``, so
``requests_total == ok + shed + rejected + deadline_missed + errors`` must
hold at any quiescent point.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Deque, Dict

COUNTERS = (
    "requests_total",
    "ok",
    "shed",
    "rejected",
    "deadline_missed",
    "errors",
    "batches",
    "reload_generations",
    "reload_failures",
    "reload_rollbacks",
)

GAUGES = ("queue_depth", "queue_peak", "generation", "degraded", "ready", "draining")

# ``Fleet/*`` series: the router + fleet supervisor share ONE of these, so the
# drill's single ``stats`` op sees request accounting, failover activity and
# supervision events in the same snapshot. The terminal subset obeys the same
# invariant as Serve/*: requests_total == ok+shed+rejected+deadline_missed+errors.
FLEET_COUNTERS = (
    "requests_total",
    "ok",
    "shed",
    "rejected",
    "deadline_missed",
    "errors",
    "retries",
    "failovers",
    "dial_failures",
    "fenced_writes",
    "membership_updates",
    "heartbeats",
    "replica_restarts",
    "replica_preemptions",
    "replica_failures",
    "replica_kills",
    "deploys",
    "deploy_rollbacks",
)

FLEET_GAUGES = ("members", "outstanding", "ready", "draining", "epoch_max", "replicas_live")


class ServeStats:
    _COUNTERS = COUNTERS
    _GAUGES = GAUGES
    _PREFIX = "Serve"

    def __init__(self, latency_window: int = 4096):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {k: 0 for k in self._COUNTERS}
        self._gauges: Dict[str, float] = {k: 0.0 for k in self._GAUGES}
        # windowed reservoir: p50/p99 over the LAST N served requests, not the
        # lifetime mean — load tests care about current-tail behaviour. The
        # maxlen bound is what keeps a long-running server's memory flat; the
        # window size/cap are exposed as gauges so operators can see how much
        # history the percentiles actually cover.
        self._latency_cap = max(int(latency_window), 1)
        self._latencies: Deque[float] = deque(maxlen=self._latency_cap)
        # snapshot() used to re-sort the full window on EVERY stats op; cache
        # the sorted view and only re-sort when new observations arrived, so a
        # tight health/stats polling loop against an idle server costs O(1)
        self._lat_sorted: list = []
        self._lat_dirty = False
        self._occupancy_sum = 0.0
        self._occupancy_n = 0

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] += int(n)

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe_queue_depth(self, depth: int) -> None:
        with self._lock:
            self._gauges["queue_depth"] = float(depth)
            if depth > self._gauges["queue_peak"]:
                self._gauges["queue_peak"] = float(depth)

    def observe_batch(self, n_live: int, bucket: int) -> None:
        with self._lock:
            self._counts["batches"] += 1
            self._occupancy_sum += n_live / max(bucket, 1)
            self._occupancy_n += 1

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self._latencies.append(float(seconds))
            self._lat_dirty = True

    @staticmethod
    def _percentile(sorted_vals, q: float) -> float:
        if not sorted_vals:
            return 0.0
        idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
        return sorted_vals[idx]

    def snapshot(self) -> Dict[str, Any]:
        """Prefix-keyed dict (counters, gauges, occupancy, p50/p99 ms)."""
        p = self._PREFIX
        with self._lock:
            counts = dict(self._counts)
            gauges = dict(self._gauges)
            if self._lat_dirty:
                self._lat_sorted = sorted(self._latencies)
                self._lat_dirty = False
            lat = self._lat_sorted
            occ = self._occupancy_sum / self._occupancy_n if self._occupancy_n else 0.0
        out: Dict[str, Any] = {f"{p}/{k}": v for k, v in counts.items()}
        out.update({f"{p}/{k}": v for k, v in gauges.items()})
        out[f"{p}/batch_occupancy"] = occ
        out[f"{p}/latency_p50_ms"] = self._percentile(lat, 0.50) * 1000.0
        out[f"{p}/latency_p99_ms"] = self._percentile(lat, 0.99) * 1000.0
        out[f"{p}/latency_window_size"] = len(lat)
        out[f"{p}/latency_window_cap"] = self._latency_cap
        return out


class FleetStats(ServeStats):
    """``Fleet/*`` accounting shared by the failover router and the fleet
    supervisor. The latency window records ROUTER-side end-to-end latency
    (admit at the router to terminal response), i.e. what a fleet client
    actually experiences across failover retries."""

    _COUNTERS = FLEET_COUNTERS
    _GAUGES = FLEET_GAUGES
    _PREFIX = "Fleet"
