"""Micro-batcher: coalesce concurrent inference requests onto pow-2 buckets.

The worker thread closes a batch when either ``max_batch`` requests are queued
or the OLDEST queued request has waited ``max_wait``; the batch is then padded
to :func:`~sheeprl_tpu.core.compile.pow2_bucket` by the engine, so any request
mix routes to one of O(log max_batch) AOT-compiled shapes and never retraces.

Backpressure is explicit and graded (shed load before missing deadlines,
reject before crashing):

- the queue is bounded (``queue.max_depth``); past it, admission either
  rejects the NEW request with a retry-after hint (``admission: reject``) or
  evicts the oldest request of the LOWEST priority class in sight
  (``admission: shed_oldest`` — freshest observations win within a class, but
  priority-0 traffic is always shed before priority-1; a newcomer of strictly
  lower priority than everything queued sheds itself). Shed responses carry
  the same ``retry_after_ms`` hint as rejects, so a fleet router can back off
  intelligently either way;
- every request carries a deadline budget; work already past its deadline is
  dropped at batch-assembly time instead of computing a dead answer.

Every submitted request resolves to EXACTLY ONE terminal response
(``ok | shed | rejected | deadline_expired | error``) and bumps exactly one
``Serve/*`` counter — the invariant the chaos drill audits.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Deque, Dict, List, Optional

from sheeprl_tpu.core.compile import pow2_bucket
from sheeprl_tpu.serve.stats import ServeStats
from sheeprl_tpu.telemetry import trace

# terminal status -> Serve/* counter
_STATUS_COUNTER = {
    "ok": "ok",
    "shed": "shed",
    "rejected": "rejected",
    "deadline_expired": "deadline_missed",
    "error": "errors",
}


class PendingRequest:
    __slots__ = (
        "rid",
        "obs",
        "future",
        "enqueued_at",
        "deadline_at",
        "span_id",
        "batched_at",
        "priority",
    )

    def __init__(self, rid: Any, obs: Any, deadline_s: Optional[float], priority: int = 1):
        self.rid = rid
        self.obs = obs
        self.future: Future = Future()
        self.enqueued_at = time.monotonic()
        self.deadline_at = None if deadline_s is None else self.enqueued_at + deadline_s
        # request priority class (0 = best-effort, higher = more important):
        # only consulted by shed_oldest victim selection — scheduling within
        # the queue stays strictly FIFO so batches keep coalescing untouched
        self.priority = int(priority)
        # telemetry: the request span's id is allocated at ADMIT so the
        # queue-wait child recorded at batch-assembly time can point at its
        # parent before the parent closes ("" while tracing is disabled —
        # new_span_id is one identity check on the disabled fast path)
        self.span_id = trace.new_span_id()
        self.batched_at: Optional[float] = None


class MicroBatcher:
    def __init__(
        self,
        compute_fn: Callable[[List[PendingRequest]], List[Dict[str, Any]]],
        *,
        max_batch: int,
        max_wait_s: float,
        max_depth: int,
        admission: str = "reject",
        retry_after_ms: float = 25.0,
        default_deadline_s: Optional[float] = None,
        stats: Optional[ServeStats] = None,
    ):
        if admission not in ("reject", "shed_oldest"):
            raise ValueError(f"queue.admission must be 'reject' or 'shed_oldest', got {admission!r}")
        self._compute = compute_fn
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.max_depth = int(max_depth)
        self.admission = admission
        self.retry_after_ms = float(retry_after_ms)
        self.default_deadline_s = default_deadline_s
        self.stats = stats or ServeStats()
        self._queue: Deque[PendingRequest] = deque()
        self._cond = threading.Condition()
        self._in_flight = 0
        self._draining = False
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    # ----- lifecycle ------------------------------------------------------------
    def start(self) -> "MicroBatcher":
        self._thread = threading.Thread(target=self._loop, name="sheeprl-serve-batcher", daemon=True)
        self._thread.start()
        return self

    def drain(self, timeout: float = 10.0) -> bool:
        """Refuse new work, serve everything already admitted. True if the
        queue and the in-flight batch emptied within ``timeout``."""
        deadline = time.monotonic() + float(timeout)
        with self._cond:
            self._draining = True
            self.stats.set_gauge("draining", 1)
            self._cond.notify_all()
            while self._queue or self._in_flight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.1))
        return True

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._draining = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # ----- admission ------------------------------------------------------------
    def submit(
        self,
        obs: Any,
        deadline_s: Optional[float] = None,
        rid: Any = None,
        priority: int = 1,
    ) -> Future:
        """Admit one request; ALWAYS returns a future that resolves to a
        terminal response dict — backpressure answers arrive through the same
        channel as actions, so clients need exactly one code path."""
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        req = PendingRequest(rid, obs, deadline_s, priority=priority)
        self.stats.inc("requests_total")
        shed: Optional[PendingRequest] = None
        with self._cond:
            if self._draining or self._closed:
                self._resolve_locked(req, "rejected", reason="draining")
                return req.future
            if len(self._queue) >= self.max_depth:
                if self.admission == "reject":
                    self._resolve_locked(req, "rejected", retry_after_ms=self.retry_after_ms)
                    return req.future
                # shed the oldest request of the LOWEST priority class in
                # sight. A newcomer of strictly lower priority than everything
                # queued is the victim itself — evicting queued higher-priority
                # work for it would invert the policy.
                victim = min(self._queue, key=lambda r: (r.priority, r.enqueued_at))
                if victim.priority <= req.priority:
                    self._queue.remove(victim)
                    self._queue.append(req)
                    shed = victim
                else:
                    shed = req
            else:
                self._queue.append(req)
            self.stats.observe_queue_depth(len(self._queue))
            self._cond.notify_all()
        if shed is not None:
            # the shed answer carries the same backoff hint as a reject: the
            # fleet router (and any client) backs off identically either way
            self._finish(shed, "shed", retry_after_ms=self.retry_after_ms)
        return req.future

    # ----- worker ---------------------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(0.1)
                if self._closed and not self._queue:
                    return
                # the admission window is anchored on the oldest request: close
                # the batch at max_batch or when IT has waited max_wait
                close_at = self._queue[0].enqueued_at + self.max_wait_s
                while len(self._queue) < self.max_batch and not self._closed:
                    remaining = close_at - time.monotonic()
                    if remaining <= 0 or not self._queue:
                        break
                    self._cond.wait(remaining)
                batch = [
                    self._queue.popleft() for _ in range(min(self.max_batch, len(self._queue)))
                ]
                self._in_flight = len(batch)
                self.stats.observe_queue_depth(len(self._queue))
            try:
                if batch:
                    self._run_batch(batch)
            finally:
                with self._cond:
                    self._in_flight = 0
                    self._cond.notify_all()

    def _run_batch(self, batch: List[PendingRequest]) -> None:
        now = time.monotonic()
        live: List[PendingRequest] = []
        for r in batch:
            r.batched_at = now
            if r.deadline_at is not None and now > r.deadline_at:
                self._finish(r, "deadline_expired")
            else:
                live.append(r)
        if not live:
            return
        self.stats.observe_batch(len(live), min(pow2_bucket(len(live)), self.max_batch))
        try:
            with trace.span("serve/infer", plane="serve", batch=len(live)):
                results = self._compute(live)
        except Exception as e:  # device/engine failure: fail the batch, not the server
            err = f"{type(e).__name__}: {e}"
            for r in live:
                self._finish(r, "error", error=err)
            return
        done = time.monotonic()
        for r, res in zip(live, results):
            self.stats.observe_latency(done - r.enqueued_at)
            self._finish(r, "ok", **res)

    # ----- terminal resolution ----------------------------------------------------
    def _finish(self, req: PendingRequest, status: str, **extra: Any) -> None:
        self.stats.inc(_STATUS_COUNTER[status])
        if req.span_id:  # tracing was enabled at admit: close the lifecycle spans
            done = time.monotonic()
            if req.batched_at is not None:
                # admit -> batch assembly, as a child of the request span
                trace.add_span(
                    "serve/queue_wait",
                    req.enqueued_at,
                    req.batched_at,
                    plane="serve",
                    parent_id=req.span_id,
                )
            trace.add_span(
                "serve/request",
                req.enqueued_at,
                done,
                plane="serve",
                span_id=req.span_id,
                status=status,
                rid=str(req.rid),
            )
        payload = {"id": req.rid, "status": status}
        payload.update(extra)
        if not req.future.set_running_or_notify_cancel():
            return
        req.future.set_result(payload)

    def _resolve_locked(self, req: PendingRequest, status: str, **extra: Any) -> None:
        # same as _finish; named for call sites inside self._cond (the future
        # callback runs synchronously — keep it cheap there)
        self._finish(req, status, **extra)
