// Multithreaded sequence gather for the replay-buffer sampling hot path.
//
// The reference's equivalent is torch/numpy fancy indexing inside
// SequentialReplayBuffer._get_samples (sheeprl/data/buffers.py:467-526) — a
// single-threaded gather followed by a transpose. Here one pass writes rows
// straight into the final [n_samples, L, B, row] layout (gather + transpose
// fused), parallelized over (sample, batch) pairs. This is host-side work that
// overlaps with TPU compute; keeping it off the GIL matters because the rollout
// loop shares the process.
//
// Built by sheeprl_tpu/native/__init__.py with g++ -O3 -march=native; called
// through ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// src:   [capacity, n_envs, row_bytes]  (contiguous byte view)
// dst:   [n_pairs/B, L, B, row_bytes]   (contiguous byte view)
// starts/envs: per (sample, batch) pair, length n_pairs; the sequence for pair
// p = (n, b) reads src[(starts[p] + t) % capacity, envs[p], :] for t in [0, L).
void seq_gather(const char* src, char* dst, const int64_t* starts,
                const int64_t* envs, int64_t n_pairs, int64_t B, int64_t L,
                int64_t capacity, int64_t n_envs, int64_t row_bytes,
                int32_t n_threads) {
  const int64_t src_step = n_envs * row_bytes;  // one time-step of all envs
  auto worker = [&](int64_t p_begin, int64_t p_end) {
    for (int64_t p = p_begin; p < p_end; ++p) {
      const int64_t n = p / B;
      const int64_t b = p % B;
      const int64_t start = starts[p];
      const char* env_base = src + envs[p] * row_bytes;
      char* out_base = dst + (n * L * B + b) * row_bytes;
      for (int64_t t = 0; t < L; ++t) {
        const int64_t idx = (start + t) % capacity;
        std::memcpy(out_base + t * B * row_bytes, env_base + idx * src_step,
                    static_cast<size_t>(row_bytes));
      }
    }
  };

  if (n_threads <= 1 || n_pairs < 2 * n_threads) {
    worker(0, n_pairs);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(static_cast<size_t>(n_threads));
  const int64_t chunk = (n_pairs + n_threads - 1) / n_threads;
  for (int32_t i = 0; i < n_threads; ++i) {
    const int64_t b0 = i * chunk;
    const int64_t b1 = b0 + chunk < n_pairs ? b0 + chunk : n_pairs;
    if (b0 >= b1) break;
    threads.emplace_back(worker, b0, b1);
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
