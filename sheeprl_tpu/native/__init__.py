"""Native (C++) host-runtime components.

The TPU compute path is JAX/XLA/Pallas; the host runtime around it — here the
replay-buffer sequence gather that feeds every Dreamer gradient step (SURVEY
hot loop #4, reference buffers.py:467-526) — is C++ compiled on first use with
the toolchain baked into the image (no pybind11: plain ``extern "C"`` + ctypes).

The shared object is cached under ``~/.cache/sheeprl_tpu_native/`` keyed by a
source hash, so rebuilds happen only when the source changes. Opt out entirely
with ``SHEEPRL_TPU_NO_NATIVE=1`` (pure-numpy fallbacks are always available and
tested for parity).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "seq_gather.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    if os.environ.get("SHEEPRL_TPU_NO_NATIVE"):
        return None
    try:
        import platform

        with open(_SRC, "rb") as f:
            src_bytes = f.read()
        # -march=native binaries are host-specific: key the cache on the target
        # ISA too, or a shared home dir on a heterogeneous fleet serves an .so
        # with illegal instructions to older CPUs
        try:
            target = subprocess.run(
                ["g++", "-march=native", "-Q", "--help=target"], capture_output=True
            ).stdout
        except Exception:
            target = b""
        digest = hashlib.sha256(src_bytes + platform.machine().encode() + target).hexdigest()[:16]
        cache_dir = os.environ.get(
            "SHEEPRL_TPU_NATIVE_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache", "sheeprl_tpu_native"),
        )
        os.makedirs(cache_dir, exist_ok=True)
        so_path = os.path.join(cache_dir, f"seq_gather_{digest}.so")
        if not os.path.exists(so_path):
            tmp = so_path + f".tmp{os.getpid()}"
            subprocess.run(
                ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17", "-pthread", _SRC, "-o", tmp],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, so_path)
        lib = ctypes.CDLL(so_path)
        lib.seq_gather.restype = None
        lib.seq_gather.argtypes = [
            ctypes.c_char_p,  # src
            ctypes.c_char_p,  # dst
            ctypes.POINTER(ctypes.c_int64),  # starts
            ctypes.POINTER(ctypes.c_int64),  # envs
            ctypes.c_int64,  # n_pairs
            ctypes.c_int64,  # B
            ctypes.c_int64,  # L
            ctypes.c_int64,  # capacity
            ctypes.c_int64,  # n_envs
            ctypes.c_int64,  # row_bytes
            ctypes.c_int32,  # n_threads
        ]
        return lib
    except Exception:  # pragma: no cover - toolchain missing / build failure
        return None


def native_available() -> bool:
    return _get_lib() is not None


def _get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if not _TRIED:
        with _LOCK:
            if not _TRIED:
                _LIB = _build_and_load()
                _TRIED = True
    return _LIB


def _n_threads(n_pairs: int) -> int:
    cpus = os.cpu_count() or 1
    return max(1, min(8, cpus - 1, n_pairs))


def seq_gather(
    src: np.ndarray,  # [capacity, n_envs, *feat]
    starts: np.ndarray,  # [n_samples * B] int64 start indices
    envs: np.ndarray,  # [n_samples * B] int64 env indices
    n_samples: int,
    batch_size: int,
    sequence_length: int,
) -> Optional[np.ndarray]:
    """Gather sequences into ``[n_samples, L, B, *feat]``; None if unavailable.

    Semantics: ``out[n, t, b] = src[(starts[n*B+b] + t) % capacity, envs[n*B+b]]``.
    """
    lib = _get_lib()
    if lib is None:
        return None
    src = np.ascontiguousarray(src)
    feat_shape = src.shape[2:]
    row_bytes = int(np.prod(feat_shape, dtype=np.int64)) * src.dtype.itemsize
    if row_bytes == 0:
        return np.empty((n_samples, sequence_length, batch_size, *feat_shape), dtype=src.dtype)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    envs = np.ascontiguousarray(envs, dtype=np.int64)
    n_pairs = n_samples * batch_size
    out = np.empty((n_samples, sequence_length, batch_size, *feat_shape), dtype=src.dtype)
    lib.seq_gather(
        src.ctypes.data_as(ctypes.c_char_p),
        out.ctypes.data_as(ctypes.c_char_p),
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        envs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n_pairs,
        batch_size,
        sequence_length,
        src.shape[0],
        src.shape[1],
        row_bytes,
        _n_threads(n_pairs),
    )
    return out
