"""A2C utilities (reference sheeprl/algos/a2c/utils.py)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.utils import test  # noqa: F401  (same greedy test loop)

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Resilience/env_restarts",
    "Resilience/env_timeouts",
    "Resilience/nonfinite_skips",
}
# Compilation-management counters (core/compile.py), drained once per iteration.
AGGREGATOR_KEYS |= {
    "Compile/retraces",
    "Compile/cache_hits",
    "Compile/cache_misses",
    "Time/compile_seconds",
}
MODELS_TO_REGISTER = {"agent"}


def normalize_obs(obs, cnn_keys: Sequence[str], obs_keys: Sequence[str]):
    return {k: jnp.asarray(obs[k], dtype=jnp.float32) for k in obs_keys}


def prepare_obs(runtime, obs: Dict[str, np.ndarray], *, num_envs: int = 1, **kwargs) -> Dict[str, jax.Array]:
    """A2C is vector-obs only (reference utils.py:16-21); obs land on the player device."""
    device = runtime.player_device if runtime is not None else None
    out = {}
    for k, v in obs.items():
        arr = np.asarray(v, dtype=np.float32).reshape(num_envs, -1)
        out[k] = jax.device_put(arr, device) if device is not None else jnp.asarray(arr)
    return out

# Single-'agent' registration shared with the other model-free algos.
from sheeprl_tpu.utils.model_manager import log_agent_from_checkpoint as log_models_from_checkpoint  # noqa: E402, F401
