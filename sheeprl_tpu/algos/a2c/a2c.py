"""A2C, coupled training (reference sheeprl/algos/a2c/a2c.py:26-118 train, :118 main).

Same rollout skeleton as PPO; the optimization phase is one jitted call that
accumulates gradients across minibatches (`lax.scan`) and applies a single optimizer
step — the in-graph equivalent of the reference's `fabric.no_backward_sync`
gradient-accumulation loop.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.algos.a2c.loss import policy_loss, value_loss
from sheeprl_tpu.algos.a2c.utils import normalize_obs, prepare_obs, test
from sheeprl_tpu.algos.ppo.agent import build_agent, evaluate_actions
from sheeprl_tpu.algos.ppo.loss import entropy_loss
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.core import failpoints
from sheeprl_tpu.core import health as health_mod
from sheeprl_tpu.core import resilience
from sheeprl_tpu.core.pipeline import AsyncEnvStepper, PackedObsCodec, pipeline_enabled
from sheeprl_tpu.data.factory import make_rollout_buffer
from sheeprl_tpu.envs import ingraph as ingraph_envs
from sheeprl_tpu.parallel import handoff, overlap
from sheeprl_tpu.telemetry import device as tel_device
from sheeprl_tpu.telemetry import programs as tel_programs
from sheeprl_tpu.telemetry import trace
from sheeprl_tpu.utils.env import finished_episodes, make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.optim import with_clipping
from sheeprl_tpu.utils.profiler import TraceProfiler
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import PlayerParamsSync, gae, normalize_tensor, save_configs


def make_update_impl(
    agent, tx, cfg, runtime, n_data: int, obs_keys, params_sync=None, *, axis_name=None, shards=1, constrain_data=True, batch_size=None
):
    """Build the raw (unjitted) per-iteration optimization function.

    Same two flavors as :func:`sheeprl_tpu.algos.ppo.ppo.make_update_impl`:
    the default is the jitted split-path train step AND the single-device fused
    iteration's update phase; ``axis_name="data"``/``shards=N`` is the
    shard-local body for the fused ``shard_map`` variant — the accumulated
    gradient (and the ``pg_sum``/``v_sum``/``gnorm`` scalars feeding the
    nonfinite guard's decision, so every shard takes the identical
    apply-or-skip branch) all-reduce via ``jax.lax.pmean`` before the single
    optimizer step.
    """
    # batch_size overrides the data-parallel global batch for the population
    # trainer's member-sharded mesh (see the PPO twin)
    global_bs = (
        int(batch_size) if batch_size is not None
        else int(cfg.algo.per_rank_batch_size) * runtime.world_size
    )
    shards = int(shards)
    local_n = n_data // shards
    local_bs = max(global_bs // shards, 1)
    n_minibatches = max(local_n // local_bs, 1)
    # constrain_data=False: see the PPO twin — the population trainer vmaps
    # this body over a member axis where the env-batch constraint is invalid.
    data_sharding = (
        NamedSharding(runtime.mesh, P("data")) if (axis_name is None and constrain_data) else None
    )
    nonfinite_guard = resilience.guard_enabled(resilience.resolve(cfg))

    def loss_fn(params, batch):
        norm_obs = normalize_obs(batch, [], obs_keys)
        actions = (
            jnp.split(batch["actions"], np.cumsum(agent.actions_dim)[:-1].tolist(), axis=-1)
            if len(agent.actions_dim) > 1
            else [batch["actions"]]
        )
        actor_outs, new_values = agent.apply(params, norm_obs)
        logprobs, entropy = evaluate_actions(actor_outs, actions, agent.is_continuous, agent.distribution)
        advantages = batch["advantages"]
        if cfg.algo.normalize_advantages:
            advantages = normalize_tensor(advantages)
        pg_loss = policy_loss(logprobs, advantages, cfg.algo.loss_reduction)
        v_loss = value_loss(new_values, batch["returns"], cfg.algo.loss_reduction)
        ent_loss = entropy_loss(entropy, cfg.algo.loss_reduction)
        total = pg_loss + cfg.algo.vf_coef * v_loss + cfg.algo.ent_coef * ent_loss
        return total, (pg_loss, v_loss)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    micro = overlap.microbatches(cfg)
    # gradient-sync overlap (parallel/overlap.py): with micro > 1 each
    # minibatch's gradient is computed chunk-by-chunk with a per-bucket psum,
    # so the returned per-minibatch gradient is ALREADY axis-averaged — the
    # single post-scan pmean below must then be skipped for grads (the scalar
    # sums still reduce once). micro == 1 keeps the op-identical reference
    # path: local grads accumulated, ONE pmean at the end.
    inner_axis = axis_name if micro > 1 else None

    def train(params, opt_state, data, next_values, key, lr_scale):
        returns, advantages = gae(
            data["rewards"],
            data["values"],
            data["dones"],
            next_values,
            cfg.algo.rollout_steps,
            cfg.algo.gamma,
            cfg.algo.gae_lambda,
        )
        data = dict(data)
        data["returns"] = returns
        data["advantages"] = advantages
        flat = {k: v.reshape((-1,) + v.shape[2:]) for k, v in data.items()}
        if n_minibatches == 1 and local_bs >= local_n:
            # ONE minibatch covering every row: a permutation only reorders the
            # batch mean, so skip the O(N log N) sort and the full-data gather
            perm = None
        else:
            n_keep = n_minibatches * local_bs
            perm = jax.random.permutation(key, local_n)[:n_keep].reshape(n_minibatches, local_bs)

        def accumulate(carry, idx):
            grads_acc, pg_acc, v_acc = carry
            if idx is None:
                batch = flat
                if data_sharding is not None:
                    batch = jax.tree_util.tree_map(
                        lambda v: jax.lax.with_sharding_constraint(v, data_sharding), batch
                    )
            elif data_sharding is not None:
                batch = jax.tree_util.tree_map(
                    lambda v: jax.lax.with_sharding_constraint(jnp.take(v, idx, axis=0), data_sharding), flat
                )
            else:
                # shard-local body: the rows are already this shard's block
                batch = jax.tree_util.tree_map(lambda v: jnp.take(v, idx, axis=0), flat)
            (_, (pg, vl)), grads = overlap.accumulate_grads(
                grad_fn, params, batch,
                microbatches=micro, axis_name=inner_axis, axis_size=shards,
            )
            grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc, grads)
            return (grads_acc, pg_acc + pg, v_acc + vl), None

        zero_grads = jax.tree_util.tree_map(jnp.zeros_like, params)
        (grads, pg_sum, v_sum), _ = jax.lax.scan(
            accumulate, (zero_grads, jnp.float32(0), jnp.float32(0)), perm,
            length=1 if perm is None else None,
        )
        if axis_name is not None:
            # data-parallel all-reduce of the ONE accumulated update; the loss
            # sums reduce too so the finite_or_skip decision below is
            # replicated (a shard-local skip would fork the param replicas).
            # With microbatching the grads already all-reduced per bucket
            # inside accumulate_grads — only the scalars remain.
            if inner_axis is None:
                grads = jax.lax.pmean(grads, axis_name)
            pg_sum = jax.lax.pmean(pg_sum, axis_name)
            v_sum = jax.lax.pmean(v_sum, axis_name)
        gnorm = optax.global_norm(grads)
        updates, new_opt_state = tx.update(grads, opt_state, params)
        # health-sentinel LR backoff: traced scalar operand; 1.0 is IEEE-exact
        updates = jax.tree_util.tree_map(lambda u: u * lr_scale, updates)
        new_params = optax.apply_updates(params, updates)
        if nonfinite_guard:
            # one accumulated update per iteration: guard that single apply
            (params, opt_state), skipped = resilience.finite_or_skip(
                (pg_sum, v_sum, gnorm), (new_params, new_opt_state), (params, opt_state)
            )
        else:
            params, opt_state, skipped = new_params, new_opt_state, jnp.float32(0.0)
        flat_params = params_sync.ravel(params) if params_sync is not None else jnp.zeros(())
        return params, opt_state, flat_params, {
            "Loss/policy_loss": pg_sum / n_minibatches,
            "Loss/value_loss": v_sum / n_minibatches,
            "Resilience/nonfinite_skips": skipped,
            "Grads/global_norm": gnorm,
        }

    return train


def make_train_fn(agent, tx, cfg, runtime, n_data: int, obs_keys, params_sync=None):
    """The jitted split-path train step (see :func:`make_update_impl`)."""
    train = make_update_impl(agent, tx, cfg, runtime, n_data, obs_keys, params_sync)
    return jax_compile.guarded_jit(train, name="a2c.train", donate_argnums=(0, 1))


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    use_ingraph = ingraph_envs.env_backend(cfg) == "ingraph"
    if len(cfg.algo.cnn_keys.encoder) > 0:
        raise ValueError("A2C is vector-observation only: do not set `algo.cnn_keys.encoder`")
    world_size = runtime.world_size

    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_state

        state = load_state(cfg.checkpoint.resume_from)

    logger = get_logger(runtime, cfg)
    if logger:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.logger = logger
    runtime.print(f"Log dir: {log_dir}")
    if runtime.is_global_zero and log_dir:
        # compiled-program ledger for this run (parent-pinned env path wins)
        tel_programs.configure_default(os.path.join(log_dir, "telemetry", "programs.jsonl"))

    ft = resilience.resolve(cfg)
    sentinel = health_mod.HealthSentinel(
        cfg, log_dir=log_dir if runtime.is_global_zero else None, world_size=world_size
    )
    n_envs = cfg.env.num_envs * world_size
    if use_ingraph:
        # in-graph backend (envs/ingraph/): the env batch is one device-resident
        # pytree stepped inside the fused rollout scan (see ppo.py for the
        # full rationale — A2C shares the structure)
        collect_device = runtime.device
        envs = ingraph_envs.make_vector_env(cfg, n_envs, cfg.seed, device=collect_device)
    else:
        envs = resilience.make_supervised_env(
            [
                make_env(cfg, cfg.seed + i, 0, log_dir if runtime.is_global_zero else None, "train", vector_env_idx=i)
                for i in range(n_envs)
            ],
            sync=cfg.env.sync_env,
            ft=ft,
        )
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    obs_keys = cfg.algo.mlp_keys.encoder

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (envs.single_action_space.nvec.tolist() if is_multidiscrete else [envs.single_action_space.n])
    )

    agent, params, player = build_agent(
        runtime, actions_dim, is_continuous, cfg, observation_space, state["agent"] if state else None
    )
    if use_ingraph:
        # policy forward runs inside the scan on the collect device, not on the
        # (host) player device build_agent placed the params on
        player.params = jax.device_put(player.params, collect_device)
    player_sync_device = collect_device if use_ingraph else runtime.player_device

    tx = with_clipping(instantiate(dict(cfg.algo.optimizer))(), cfg.algo.max_grad_norm)
    opt_state = tx.init(params)
    if state:
        opt_state = jax.tree_util.tree_map(jnp.asarray, state["optimizer"])
    opt_state = runtime.place_params(opt_state)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    rb = make_rollout_buffer(cfg, runtime, n_envs, obs_keys, log_dir)
    device_rollout = getattr(rb, "backend", "host") == "device"

    last_train = 0
    train_step = 0
    start_iter = (state["iter_num"] // world_size) + 1 if state else 1
    policy_step = state["iter_num"] * cfg.env.num_envs * cfg.algo.rollout_steps if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(n_envs * cfg.algo.rollout_steps)
    total_iters = cfg.algo.total_steps // policy_steps_per_iter if not cfg.dry_run else 1
    if state:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
    n_data = cfg.algo.rollout_steps * n_envs

    params_sync = PlayerParamsSync(player.params)
    train_fn = make_train_fn(agent, tx, cfg, runtime, n_data, obs_keys, params_sync)
    profiler = TraceProfiler(cfg.metric.get("profiler"), log_dir if runtime.is_global_zero else None)
    rng = jax.random.PRNGKey(cfg.seed)
    player_rng = jax.device_put(jax.random.PRNGKey(cfg.seed + 1), runtime.player_device)
    if state and "rng" in state:
        rng = jnp.asarray(state["rng"])
        player_rng = jax.device_put(jnp.asarray(state["player_rng"]), runtime.player_device)

    step_data = {}
    reset_obs = envs.reset(seed=cfg.seed)[0]
    next_obs = {}
    for k in obs_keys:
        next_obs[k] = reset_obs[k]
        step_data[k] = reset_obs[k][np.newaxis]

    # ----- software pipeline (core/pipeline.py): same structure as ppo.py — env
    # workers step while the host closes out the previous step; obs reach the
    # device as ONE packed put per step with the prior rewards/dones riding along
    stepper = AsyncEnvStepper(envs, enabled=pipeline_enabled(cfg) and not use_ingraph)
    codec = PackedObsCodec(cnn_keys=(), device=runtime.player_device)
    collector = None
    fused_trainer = None
    if use_ingraph:
        # A2C's loss recomputes logprobs, so the collector stores only
        # obs/actions/values/rewards/dones
        collector = ingraph_envs.InGraphRolloutCollector(
            envs,
            player,
            rollout_steps=cfg.algo.rollout_steps,
            gamma=cfg.algo.gamma,
            clip_rewards=cfg.env.clip_rewards,
            store_logprobs=False,
            name="a2c",
        )
        if ingraph_envs.fused_enabled(cfg):
            # ----- whole-iteration fusion (envs/ingraph/fused.py): rollout scan
            # + GAE + the accumulate-and-apply update compile into ONE program;
            # on a multi-device mesh the env batch shards on the `data` axis and
            # the accumulated gradient all-reduces in-graph
            update_impl = make_update_impl(
                agent,
                tx,
                cfg,
                runtime,
                n_data,
                obs_keys,
                params_sync,
                axis_name="data" if world_size > 1 else None,
                shards=world_size,
            )
            fused_trainer = ingraph_envs.FusedInGraphTrainer(
                collector,
                update_impl,
                n_extras=1,
                mesh=runtime.mesh if world_size > 1 else None,
                name="a2c",
            )
            fused_trainer.shard_carry()
    zero_extra = {
        "rewards": np.zeros((n_envs, 1), np.float32),
        "dones": np.zeros((n_envs, 1), np.float32),
    }

    # ----- AOT warmup (core/compile.py): same scheme as ppo.py — compile the
    # packed-act step, the accumulate-and-apply train step, and the metric-drain
    # kernels on a background thread while the first rollout collects.
    warmup = jax_compile.AOTWarmup(enabled=jax_compile.aot_enabled(cfg))
    if warmup.enabled and use_ingraph:
        if fused_trainer is not None:
            # ONE entry point for the whole iteration: collect + GAE + the
            # accumulated update. Specs come from the live (mesh-sharded, for
            # the shard_map variant) params/opt_state/carry.
            warmup.add(
                fused_trainer.step_fn,
                *fused_trainer.warmup_specs(params, opt_state, rng, jnp.float32(1.0)),
            )
        else:
            # ONE rollout entry point (the fused scan); its abstract outputs are
            # the train step's input specs — both derive without touching the
            # device
            warmup.add(collector.collect_fn, *collector.warmup_specs())
            data_specs, nv_spec = collector.output_specs()
            warmup.add(
                train_fn,
                jax_compile.specs_of(params),
                jax_compile.specs_of(opt_state),
                # the handoff assembles the batch PRE-SHARDED on the mesh (env
                # axis): warmup against that layout (see ppo.py)
                handoff.shard_specs(data_specs, runtime.mesh, batch_axis=1),
                jax.ShapeDtypeStruct(nv_spec.shape, jnp.float32, sharding=runtime.replicated),
                jax_compile.spec_like(rng),
                jax.ShapeDtypeStruct((), jnp.float32),
            )
        if aggregator is not None:
            warmup.add_task(
                lambda: aggregator.precompile_drain(
                    (
                        "Loss/policy_loss",
                        "Loss/value_loss",
                        "Resilience/nonfinite_skips",
                        "Grads/global_norm",
                    )
                ),
                name="metric.drain",
            )
        warmup.start()
    elif warmup.enabled:
        packed0 = codec.encode(next_obs, extra=zero_extra)
        act_fn = player.packed_act_fn(codec)
        act_specs = (
            jax_compile.specs_of(player.params),
            jax_compile.spec_like(packed0),
            jax_compile.spec_like(player_rng),
        )
        warmup.add(act_fn, *act_specs)
        if not device_rollout:
            cat_s, _env_s, _logp_s, val_s, _key_s = jax.eval_shape(act_fn.fun, *act_specs)
            T = int(cfg.algo.rollout_steps)
            data_specs = {
                k: jax.ShapeDtypeStruct((T, *next_obs[k].shape), jnp.float32) for k in obs_keys
            }
            for k, s in (("actions", cat_s), ("values", val_s)):
                data_specs[k] = jax.ShapeDtypeStruct((T, *s.shape), jnp.float32)
            for k in ("rewards", "dones"):
                data_specs[k] = jax.ShapeDtypeStruct((T, n_envs, 1), jnp.float32)
            warmup.add(
                train_fn,
                jax_compile.specs_of(params),
                jax_compile.specs_of(opt_state),
                # host rollout enters the mesh shard-at-put (env axis)
                handoff.shard_specs(data_specs, runtime.mesh, batch_axis=1),
                jax.ShapeDtypeStruct(val_s.shape, jnp.float32),
                jax_compile.spec_like(rng),
                jax.ShapeDtypeStruct((), jnp.float32),
            )
        if aggregator is not None:
            warmup.add_task(
                lambda: aggregator.precompile_drain(
                    (
                        "Loss/policy_loss",
                        "Loss/value_loss",
                        "Resilience/nonfinite_skips",
                        "Grads/global_norm",
                    )
                ),
                name="metric.drain",
            )
        warmup.start()

    pending: Dict[str, Any] = {}

    def _process_pending(cur_packed):
        """Close out the previous step while the env workers run (see ppo.py)."""
        if not pending:
            return
        if device_rollout:
            if cur_packed is not None:
                extra_packed, extra_only = cur_packed, False
            else:
                extra_packed, extra_only = (
                    codec.encode_extra_only(
                        {"rewards": pending["rewards"], "dones": pending["dones"]}
                    ),
                    True,
                )
            rb.add_env_packed(codec, pending["packed"], extra_packed, extra_only=extra_only)
        else:
            rewards = pending["rewards"]
            step_data["dones"] = pending["dones"][np.newaxis]
            step_data["values"] = np.asarray(pending["values"])[np.newaxis]
            step_data["actions"] = np.asarray(pending["cat_actions"])[np.newaxis]
            step_data["rewards"] = rewards[np.newaxis]
            if cfg.buffer.memmap:
                step_data["returns"] = np.zeros_like(rewards, shape=(1, *rewards.shape))
                step_data["advantages"] = np.zeros_like(rewards, shape=(1, *rewards.shape))
            rb.add(step_data, validate_args=cfg.buffer.validate_args)
            for k in obs_keys:
                step_data[k] = next_obs[k][np.newaxis]
        if cfg.metric.log_level > 0:
            for i, (ep_rew, ep_len) in enumerate(finished_episodes(pending["info"])):
                if aggregator and "Rewards/rew_avg" in aggregator:
                    aggregator.update("Rewards/rew_avg", ep_rew)
                if aggregator and "Game/ep_len_avg" in aggregator:
                    aggregator.update("Game/ep_len_avg", ep_len)
                runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")
        pending.clear()

    def _ckpt_state():
        # shared by the periodic checkpoint and the preemption emergency save so
        # both are resumable through the identical path; the rng chains make the
        # resumed run BIT-IDENTICAL to an uninterrupted one
        return {
            "agent": jax.device_get(params),
            "optimizer": jax.device_get(opt_state),
            "iter_num": iter_num * world_size,
            "batch_size": cfg.algo.per_rank_batch_size * world_size,
            "last_log": last_log,
            "last_checkpoint": last_checkpoint,
            "rng": jax.device_get(rng),
            "player_rng": jax.device_get(player_rng),
        }

    def _drain_ingraph_episodes(roll_metrics):
        """Pull and log the [T, B] episode-metric leaves from an ingraph rollout.

        Skipped when nothing consumes them: aggregator disabled, or between
        ``log_every`` drains (episodes are then sampled at drain iterations
        rather than fetched every iteration) — see ppo.py."""
        if cfg.metric.log_level <= 0 or aggregator is None or aggregator.disabled:
            return
        if policy_step - last_log < cfg.metric.log_every and iter_num != total_iters:
            return
        for ep_rew, ep_len in ingraph_envs.iter_finished_episodes(roll_metrics):
            if "Rewards/rew_avg" in aggregator:
                aggregator.update("Rewards/rew_avg", ep_rew)
            if "Game/ep_len_avg" in aggregator:
                aggregator.update("Game/ep_len_avg", ep_len)
            runtime.print(f"Rank-0: policy_step={policy_step}, episode_reward={ep_rew}")

    guard = resilience.PreemptionGuard(
        enabled=ft.preemption.enabled, stop_after_iters=ft.preemption.stop_after_iters
    )
    with guard:
        for iter_num in range(start_iter, total_iters + 1):
            profiler.step(policy_step)
            if fused_trainer is not None:
                # ----- whole-iteration fused step (envs/ingraph/fused.py): the
                # rollout scan, GAE, and the accumulated update run as ONE
                # compiled donated-carry program (see ppo.py)
                failpoints.failpoint("train.fused_update", iter=iter_num)
                failpoints.failpoint(
                    "train.grad_sync", iter=iter_num, microbatches=overlap.microbatches(cfg)
                )
                with trace.span("train/update", fused=True, iter=iter_num), timer(
                    "Time/train_time", SumMetric()
                ):
                    if iter_num == start_iter:
                        warmup.wait()
                    policy_step += n_envs * cfg.algo.rollout_steps
                    rng, train_key = jax.random.split(rng)
                    params, opt_state, flat_params, roll_metrics, train_metrics = fused_trainer.step(
                        params,
                        opt_state,
                        fused_trainer.to_mesh(train_key),
                        fused_trainer.to_mesh(jnp.float32(sentinel.lr_scale)),
                    )
                    player.params = params_sync.pull(flat_params, player_sync_device)
                    if not timer.disabled:  # sync only when the phase is being timed
                        jax.block_until_ready(params)
                train_step += world_size
                envs.fire_autoreset_failpoints(roll_metrics["dones"])
                _drain_ingraph_episodes(roll_metrics)
            elif use_ingraph:
                # ----- split ingraph path (env.fused=False): the fused rollout
                # scan followed by the separately jitted train step below — the
                # fused path's parity reference
                with trace.span("train/collect", iter=iter_num), timer(
                    "Time/env_interaction_time", SumMetric()
                ):
                    policy_step += n_envs * cfg.algo.rollout_steps
                    ingraph_data, roll_metrics, ingraph_next_values = collector.collect()
                # zero-cost unless an env.autoreset drill is armed
                envs.fire_autoreset_failpoints(roll_metrics["dones"])
                _drain_ingraph_episodes(roll_metrics)
            else:
                for _ in range(cfg.algo.rollout_steps):
                    policy_step += n_envs

                    with timer("Time/env_interaction_time", SumMetric()):
                        # ONE packed host->device transfer per step (A2C reuses the
                        # PPO agent, vector obs only; see PPOPlayer.act_packed)
                        packed = codec.encode(
                            next_obs,
                            extra={"rewards": pending["rewards"], "dones": pending["dones"]}
                            if pending
                            else zero_extra,
                        )
                        cat_actions, env_actions, _, values, player_rng = player.act_packed(
                            codec, packed, player_rng
                        )
                        # the one unavoidable per-step device->host sync: env actions
                        real_actions = np.asarray(env_actions)
                        stepper.step_async(real_actions.reshape(envs.action_space.shape))

                        # ---- overlap window: env workers are stepping
                        _process_pending(packed)
                        if device_rollout:
                            # in-graph scatter: actions/values stay in HBM (A2C's loss
                            # recomputes logprobs, so only these two leaves are stored)
                            rb.add_policy({"actions": cat_actions, "values": values})

                        obs, rewards, terminated, truncated, info = stepper.step_wait()
                        dones = np.logical_or(terminated, truncated).reshape(n_envs, -1).astype(np.uint8)
                        rewards = np.asarray(rewards, dtype=np.float32).reshape(n_envs, -1)

                        pending.update(
                            packed=packed,
                            rewards=rewards,
                            dones=dones,
                            info=info,
                            values=values,
                            cat_actions=cat_actions,
                        )

                        next_obs = {}
                        for k in obs_keys:
                            next_obs[k] = obs[k]

                with timer("Time/env_interaction_time", SumMetric()):
                    # flush: the rollout's last row has no next act transfer to ride
                    _process_pending(None)

            # ----- optimization phase: single jitted call. The fused path
            # already ran its update inside the one program above.
            if fused_trainer is None:
                if not device_rollout and not use_ingraph:
                    local_data = rb.to_arrays(dtype=np.float32)
                    if cfg.buffer.size > cfg.algo.rollout_steps:
                        idx = np.arange(rb._pos - cfg.algo.rollout_steps, rb._pos) % cfg.buffer.size
                        local_data = {k: v[idx] for k, v in local_data.items()}
                with trace.span("train/update", iter=iter_num), timer(
                    "Time/train_time", SumMetric()
                ):
                    if iter_num == start_iter:
                        # surface any residual warmup compile time here rather than
                        # inside the train call (the rollout overlapped the thread)
                        warmup.wait()
                    rng, train_key = jax.random.split(rng)
                    # ----- donated per-shard handoff (parallel/handoff.py): the
                    # [T, B, *] rollout shards on the env axis (B) so GAE's scan
                    # over T stays shard-local — each mesh device receives ONE
                    # put of only its env block instead of a full replicated
                    # copy. Bootstrap values are tiny and stay replicated.
                    if use_ingraph:
                        device_data = handoff.shard_put(
                            ingraph_data, runtime.mesh, batch_axis=1
                        )
                        next_values = runtime.replicate(ingraph_next_values)
                    elif device_rollout:
                        jax_obs = prepare_obs(runtime, next_obs, num_envs=n_envs)
                        device_data = handoff.shard_put(
                            rb.rollout(), runtime.mesh, batch_axis=1
                        )
                        next_values = runtime.replicate(player.get_values(jax_obs))
                    else:
                        jax_obs = prepare_obs(runtime, next_obs, num_envs=n_envs)
                        next_values = np.asarray(player.get_values(jax_obs))
                        device_data = handoff.shard_put(
                            {k: v for k, v in local_data.items() if k not in ("returns", "advantages")},
                            runtime.mesh,
                            batch_axis=1,
                        )
                    failpoints.failpoint(
                        "train.grad_sync", iter=iter_num, microbatches=overlap.microbatches(cfg)
                    )
                    params, opt_state, flat_params, train_metrics = train_fn(
                        params, opt_state, device_data, next_values, train_key,
                        jnp.float32(sentinel.lr_scale),
                    )
                    player.params = params_sync.pull(flat_params, player_sync_device)
                    if not timer.disabled:
                        jax.block_until_ready(params)
                train_step += world_size

            if cfg.metric.log_level > 0:
                if aggregator:
                    aggregator.update_from_device(train_metrics)
                if policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters:
                    overlap_s, overlap_steps = stepper.drain_overlap()
                    if overlap_s > 0:
                        sps_overlap = overlap_steps * n_envs * cfg.env.action_repeat / overlap_s
                        if aggregator and "Time/sps_pipeline_overlap" in aggregator:
                            aggregator.update("Time/sps_pipeline_overlap", sps_overlap)
                        else:
                            logger.log_metrics({"Time/sps_pipeline_overlap": sps_overlap}, policy_step)
                    if aggregator and not aggregator.disabled:
                        logger.log_metrics(aggregator.compute(), policy_step)
                        aggregator.reset()
                    if not timer.disabled:
                        timer_metrics = timer.compute()
                        if timer_metrics.get("Time/train_time", 0) > 0:
                            logger.log_metrics(
                                {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                                policy_step,
                            )
                            # MFU from the compiler's own cost model (ppo.py
                            # scheme): per-call FLOPs captured off
                            # cost_analysis() when the fused/split train
                            # executable AOT-compiled
                            _train_gfn = fused_trainer.step_fn if fused_trainer is not None else train_fn
                            _mfu = tel_device.mfu(
                                getattr(_train_gfn, "last_step_flops", None),
                                timer_metrics["Time/train_time"] / max(train_step - last_train, 1),
                                runtime.device,
                            )
                            if _mfu is not None:
                                logger.log_metrics({"Time/mfu": _mfu}, policy_step)
                        if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                            logger.log_metrics(
                                {
                                    "Time/sps_env_interaction": (
                                        (policy_step - last_log) / world_size * cfg.env.action_repeat
                                    )
                                    / timer_metrics["Time/env_interaction_time"]
                                },
                                policy_step,
                            )
                        timer.reset()
                    last_log = policy_step
                    last_train = train_step

            resilience.enforce_nonfinite_policy(ft, train_metrics)
            env_deltas = resilience.drain_env_counters(envs, aggregator)
            jax_compile.drain_compile_counters(aggregator)
            if iter_num == start_iter:
                # everything reachable has compiled once: later traces are drift
                jax_compile.mark_steady()

            # ----- health sentinel: warn -> backoff (lr_scale) -> rollback
            action = sentinel.observe(policy_step, train_metrics=train_metrics, env_counters=env_deltas)
            if action.rollback:
                rb_state = sentinel.take_rollback_state(os.path.join(log_dir, "checkpoint"))
                if rb_state is not None:
                    params = runtime.place_params(
                        jax.tree_util.tree_map(jnp.asarray, rb_state["agent"])
                    )
                    opt_state = runtime.place_params(
                        jax.tree_util.tree_map(jnp.asarray, rb_state["optimizer"])
                    )
                    if "rng" in rb_state:
                        rng = jnp.asarray(rb_state["rng"])
                        player_rng = jax.device_put(
                            jnp.asarray(rb_state["player_rng"]), runtime.player_device
                        )
                    player.params = params_sync.pull(params_sync.ravel(params), player_sync_device)
                    if sentinel.reseed_envs:
                        pending.clear()
                        reset_obs = envs.reset(seed=cfg.seed + iter_num)[0]
                        next_obs = {}
                        for k in obs_keys:
                            next_obs[k] = reset_obs[k]
                            step_data[k] = reset_obs[k][np.newaxis]
                        # the fused sharded step expects its carry back in the
                        # mesh layout after any reset
                        if fused_trainer is not None:
                            fused_trainer.shard_carry()
                    runtime.print(
                        f"Health rollback at policy_step={policy_step}: restored certified "
                        "checkpoint, training continues."
                    )
            sentinel.drain(aggregator)

            if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
                iter_num == total_iters and cfg.checkpoint.save_last
            ):
                last_checkpoint = policy_step
                ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{runtime.global_rank}.ckpt")
                runtime.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=_ckpt_state(),
                    healthy=sentinel.certifiable,
                    policy_step=policy_step,
                )

            guard.completed_iteration()
            if guard.should_stop:
                if last_checkpoint != policy_step:  # periodic save above already covered this step
                    last_checkpoint = policy_step
                    ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{runtime.global_rank}.ckpt")
                    runtime.call(
                        "on_checkpoint_coupled",
                        ckpt_path=ckpt_path,
                        state=_ckpt_state(),
                        healthy=sentinel.certifiable,
                        policy_step=policy_step,
                    )
                runtime.print(
                    f"Preemption ({guard.describe()}) at iteration {iter_num}: emergency "
                    "checkpoint saved, exiting cleanly for resume."
                )
                break

    profiler.close()
    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        if use_ingraph:
            ingraph_envs.test(player, runtime, cfg, log_dir)
        else:
            test(player, runtime, cfg, log_dir)
    if logger:
        logger.finalize()
