from sheeprl_tpu.algos.a2c import a2c  # noqa: F401
from sheeprl_tpu.algos.a2c import evaluate  # noqa: F401
