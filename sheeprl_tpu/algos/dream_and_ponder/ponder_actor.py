"""PonderNet actor for Dream-and-Ponder (reference
sheeprl/algos/dream_and_ponder/ponder_actor.py:29-319 and the `Actor` wrapper at
agent.py:747-1003).

The actor recurrently refines an abstract "goal" representation from the latent
env state until a learned halting unit decides the goal is ready to be decoded
into action logits (PonderNet, Banino et al. 2021).

TPU-first design notes:
- Training mode runs ALL ``max_ponder_steps`` refinements (same as the
  reference) as an unrolled static loop — N is small and static, so XLA fuses
  the whole ponder stack into one program.
- Inference mode replaces the reference's data-dependent early-break +
  active-instance gather/scatter (ponder_actor.py:177-222) with DENSE masked
  compute: every instance runs all N steps and `jnp.where` masks freeze the
  halted ones. On the MXU dense-but-masked beats sparse control flow, and it
  keeps the program shape static for jit.
- The halting distribution puts the leftover mass on the last step
  (ponder_actor.py:96-99), and the geometric prior puts its tail mass there too
  (ponder_actor.py:279-294), so both always sum to 1 over the truncated support.
"""

from __future__ import annotations

import math
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from sheeprl_tpu.algos.dreamer_v3.agent import hafner_trunc_init, hafner_uniform_init
from sheeprl_tpu.models.models import MLP

PRE_SIGMOID_CLAMP = (-7.0, 7.0)


def compute_halting_distribution(halt_probs: jax.Array) -> jax.Array:
    """Convert halting probabilities λ_n to the distribution
    p_n = λ_n * Π_{i<n} (1 - λ_i), with the leftover mass assigned to the last
    step (reference ponder_actor.py:81-100). ``halt_probs``: [..., N]."""
    not_halt = jnp.clip(1.0 - halt_probs, min=1e-7)
    cumprods = jnp.concatenate(
        [jnp.ones_like(not_halt[..., :1]), jnp.cumprod(not_halt[..., :-1], axis=-1)], axis=-1
    )
    p_n = halt_probs * cumprods
    last = jnp.clip(1.0 - p_n[..., :-1].sum(axis=-1, keepdims=True), min=0.0)
    return jnp.concatenate([p_n[..., :-1], last], axis=-1)


def geometric_prior(max_ponder_steps: int, lambda_prior_geom: float) -> np.ndarray:
    """Truncated geometric prior with tail mass at the last step:
    p_G(n) = λ(1-λ)^(n-1) for n < N; p_G(N) = (1-λ)^(N-1)
    (reference ponder_actor.py:279-294)."""
    if not 0.01 <= lambda_prior_geom < 1:
        raise ValueError("lambda_prior_geom must be in [0.01, 1)")
    n = max_ponder_steps
    if n == 1:
        return np.ones((1,), dtype=np.float32)
    base = 1.0 - float(lambda_prior_geom)
    head = float(lambda_prior_geom) * base ** np.arange(n - 1, dtype=np.float32)
    return np.concatenate([head, [base ** (n - 1)]]).astype(np.float32)


def ponder_loss(
    halt_step_task_losses: jax.Array,  # [B, N]
    halt_distribution: jax.Array,  # [B, N]
    prior: jax.Array,  # [N]
    beta: float = 0.01,
) -> jax.Array:
    """PonderNet loss: E_p[L_task] + β * KL(p || p_G)
    (reference ponder_actor.py:243-319)."""
    expected = (halt_step_task_losses * halt_distribution).sum(axis=-1).mean()
    eps = 1e-8
    kl = jnp.log((halt_distribution + eps) / (prior + eps))
    kl_div = (halt_distribution * kl).sum(axis=-1).mean()
    return expected + beta * kl_div


class PonderActor(nn.Module):
    """DV3-style actor with a PonderNet core (reference agent.py:747-1003).

    Exposes two apply methods:
    - ``ponder_train(state)`` -> (pre_dist list of [..., N, dim], halt_probs
      [..., N], halt_distribution [..., N]): computes every ponder step's
      decoded action logits (training mode, reference ponder_actor.py:109-157).
    - ``ponder_infer(state, key)`` -> (pre_dist list of [..., dim], halted_step
      [...]): samples per-instance halting decisions (Bernoulli, or λ>0.5 when
      ``deterministic_inference``), freezes halted instances with masks, and
      decodes only the halted-at goal (reference ponder_actor.py:159-240).

    Carries the same distribution fields as `dreamer_v3.agent.Actor` so
    `dreamer_v3.agent.ActorOutput` can wrap its outputs unchanged.
    """

    latent_state_size: int
    actions_dim: Sequence[int]
    is_continuous: bool
    distribution: str = "auto"
    init_std: float = 2.0
    min_std: float = 0.1
    max_std: float = 1.0
    dense_units: int = 1024
    mlp_layers: int = 5
    layer_norm: bool = True
    layer_norm_eps: float = 1e-3
    activation: str = "silu"
    unimix: float = 0.01
    action_clip: float = 1.0
    max_ponder_steps: int = 4
    cum_halt_prob_threshold: float = 0.9
    deterministic_inference: bool = False
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    def resolved_distribution(self) -> str:
        dist = self.distribution.lower()
        if dist not in ("auto", "normal", "tanh_normal", "discrete", "scaled_normal"):
            raise ValueError(
                "The distribution must be on of: `auto`, `discrete`, `normal`, `tanh_normal` and `scaled_normal`. "
                f"Found: {dist}"
            )
        if dist == "discrete" and self.is_continuous:
            raise ValueError("You have choose a discrete distribution but `is_continuous` is true")
        if dist == "auto":
            dist = "scaled_normal" if self.is_continuous else "discrete"
        return dist

    def setup(self):
        if not 0 < self.cum_halt_prob_threshold <= 1:
            raise ValueError("cum_halt_prob_threshold must be in (0, 1]")
        if self.max_ponder_steps <= 0:
            raise ValueError("max_ponder_steps must be positive")
        mk = dict(
            activation=self.activation,
            layer_norm=self.layer_norm,
            norm_args={"eps": self.layer_norm_eps},
            use_bias=not self.layer_norm,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=hafner_trunc_init,
        )
        # Hidden-depth split mirrors the reference (agent.py:818-847): the goal
        # refiner gets 80% of the layers, the halt unit and decoder 40% each.
        self.goal_ponder_module = MLP(
            input_dims=self.latent_state_size * 2,  # current state + goal
            output_dim=self.latent_state_size,  # refined goal
            hidden_sizes=[self.dense_units] * math.ceil(self.mlp_layers * 0.8),
            **mk,
        )
        self.halt_module = MLP(
            input_dims=self.latent_state_size * 2,  # current state + goal in question
            output_dim=1,  # halt probability logit
            hidden_sizes=[self.dense_units] * math.ceil(self.mlp_layers * 0.4),
            **mk,
        )
        self.action_decoder = MLP(
            input_dims=self.latent_state_size,  # goal
            output_dim=None,
            hidden_sizes=[self.dense_units] * math.ceil(self.mlp_layers * 0.4),
            **mk,
        )
        head_kw = dict(
            dtype=self.dtype, param_dtype=self.param_dtype, kernel_init=hafner_uniform_init(1.0)
        )
        if self.is_continuous:
            self.heads = [nn.Dense(int(np.sum(self.actions_dim)) * 2, name="head_0", **head_kw)]
        else:
            self.heads = [
                nn.Dense(dim, name=f"head_{i}", **head_kw) for i, dim in enumerate(self.actions_dim)
            ]
        self.no_goal_yet = self.param(
            "no_goal_yet", nn.initializers.uniform(scale=1.0), (self.latent_state_size,), self.param_dtype
        )

    def _ponder_step(self, state: jax.Array, goal: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """One refinement: new goal + halting probability (reference :123-136)."""
        new_goal = self.goal_ponder_module(jnp.concatenate([state, goal], axis=-1))
        logit = self.halt_module(jnp.concatenate([state, new_goal], axis=-1))
        logit = jnp.clip(logit, *PRE_SIGMOID_CLAMP)  # avoid vanishing sigmoid grads
        return new_goal, jax.nn.sigmoid(logit)[..., 0]

    def __call__(self, state: jax.Array):
        return self.ponder_train(state)

    def ponder_train(self, state: jax.Array):
        """All-steps forward (training mode). ``state``: [..., L]."""
        goal = jnp.broadcast_to(self.no_goal_yet, state.shape).astype(state.dtype)
        goals: List[jax.Array] = []
        halt_probs: List[jax.Array] = []
        for _ in range(self.max_ponder_steps):
            goal, halt_prob = self._ponder_step(state, goal)
            goals.append(goal)
            halt_probs.append(halt_prob)
        goals_st = jnp.stack(goals, axis=-2)  # [..., N, L]
        halt_probs_st = jnp.stack(halt_probs, axis=-1)  # [..., N]
        halt_distribution = compute_halting_distribution(halt_probs_st)
        feats = self.action_decoder(goals_st)  # [..., N, dense]
        pre_dist = [head(feats) for head in self.heads]  # each [..., N, dim]
        return pre_dist, halt_probs_st, halt_distribution

    def ponder_infer(self, state: jax.Array, key: jax.Array):
        """Masked halting forward (inference mode). ``state``: [..., L]."""
        batch_shape = state.shape[:-1]
        goal = jnp.broadcast_to(self.no_goal_yet, state.shape).astype(state.dtype)
        has_halted = jnp.zeros(batch_shape, dtype=bool)
        halted_goal = jnp.zeros_like(state)
        halted_step = jnp.zeros(batch_shape, dtype=jnp.int32)
        cum_halt_prob = jnp.zeros(batch_shape, dtype=jnp.float32)
        for step in range(self.max_ponder_steps):
            goal, halt_prob = self._ponder_step(state, goal)
            if self.deterministic_inference:
                decision = halt_prob > 0.5
            else:
                decision = jax.random.bernoulli(jax.random.fold_in(key, step), halt_prob.astype(jnp.float32))
            new_halts = decision & ~has_halted
            halted_goal = jnp.where(new_halts[..., None], goal, halted_goal)
            halted_step = jnp.where(new_halts, step + 1, halted_step)
            has_halted = has_halted | decision
            # Accumulate λ for still-active instances; force-halt past the threshold
            cum_halt_prob = cum_halt_prob + halt_prob.astype(jnp.float32) * (~has_halted)
            threshold_halts = (cum_halt_prob >= self.cum_halt_prob_threshold) & ~has_halted
            halted_goal = jnp.where(threshold_halts[..., None], goal, halted_goal)
            halted_step = jnp.where(threshold_halts, step + 1, halted_step)
            has_halted = has_halted | threshold_halts
        # Instances that never halted take the final goal (reference :224-228)
        halted_goal = jnp.where(has_halted[..., None], halted_goal, goal)
        halted_step = jnp.where(has_halted, halted_step, self.max_ponder_steps)
        feats = self.action_decoder(halted_goal)
        pre_dist = [head(feats) for head in self.heads]  # each [..., dim]
        return pre_dist, halted_step


# Exposed for config-driven class selection (reference configs point at
# sheeprl.algos.dream_and_ponder.agent.Actor).
Actor = PonderActor
