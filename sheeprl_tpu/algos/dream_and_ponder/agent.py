"""Dream-and-Ponder agent: the full DreamerV3 world model + critic with the
actor replaced by a PonderNet actor (reference
sheeprl/algos/dream_and_ponder/agent.py:1104-1422).

The world model, critic, and player plumbing are DV3's; only the actor (and how
the player queries it — inference-mode pondering needs a PRNG for the halting
decisions) differs.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import gymnasium
import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dream_and_ponder.ponder_actor import PonderActor
from sheeprl_tpu.algos.dreamer_v3.agent import (
    ActorOutput,
    DV3Modules,
    PlayerDV3,
    _ln_enabled,
    build_agent as dv3_build_agent,
)

# Exposed for config-driven class selection (reference agent.py:747).
Actor = PonderActor


class PlayerDAP(PlayerDV3):
    """DV3 player whose per-step actor call runs inference-mode pondering.

    Reference PlayerDV3.get_actions (agent.py:710-744) sets
    ``actor.training = False`` so the ponder actor early-halts; here the halting
    decisions are explicit Bernoulli draws keyed off the step PRNG.
    """

    def _actor_step(self, actor_params, latent, key, greedy: bool = False, mask=None):
        del mask  # no masked (MineDojo) variant of the ponder actor (reference agent.py:1006-1024)
        k_halt, k_act = jax.random.split(key)
        pre_dist, _ = self.actor.apply(actor_params, latent, k_halt, method=PonderActor.ponder_infer)
        out = ActorOutput(self.actor, pre_dist)
        return out.sample_actions(k_act, greedy=greedy)


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Dict[str, Any]] = None,
    actor_state: Optional[Dict[str, Any]] = None,
    critic_state: Optional[Dict[str, Any]] = None,
    target_critic_state: Optional[Dict[str, Any]] = None,
) -> Tuple[DV3Modules, Dict[str, Any], PlayerDAP]:
    """Build module defs + init params (reference agent.py:1104-1422).

    Returns (modules, params, player); ``params`` keys match DreamerV3's
    (world_model/actor/critic/target_critic) so checkpoints and the model
    manager share the DV3 layout.
    """
    world_model_cfg = cfg.algo.world_model
    actor_cfg = cfg.algo.actor
    ponder_cfg = cfg.algo.ponder
    stochastic_size = int(world_model_cfg.stochastic_size) * int(world_model_cfg.discrete_size)
    recurrent_state_size = int(world_model_cfg.recurrent_model.recurrent_state_size)
    latent_state_size = stochastic_size + recurrent_state_size

    dv3_modules, dv3_params, _ = dv3_build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        world_model_state,
        None,
        critic_state,
        target_critic_state,
        build_actor=False,
    )

    actor_ln, actor_eps = _ln_enabled(actor_cfg.get("layer_norm"))
    actor = PonderActor(
        latent_state_size=latent_state_size,
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=cfg.distribution.get("type", "auto"),
        init_std=float(actor_cfg.init_std),
        min_std=float(actor_cfg.min_std),
        max_std=float(actor_cfg.get("max_std", 1.0)),
        dense_units=int(actor_cfg.dense_units),
        mlp_layers=int(actor_cfg.mlp_layers),
        layer_norm=actor_ln,
        layer_norm_eps=actor_eps,
        activation=actor_cfg.dense_act,
        unimix=float(cfg.algo.unimix),
        action_clip=float(actor_cfg.get("action_clip", 1.0)),
        max_ponder_steps=int(ponder_cfg.max_ponder_steps),
        cum_halt_prob_threshold=float(ponder_cfg.cum_halt_prob_threshold),
        deterministic_inference=bool(ponder_cfg.get("deterministic_inference", False)),
        dtype=runtime.compute_dtype,
    )
    actor_params = actor.init(jax.random.PRNGKey(cfg.seed + 2), jnp.zeros((1, latent_state_size)))
    if actor_state:
        actor_params = jax.tree_util.tree_map(jnp.asarray, actor_state)

    modules = DV3Modules(
        encoder=dv3_modules.encoder,
        rssm=dv3_modules.rssm,
        observation_model=dv3_modules.observation_model,
        reward_model=dv3_modules.reward_model,
        continue_model=dv3_modules.continue_model,
        actor=actor,
        critic=dv3_modules.critic,
    )
    params = {
        "world_model": dv3_params["world_model"],
        "actor": actor_params,
        "critic": dv3_params["critic"],
        "target_critic": dv3_params["target_critic"],
    }

    player = PlayerDAP(
        encoder=dv3_modules.encoder,
        rssm=dv3_modules.rssm,
        actor=actor,
        actions_dim=actions_dim,
        num_envs=cfg.env.num_envs,
        stochastic_size=int(world_model_cfg.stochastic_size),
        recurrent_state_size=recurrent_state_size,
        discrete_size=int(world_model_cfg.discrete_size),
    )
    player.wm_params = params["world_model"]
    player.actor_params = actor_params
    return modules, params, player
