from sheeprl_tpu.algos.dream_and_ponder import dream_and_ponder  # noqa: F401
from sheeprl_tpu.algos.dream_and_ponder import evaluate  # noqa: F401  (must import after the algorithm registers)
