"""Dream-and-Ponder utilities (reference sheeprl/algos/dream_and_ponder/utils.py)."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs, test  # noqa: F401

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "State/expected_ponder_steps",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}
# Compilation-management counters (core/compile.py), drained once per iteration.
AGGREGATOR_KEYS |= {
    "Compile/retraces",
    "Compile/cache_hits",
    "Compile/cache_misses",
    "Time/compile_seconds",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic", "moments"}


def log_models_from_checkpoint(runtime, env, cfg, state) -> Dict[str, Any]:
    """Register Dream-and-Ponder models from a checkpoint (reference utils.py:120-254)."""
    import gymnasium as gym

    from sheeprl_tpu.algos.dream_and_ponder.agent import build_agent
    from sheeprl_tpu.utils.model_manager import log_model

    is_continuous = isinstance(env.action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(env.action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        env.action_space.shape
        if is_continuous
        else (env.action_space.nvec.tolist() if is_multidiscrete else [env.action_space.n])
    )
    _, params, _ = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        env.observation_space,
        state["world_model"],
        state["actor"],
        state["critic"],
        state["target_critic"],
    )
    info = {}
    for name in ("world_model", "actor", "critic", "target_critic"):
        info[name] = log_model(runtime, cfg, name, params[name])
    info["moments"] = log_model(runtime, cfg, "moments", state.get("moments"))
    return info
