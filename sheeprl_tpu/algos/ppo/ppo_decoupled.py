"""PPO, decoupled actor-learner (reference sheeprl/algos/ppo/ppo_decoupled.py:33-670).

Role split on the device mesh (see sheeprl_tpu/parallel/decoupled.py): device 0
is the PLAYER (policy forwards for env stepping run on their own chip), devices
1..N-1 are the TRAINERS (the jitted PPO optimization phase data-shards its
minibatches over the trainer mesh; XLA's all-reduce over ICI is the reference's
DDP ``optimization_pg``). Per round the player ships the full rollout to the
trainer role and blocks for the refreshed parameters — the same synchronous
scatter -> train -> broadcast cycle as the reference (:294-310), with
``jax.device_put`` replacing both the object scatter and the flattened-vector
parameter broadcast.

Per-rank semantics: ``per_rank_batch_size`` applies per TRAINER device, so the
global minibatch is ``per_rank_batch_size * (num_devices - 1)`` — matching the
reference where only ranks 1..N-1 optimize (:497-548).

Multi-process worlds (``fabric.multihost=True`` under a multi-host launcher,
the reference's multi-node ``sheeprl exp=ppo_decoupled`` case, :623-670) take
the CROSS-HOST path automatically: the role split spans the GLOBAL device set
(process 0's first chip plays, every other chip in the world trains), rollouts
ride one device broadcast collective to the cross-process trainer mesh, and the
trainer processes join every round with zero templates shaped by a one-time
spec exchange over the coordinator KV store (see
sheeprl_tpu/parallel/decoupled.py:CrossHostTransport).
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.algos.ppo.agent import build_agent
from sheeprl_tpu.algos.ppo.ppo import make_train_fn
from sheeprl_tpu.algos.ppo.utils import prepare_obs, test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.core import failpoints
from sheeprl_tpu.core import health as health_mod
from sheeprl_tpu.core import resilience
from sheeprl_tpu.core.pipeline import AsyncEnvStepper, PackedObsCodec, pipeline_enabled
from sheeprl_tpu.data.factory import make_rollout_buffer
from sheeprl_tpu.parallel import handoff, overlap, split_runtime, split_runtime_crosshost
from sheeprl_tpu.utils.env import finished_episodes, make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.optim import with_clipping
from sheeprl_tpu.utils.profiler import TraceProfiler
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import polynomial_decay, save_configs


@register_algorithm(decoupled=True)
def main(runtime, cfg: Dict[str, Any]):
    if "minedojo" in cfg.env.wrapper._target_.lower():
        raise ValueError(
            "MineDojo is not currently supported by PPO agent, since it does not take "
            "into consideration the action masks provided by the environment, but needed "
            "in order to play correctly the game. "
            "As an alternative you can use one of the Dreamers' agents."
        )
    # Multi-process world -> the cross-host role split; single controller -> the
    # local device split (reference: one code path, group membership decides,
    # ppo_decoupled.py:645-666).
    if jax.process_count() > 1:
        player_rt, trainer_rt, transport = split_runtime_crosshost(runtime)
    else:
        player_rt, trainer_rt = split_runtime(runtime)
        transport = None
    is_player = transport is None or transport.is_player_process
    trainer_world = trainer_rt.world_size
    initial_ent_coef = float(cfg.algo.ent_coef)
    initial_clip_coef = float(cfg.algo.clip_coef)

    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_state

        state = load_state(cfg.checkpoint.resume_from)

    logger = get_logger(runtime, cfg)
    if logger:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    ft = resilience.resolve(cfg)
    # Warn-only sentinel: the decoupled PPO split keeps the optimizer state on
    # the trainer role, so an in-loop rollback would need a cross-role restore
    # protocol; detection + certification still run so operators get the signal
    # and certified checkpoints for a manual (or resume-time) rollback.
    sentinel = health_mod.HealthSentinel(
        cfg,
        log_dir=log_dir if runtime.is_global_zero else None,
        world_size=runtime.world_size,
        supports=("warn",),
    )
    if transport is not None:
        transport.set_scope(log_dir)  # run-scope the KV spec exchange (coordinator store outlives runs)
        transport.configure_faults(
            op_timeout_ms=ft.transport.op_timeout_ms,
            retries=ft.transport.retries,
            backoff_base_s=ft.transport.backoff_base_s,
            backoff_max_s=ft.transport.backoff_max_s,
        )
        if cfg.checkpoint.resume_from:
            # every process loaded its own copy of the checkpoint: verify they
            # are the SAME file before any of its state drives a collective
            transport.verify_resume_digest(cfg.checkpoint.resume_from)
    runtime.logger = logger
    runtime.print(f"Log dir: {log_dir}")
    runtime.print(
        f"Decoupled PPO: player on {player_rt.mesh.devices.ravel()[0]}, "
        f"{trainer_world} trainer device(s)"
    )

    # The player drives num_envs envs (reference player, ppo_decoupled.py:56-70);
    # trainer processes probe ONE env for the spaces build_agent needs (the
    # reference ships agent_args to trainers via object broadcast, :114-117)
    n_envs = cfg.env.num_envs
    if is_player:
        envs = resilience.make_supervised_env(
            [
                make_env(cfg, cfg.seed + i, 0, log_dir if runtime.is_global_zero else None, "train", vector_env_idx=i)
                for i in range(n_envs)
            ],
            sync=cfg.env.sync_env,
            ft=ft,
        )
        observation_space = envs.single_observation_space
        action_space = envs.single_action_space
    else:
        envs = None
        probe_env = make_env(cfg, cfg.seed, 0, None, "train", vector_env_idx=0)()
        observation_space = probe_env.observation_space
        action_space = probe_env.action_space
        probe_env.close()
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if cfg.algo.cnn_keys.encoder + cfg.algo.mlp_keys.encoder == []:
        raise RuntimeError(
            "You should specify at least one CNN keys or MLP keys from the cli: "
            "`cnn_keys.encoder=[rgb]` or `mlp_keys.encoder=[state]`"
        )
    obs_keys = cfg.algo.cnn_keys.encoder + cfg.algo.mlp_keys.encoder
    cnn_keys = cfg.algo.cnn_keys.encoder

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)

    # Trainer-side agent/optimizer (params replicated over the trainer mesh);
    # the player keeps its own copy on the player device (reference :114-127:
    # the player receives the initial weights from trainer rank-1).
    agent, params, player = build_agent(
        trainer_rt, actions_dim, is_continuous, cfg, observation_space, state["agent"] if state else None
    )
    if transport is None:
        player.params = player_rt.replicate(params)
    elif is_player:
        # initial refresh: local D2D put of this process's replica onto the player
        # chip (reference :126-127, the player receives the weights from rank-1)
        player.params = transport.params_to_player(params)

    policy_steps_per_iter = int(n_envs * cfg.algo.rollout_steps)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    n_data = cfg.algo.rollout_steps * n_envs
    global_bs = int(cfg.algo.per_rank_batch_size) * trainer_world
    updates_per_iter = int(cfg.algo.update_epochs) * max(n_data // global_bs, 1)
    optim_kwargs = dict(cfg.algo.optimizer)
    if cfg.algo.anneal_lr:
        lr0 = optim_kwargs.pop("lr", 1e-3)
        optim_kwargs["lr"] = optax.linear_schedule(lr0, 0.0, total_iters * updates_per_iter)
    tx = with_clipping(instantiate(optim_kwargs)(), cfg.algo.max_grad_norm)
    opt_state = tx.init(params)
    if state:
        opt_state = jax.tree_util.tree_map(jnp.asarray, state["optimizer"])
    # strategy-aware placement: replicated under DDP, parameter-sharded over the
    # trainer mesh under fabric.strategy=fsdp (core/runtime.py:place_params)
    opt_state = trainer_rt.place_params(opt_state)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    if cfg.buffer.size < cfg.algo.rollout_steps:
        raise ValueError(
            f"The size of the buffer ({cfg.buffer.size}) cannot be lower "
            f"than the rollout steps ({cfg.algo.rollout_steps})"
        )
    # device backend: the rollout lives on the player CHIP (player_rt places the
    # player on its own device in the decoupled split), so the trainer handoff
    # below is a direct chip->mesh device_put
    rb = make_rollout_buffer(cfg, player_rt, n_envs, obs_keys, log_dir) if is_player else None
    device_rollout = is_player and getattr(rb, "backend", "host") == "device"

    last_train = 0
    train_step = 0
    start_iter = state["iter_num"] + 1 if state else 1
    policy_step = state["iter_num"] * policy_steps_per_iter if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    if state:
        cfg.algo.per_rank_batch_size = state["batch_size"] // trainer_world

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    # ---- trainer role: the whole optimization phase (GAE + epochs x minibatches)
    # compiled once over the trainer mesh. The rollout handoff below assembles
    # the batch PRE-SHARDED on the mesh and never aliases a caller-visible
    # buffer, so the train fn can donate it (donate_data=True) on top of the
    # usual params/opt_state carry donation.
    train_fn = make_train_fn(agent, tx, cfg, trainer_rt, n_data, obs_keys, cnn_keys, donate_data=True)
    trainer_state = {"params": params, "opt_state": opt_state}

    def trainer_step(payload):
        # Per-shard handoff onto the trainer mesh (parallel/handoff.py): each
        # trainer device receives ONE put of only its [T, B/n] env block — no
        # full-rollout replication, no post-put reshard. The scalar riders
        # (bootstrap values, key, coefs, stop flag) stay replicated; the
        # per-minibatch sharding constraint inside train_fn keeps the global
        # permutation semantics (like the reference's DistributedSampler over
        # the scattered chunks). Cross-host: one broadcast collective replaces
        # the reference's pickled object scatter (ppo_decoupled.py:294-299).
        if transport is None:
            host_data, rest = payload[0], payload[1:]
            device_data = handoff.shard_put(host_data, trainer_rt.mesh, batch_axis=1)
            next_values, train_key, clip_coef, ent_coef, stop_flag = trainer_rt.replicate(rest)
        else:
            device_data, next_values, train_key, clip_coef, ent_coef, stop_flag = (
                transport.rollout_to_trainers(payload)
            )
        train_key = jnp.asarray(train_key).astype(jnp.uint32)
        # chaos seam for the gradient-sync dispatch (the decoupled twin of the
        # coupled loop's train.grad_sync site)
        failpoints.failpoint("train.grad_sync", microbatches=overlap.microbatches(cfg))
        new_params, new_opt, _flat, metrics = train_fn(
            trainer_state["params"], trainer_state["opt_state"], device_data, next_values, train_key,
            # the decoupled sentinel is warn-only (no backoff rung), so the
            # traced LR-scale operand is the constant healthy value
            clip_coef, ent_coef, jnp.float32(1.0),
        )
        trainer_state["params"] = new_params
        trainer_state["opt_state"] = new_opt
        # Parameter refresh for the player: direct device-to-device resharding
        # (reference :550-554 does a flattened-vector NCCL broadcast); cross-host
        # it is a LOCAL put of the player process's own replica (None elsewhere).
        if transport is None:
            player_params = jax.device_put(new_params, player_rt.replicated)
        else:
            player_params = transport.params_to_player(new_params)
        return player_params, metrics, stop_flag

    profiler = TraceProfiler(cfg.metric.get("profiler"), log_dir if runtime.is_global_zero else None)
    rng = jax.random.PRNGKey(cfg.seed)
    if state and "rng" in state:
        # restore the exact key chain so a preempted run resumes where it left off
        rng = jnp.asarray(state["rng"])
    step_data = {}
    stepper = codec = None
    pending: Dict[str, Any] = {}
    if is_player:
        reset_obs = envs.reset(seed=cfg.seed)[0]
        next_obs = {}
        for k in obs_keys:
            _obs = reset_obs[k]
            if k in cnn_keys:
                _obs = _obs.reshape(n_envs, -1, *_obs.shape[-2:])
            next_obs[k] = _obs
            step_data[k] = _obs[np.newaxis]
        # ----- software pipeline (core/pipeline.py): same structure as ppo.py,
        # player role only — trainer processes never touch envs
        stepper = AsyncEnvStepper(envs, enabled=pipeline_enabled(cfg))
        codec = PackedObsCodec(cnn_keys=cnn_keys, device=player_rt.player_device)
    zero_extra = {
        "rewards": np.zeros((n_envs, 1), np.float32),
        "dones": np.zeros((n_envs, 1), np.float32),
    }

    def _process_pending(cur_packed):
        """Close out the previous step while the env workers run (see ppo.py)."""
        if not pending:
            return
        if device_rollout:
            if cur_packed is not None:
                extra_packed, extra_only = cur_packed, False
            else:
                extra_packed, extra_only = (
                    codec.encode_extra_only(
                        {"rewards": pending["rewards"], "dones": pending["dones"]}
                    ),
                    True,
                )
            rb.add_env_packed(codec, pending["packed"], extra_packed, extra_only=extra_only)
        else:
            rewards = pending["rewards"]
            step_data["dones"] = pending["dones"][np.newaxis]
            step_data["values"] = np.asarray(pending["values"])[np.newaxis]
            step_data["actions"] = np.asarray(pending["cat_actions"])[np.newaxis]
            step_data["logprobs"] = np.asarray(pending["logprobs"])[np.newaxis]
            step_data["rewards"] = rewards[np.newaxis]
            if cfg.buffer.memmap:
                step_data["returns"] = np.zeros_like(rewards, shape=(1, *rewards.shape))
                step_data["advantages"] = np.zeros_like(rewards, shape=(1, *rewards.shape))
            rb.add(step_data, validate_args=cfg.buffer.validate_args)
            for k in obs_keys:
                step_data[k] = next_obs[k][np.newaxis]
        if cfg.metric.log_level > 0:
            for i, (ep_rew, ep_len) in enumerate(finished_episodes(pending["info"])):
                if aggregator and "Rewards/rew_avg" in aggregator:
                    aggregator.update("Rewards/rew_avg", ep_rew)
                if aggregator and "Game/ep_len_avg" in aggregator:
                    aggregator.update("Game/ep_len_avg", ep_len)
                runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")
        pending.clear()

    def _ckpt_state():
        pull = jax.device_get if transport is None else transport.pull_replicated
        return {
            "agent": pull(trainer_state["params"]),
            "optimizer": pull(trainer_state["opt_state"]),
            "iter_num": iter_num,
            "batch_size": cfg.algo.per_rank_batch_size * trainer_world,
            "last_log": last_log,
            "last_checkpoint": last_checkpoint,
            "rng": jax.device_get(rng),
        }

    guard = resilience.PreemptionGuard(
        enabled=ft.preemption.enabled, stop_after_iters=ft.preemption.stop_after_iters
    )
    with guard:
        for iter_num in range(start_iter, total_iters + 1):
            profiler.step(policy_step)
            # Only the player process steps envs; trainer processes skip straight
            # to the training collective (their policy_step advances below so the
            # anneal/bookkeeping arithmetic stays in lockstep with the player).
            for _ in (range(cfg.algo.rollout_steps) if is_player else ()):
                policy_step += n_envs

                with timer("Time/env_interaction_time", SumMetric()):
                    # ONE packed host->device transfer per step (see
                    # PPOPlayer.act_packed and core/pipeline.PackedObsCodec)
                    packed = codec.encode(
                        next_obs,
                        extra={"rewards": pending["rewards"], "dones": pending["dones"]}
                        if pending
                        else zero_extra,
                    )
                    cat_actions, env_actions, logprobs, values, rng = player.act_packed(
                        codec, packed, rng
                    )
                    # the one unavoidable per-step device->host sync: env actions
                    real_actions = np.asarray(env_actions)
                    stepper.step_async(real_actions.reshape(envs.action_space.shape))

                    # ---- overlap window: env workers are stepping
                    _process_pending(packed)
                    if device_rollout:
                        # in-graph scatter on the player chip: no host pull of
                        # values/logprobs/actions
                        rb.add_policy({"actions": cat_actions, "logprobs": logprobs, "values": values})

                    obs, rewards, terminated, truncated, info = stepper.step_wait()
                    truncated_envs = np.nonzero(truncated)[0]
                    if len(truncated_envs) > 0 and "final_obs" in info:
                        final_obs_arr = np.asarray(info["final_obs"], dtype=object)
                        real_next_obs = {k: [] for k in obs_keys}
                        valid_idx = []
                        for te in truncated_envs:
                            fo = final_obs_arr[te]
                            if fo is None:
                                continue
                            valid_idx.append(te)
                            for k in obs_keys:
                                v = np.asarray(fo[k], dtype=np.float32)
                                if k in cnn_keys:
                                    v = v.reshape(-1, *v.shape[-2:]) / 255.0 - 0.5
                                real_next_obs[k].append(v)
                        if valid_idx:
                            stacked = {k: jnp.asarray(np.stack(v)) for k, v in real_next_obs.items()}
                            vals = np.asarray(player.get_values(stacked)).reshape(len(valid_idx))
                            rewards = np.asarray(rewards, dtype=np.float32)
                            rewards[valid_idx] += cfg.algo.gamma * vals
                    dones = np.logical_or(terminated, truncated).reshape(n_envs, -1).astype(np.uint8)
                    rewards = clip_rewards_fn(np.asarray(rewards, dtype=np.float32)).reshape(n_envs, -1)

                # env products become the next step's pending work: the row
                # write and episode accounting run in the NEXT overlap window
                pending.update(
                    packed=packed,
                    rewards=rewards,
                    dones=dones,
                    info=info,
                    values=values,
                    cat_actions=cat_actions,
                    logprobs=logprobs,
                )

                next_obs = {}
                for k in obs_keys:
                    _obs = obs[k]
                    if k in cnn_keys:
                        _obs = _obs.reshape(n_envs, -1, *_obs.shape[-2:])
                    next_obs[k] = _obs

            if is_player:
                with timer("Time/env_interaction_time", SumMetric()):
                    # flush: the rollout's last row has no next act transfer to ride
                    _process_pending(None)

            # ---- ship the rollout to the trainer role, block for new params
            # (the reference's scatter_object_list + params broadcast round)
            if not is_player:
                policy_step += policy_steps_per_iter
            elif not device_rollout:
                local_data = rb.to_arrays(dtype=np.float32)
                if cfg.buffer.size > cfg.algo.rollout_steps:
                    idx = np.arange(rb._pos - cfg.algo.rollout_steps, rb._pos) % cfg.buffer.size
                    local_data = {k: v[idx] for k, v in local_data.items()}
            with timer("Time/train_time", SumMetric()):
                if is_player:
                    jax_obs = prepare_obs(player_rt, next_obs, cnn_keys=cnn_keys, num_envs=n_envs)
                    if device_rollout and transport is None:
                        # the HBM rollout feeds trainer_step's replicate as-is:
                        # a direct player-chip -> trainer-mesh device_put, the
                        # host never sees the [T, B] arrays
                        host_data = rb.rollout()
                        next_values = player.get_values(jax_obs)
                    else:
                        if device_rollout:
                            # cross-host: the broadcast collective needs host
                            # numpy, so de-layout the rollout in ONE bulk pull
                            local_data = rb.rollout_host()
                        next_values = np.asarray(player.get_values(jax_obs))
                        host_data = {k: v for k, v in local_data.items() if k not in ("returns", "advantages")}
                    if transport is not None:
                        transport.sync_payload_spec("ppo_rollout", {**host_data, "__next_values__": next_values})
                else:
                    # trainer processes join the broadcast with zero templates
                    # shaped by the player's one-time payload spec
                    transport.sync_payload_spec("ppo_rollout")
                    flat = transport.zeros_payload("ppo_rollout")
                    next_values = flat.pop("__next_values__")
                    host_data = flat
                rng, train_key = jax.random.split(rng)
                # The player's preemption flag rides the payload broadcast, so every
                # process agrees on the SAME final iteration (a unilateral break would
                # desync the next collective). Trainer-process signals are not watched:
                # fleet preemption delivers SIGTERM to process 0 too, and its next
                # broadcast carries the stop.
                stop_agreed = guard.stop_at_iteration_end()
                player_params, train_metrics, stop_flag = trainer_step(
                    (host_data, next_values, np.asarray(train_key),
                     np.float32(cfg.algo.clip_coef), np.float32(cfg.algo.ent_coef),
                     np.float32(stop_agreed))
                )
                if is_player:
                    if not timer.disabled:  # sync only when the train phase is being timed
                        jax.block_until_ready(player_params)
                    player.params = player_params
                else:
                    stop_agreed = bool(np.asarray(stop_flag))
            train_step += trainer_world

            if ft.nonfinite.policy == "halt":
                resilience.enforce_nonfinite_policy(
                    ft, transport.pull_replicated(train_metrics) if transport is not None else train_metrics
                )
            env_deltas = resilience.drain_env_counters(envs, aggregator)
            jax_compile.drain_compile_counters(aggregator)
            if transport is not None:  # KV retries / stale-epoch rejects / heartbeats into the same stream
                env_deltas.update(resilience.drain_env_counters(transport, aggregator))

            if is_player:
                # ----- health sentinel (warn-only in the decoupled split)
                sentinel.observe(
                    policy_step,
                    train_metrics=(
                        (transport.pull_replicated(train_metrics) if transport is not None else train_metrics)
                        if "train_metrics" in dir()
                        else None
                    ),
                    env_counters=env_deltas,
                )
                sentinel.drain(aggregator)

            if is_player and cfg.metric.log_level > 0:
                if aggregator:
                    aggregator.update_from_device(
                        transport.pull_replicated(train_metrics) if transport is not None else train_metrics
                    )
                logger.log_metrics(
                    {"Info/clip_coef": cfg.algo.clip_coef, "Info/ent_coef": cfg.algo.ent_coef}, policy_step
                )
                if policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters:
                    overlap_s, overlap_steps = stepper.drain_overlap()
                    if overlap_s > 0:
                        sps_overlap = overlap_steps * n_envs * cfg.env.action_repeat / overlap_s
                        if aggregator and "Time/sps_pipeline_overlap" in aggregator:
                            aggregator.update("Time/sps_pipeline_overlap", sps_overlap)
                        else:
                            logger.log_metrics({"Time/sps_pipeline_overlap": sps_overlap}, policy_step)
                    if aggregator and not aggregator.disabled:
                        logger.log_metrics(aggregator.compute(), policy_step)
                        aggregator.reset()
                    if not timer.disabled:
                        timer_metrics = timer.compute()
                        if timer_metrics.get("Time/train_time", 0) > 0:
                            logger.log_metrics(
                                {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                                policy_step,
                            )
                        if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                            logger.log_metrics(
                                {
                                    "Time/sps_env_interaction": (
                                        (policy_step - last_log) * cfg.env.action_repeat
                                    )
                                    / timer_metrics["Time/env_interaction_time"]
                                },
                                policy_step,
                            )
                        timer.reset()
                    last_log = policy_step
                    last_train = train_step

            if cfg.algo.anneal_clip_coef:
                cfg.algo.clip_coef = polynomial_decay(
                    iter_num, initial=initial_clip_coef, final=0.0, max_decay_steps=total_iters, power=1.0
                )
            if cfg.algo.anneal_ent_coef:
                cfg.algo.ent_coef = polynomial_decay(
                    iter_num, initial=initial_ent_coef, final=0.0, max_decay_steps=total_iters, power=1.0
                )

            if is_player and (
                (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
                or (iter_num == total_iters and cfg.checkpoint.save_last)
            ):
                last_checkpoint = policy_step
                ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{runtime.global_rank}.ckpt")
                runtime.call(
                    "on_checkpoint_player", ckpt_path=ckpt_path, state=_ckpt_state(),
                    healthy=sentinel.certifiable, policy_step=policy_step,
                )

            guard.completed_iteration()
            if stop_agreed if transport is not None else guard.should_stop:
                if is_player and last_checkpoint != policy_step:
                    last_checkpoint = policy_step
                    ckpt_path = os.path.join(
                        log_dir, f"checkpoint/ckpt_{policy_step}_{runtime.global_rank}.ckpt"
                    )
                    runtime.call(
                    "on_checkpoint_player", ckpt_path=ckpt_path, state=_ckpt_state(),
                    healthy=sentinel.certifiable, policy_step=policy_step,
                )
                runtime.print(
                    f"Preemption ({guard.describe()}) at iteration {iter_num}: emergency "
                    "checkpoint saved, exiting cleanly for resume."
                )
                break

    profiler.close()
    if envs is not None:
        envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        test(player, player_rt, cfg, log_dir)
    if transport is not None:
        runtime.barrier()  # leave the distributed world together
    if logger:
        logger.finalize()
