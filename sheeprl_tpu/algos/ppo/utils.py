"""PPO utilities: obs preparation, test loop, registry contracts.

Reference: sheeprl/algos/ppo/utils.py (AGGREGATOR_KEYS :21, MODELS_TO_REGISTER :22,
prepare_obs :25, test :39, normalize_obs, log_models).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/entropy_loss",
    "Resilience/env_restarts",
    "Resilience/env_timeouts",
    "Resilience/nonfinite_skips",
}
# Compilation-management counters (core/compile.py), drained once per iteration.
AGGREGATOR_KEYS |= {
    "Compile/retraces",
    "Compile/cache_hits",
    "Compile/cache_misses",
    "Time/compile_seconds",
}
# Host control-plane counters (parallel/control.py), drained by the decoupled loop.
from sheeprl_tpu.parallel.control import COUNTER_KEYS as _CONTROL_COUNTER_KEYS  # noqa: E402

AGGREGATOR_KEYS |= set(_CONTROL_COUNTER_KEYS)
MODELS_TO_REGISTER = {"agent"}


def normalize_obs(
    obs: Dict[str, jax.Array], cnn_keys: Sequence[str], obs_keys: Sequence[str]
) -> Dict[str, jax.Array]:
    """uint8 pixels -> [-0.5, 0.5] floats; mlp keys pass through as f32."""
    out = {}
    for k in obs_keys:
        v = jnp.asarray(obs[k], dtype=jnp.float32)
        out[k] = v / 255.0 - 0.5 if k in cnn_keys else v
    return out


def prepare_obs(
    runtime, obs: Dict[str, np.ndarray], cnn_keys: Sequence[str] = [], num_envs: int = 1, **kwargs
) -> Dict[str, jax.Array]:
    """Host obs dict -> normalized device arrays [num_envs, ...]; frame-stacked cnn
    keys collapse the stack into channels (reference utils.py:25-36)."""
    device = runtime.player_device if runtime is not None else None
    out = {}
    for k, v in obs.items():
        arr = np.asarray(v, dtype=np.float32)
        if k in cnn_keys:
            arr = arr.reshape(num_envs, -1, *arr.shape[-2:])
            arr = arr / 255.0 - 0.5
        else:
            arr = arr.reshape(num_envs, -1)
        out[k] = jax.device_put(arr, device) if device is not None else jnp.asarray(arr)
    return out


def test(player, runtime, cfg, log_dir: str) -> None:
    """Greedy evaluation episode (reference utils.py:39-66)."""
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=cfg.seed)[0]
    key = jax.random.PRNGKey(cfg.seed)
    while not done:
        jax_obs = prepare_obs(runtime, obs, cnn_keys=cfg.algo.cnn_keys.encoder)
        env_actions, key = player.get_actions(jax_obs, key, greedy=True)
        real_actions = np.asarray(env_actions)[0]
        obs, reward, terminated, truncated, _ = env.step(
            np.asarray(real_actions).reshape(env.action_space.shape)
        )
        done = terminated or truncated
        cumulative_rew += reward
        if cfg.dry_run:
            done = True
    if cfg.metric.log_level > 0:
        runtime.print(f"Test - Reward: {cumulative_rew}")
        if hasattr(runtime, "logger") and runtime.logger is not None:
            runtime.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()

# Single-'agent' registration shared with the other model-free algos.
from sheeprl_tpu.utils.model_manager import log_agent_from_checkpoint as log_models_from_checkpoint  # noqa: E402, F401
