"""PPO agent (flax): shared MultiEncoder + actor heads + critic.

Parity with reference sheeprl/algos/ppo/agent.py (PPOAgent :91, PPOPlayer :242,
build_agent :325). JAX design: the module returns raw actor outputs + values; all
distribution math (sampling / log-prob / entropy) lives in pure functions so the same
module serves the jitted train step and the rollout player without DDP/single-device
twin modules.
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.models.models import MLP, MultiEncoder, NatureCNN
from sheeprl_tpu.ops.distributions import Independent, Normal, OneHotCategorical
from sheeprl_tpu.utils.utils import host_float32, safeatanh, safetanh


class CNNEncoder(nn.Module):
    in_channels: int
    features_dim: int
    screen_size: int
    keys: Sequence[str]
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-3)
        return NatureCNN(
            in_channels=self.in_channels,
            features_dim=self.features_dim,
            screen_size=self.screen_size,
            dtype=self.dtype,
        )(x)


class MLPEncoder(nn.Module):
    input_dim: int
    features_dim: Optional[int]
    keys: Sequence[str]
    dense_units: int = 64
    mlp_layers: int = 2
    dense_act: str = "relu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        if self.mlp_layers == 0:
            return x
        return MLP(
            input_dims=self.input_dim,
            output_dim=self.features_dim,
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )(x)


class PPOAgent(nn.Module):
    """Feature extractor + actor heads + critic. Returns (actor_outs, values)."""

    actions_dim: Sequence[int]
    is_continuous: bool
    distribution: str
    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_input_channels: int
    mlp_input_dim: int
    screen_size: int
    encoder_cfg: Dict[str, Any]
    actor_cfg: Dict[str, Any]
    critic_cfg: Dict[str, Any]
    dtype: Any = jnp.float32

    def setup(self) -> None:
        cnn_encoder = (
            CNNEncoder(
                self.cnn_input_channels,
                self.encoder_cfg["cnn_features_dim"],
                self.screen_size,
                self.cnn_keys,
                dtype=self.dtype,
            )
            if len(self.cnn_keys) > 0
            else None
        )
        mlp_encoder = (
            MLPEncoder(
                self.mlp_input_dim,
                self.encoder_cfg["mlp_features_dim"],
                self.mlp_keys,
                self.encoder_cfg["dense_units"],
                self.encoder_cfg["mlp_layers"],
                self.encoder_cfg["dense_act"],
                self.encoder_cfg["layer_norm"],
                dtype=self.dtype,
            )
            if len(self.mlp_keys) > 0
            else None
        )
        self.feature_extractor = MultiEncoder(cnn_encoder, mlp_encoder)
        kernel_init = (
            nn.initializers.orthogonal(1.0) if self.encoder_cfg.get("ortho_init", False) else None
        )
        self.critic = MLP(
            input_dims=1,  # inferred at call; kept for API parity
            output_dim=1,
            hidden_sizes=[self.critic_cfg["dense_units"]] * self.critic_cfg["mlp_layers"],
            activation=self.critic_cfg["dense_act"],
            layer_norm=self.critic_cfg["layer_norm"],
            kernel_init=kernel_init,
        )
        self.actor_backbone = (
            MLP(
                input_dims=1,
                output_dim=None,
                hidden_sizes=[self.actor_cfg["dense_units"]] * self.actor_cfg["mlp_layers"],
                activation=self.actor_cfg["dense_act"],
                layer_norm=self.actor_cfg["layer_norm"],
                kernel_init=kernel_init,
            )
            if self.actor_cfg["mlp_layers"] > 0
            else None
        )
        if self.is_continuous:
            self.actor_heads = [nn.Dense(sum(self.actions_dim) * 2)]
        else:
            self.actor_heads = [nn.Dense(d) for d in self.actions_dim]

    def __call__(self, obs: Dict[str, jax.Array]) -> Tuple[List[jax.Array], jax.Array]:
        feat = self.feature_extractor(obs)
        values = self.critic(feat)
        x = self.actor_backbone(feat) if self.actor_backbone is not None else feat
        actor_outs = [head(x) for head in self.actor_heads]
        return actor_outs, values.astype(jnp.float32)


# ----------------------------------------------------------------------------------
# Pure distribution helpers shared by training and rollout
# ----------------------------------------------------------------------------------


def _continuous_dist(actor_out: jax.Array) -> Independent:
    mean, log_std = jnp.split(actor_out, 2, axis=-1)
    return Independent(Normal(mean, jnp.exp(log_std)), 1)


def sample_actions(
    actor_outs: List[jax.Array],
    key: jax.Array,
    is_continuous: bool,
    distribution: str,
    greedy: bool = False,
) -> List[jax.Array]:
    """Sample (or take the mode of) the policy distributions."""
    if is_continuous:
        dist = _continuous_dist(actor_outs[0])
        if greedy:
            actions = dist.base.loc
        else:
            actions = dist.rsample(key)
        if distribution == "tanh_normal":
            actions = safetanh(actions, eps=1e-6)
        return [actions]
    keys = jax.random.split(key, len(actor_outs))
    out = []
    for logits, k in zip(actor_outs, keys):
        d = OneHotCategorical(logits=logits.astype(jnp.float32))
        out.append(d.mode if greedy else d.sample(k))
    return out


def evaluate_actions(
    actor_outs: List[jax.Array],
    actions: List[jax.Array],
    is_continuous: bool,
    distribution: str,
) -> Tuple[jax.Array, jax.Array]:
    """Return (logprob[..., 1], entropy[..., 1]) for given actions (train path)."""
    if is_continuous:
        dist = _continuous_dist(actor_outs[0].astype(jnp.float32))
        act = actions[0]
        if distribution == "tanh_normal":
            pre = safeatanh(act, eps=1e-6)
            logp = dist.log_prob(pre) - 2.0 * (
                jnp.log(jnp.asarray(2.0)) - act - jax.nn.softplus(-2.0 * act)
            ).sum(-1)
            return logp[..., None], dist.entropy()[..., None]
        logp = dist.log_prob(act)
        return logp[..., None], dist.entropy()[..., None]
    logps, ents = [], []
    for logits, act in zip(actor_outs, actions):
        d = OneHotCategorical(logits=logits.astype(jnp.float32))
        logps.append(d.log_prob(act))
        ents.append(d.entropy())
    return (
        jnp.stack(logps, axis=-1).sum(axis=-1, keepdims=True),
        jnp.stack(ents, axis=-1).sum(axis=-1, keepdims=True),
    )


class PPOPlayer:
    """Rollout-side policy: holds params + jitted act/get_values (reference :242).

    Every per-step op — sampling, log-prob, the env-facing argmax/concat — is fused
    into ONE jitted call: eager ops cost a full dispatch round-trip on remote TPU
    backends, so the host loop only ever transfers results.
    """

    def __init__(self, agent: PPOAgent, params: Any, actions_dim: Sequence[int]):
        self.agent = agent
        self.params = params
        self.actions_dim = tuple(actions_dim)

        def _env_actions(actions: List[jax.Array]) -> jax.Array:
            if agent.is_continuous:
                return jnp.concatenate(actions, -1)
            return jnp.concatenate([a.argmax(-1, keepdims=True).astype(jnp.int32) for a in actions], -1)

        def _act(params, obs, key):
            key, sub = jax.random.split(key)
            actor_outs, values = agent.apply(params, obs)
            actions = sample_actions(actor_outs, sub, agent.is_continuous, agent.distribution)
            logp, _ = evaluate_actions(actor_outs, actions, agent.is_continuous, agent.distribution)
            # host_float32: rollout products are pulled to host / stored f32 (bf16
            # degrades to |V2 through the remote-TPU tunnel)
            return host_float32((jnp.concatenate(actions, -1), _env_actions(actions), logp, values)) + (key,)

        def _greedy(params, obs, key):
            key, sub = jax.random.split(key)
            actor_outs, _ = agent.apply(params, obs)
            actions = sample_actions(actor_outs, sub, agent.is_continuous, agent.distribution, greedy=True)
            return host_float32(_env_actions(actions)), key

        def _values(params, obs):
            _, values = agent.apply(params, obs)
            return host_float32(values)

        def _normalize(obs):
            # raw env obs -> the encoder's expected layout/ranges, in-graph: cnn
            # stacks arrive uint8-scaled [0,255] and become centered floats; mlp
            # obs flatten to [n_envs, features] (mirrors utils.prepare_obs)
            out = {}
            for k, v in obs.items():
                v = jnp.asarray(v, jnp.float32)
                if k in agent.cnn_keys:
                    # collapse any frame-stack dim into channels (idempotent for
                    # already-[n_envs, C, H, W] inputs)
                    out[k] = v.reshape(v.shape[0], -1, *v.shape[-2:]) / 255.0 - 0.5
                else:
                    out[k] = v.reshape(v.shape[0], -1)
            return out

        def _act_raw(params, obs, key):
            return _act(params, _normalize(obs), key)

        def _greedy_raw(params, obs, key):
            return _greedy(params, _normalize(obs), key)

        self._act = jax_compile.guarded_jit(_act, name="ppo.act")
        self._act_raw = jax_compile.guarded_jit(_act_raw, name="ppo.act_raw")
        self._greedy = jax_compile.guarded_jit(_greedy, name="ppo.greedy")
        self._greedy_raw = jax_compile.guarded_jit(_greedy_raw, name="ppo.greedy_raw")
        self._values = jax_compile.guarded_jit(_values, name="ppo.values")
        self._act_impl = _act  # unjitted: fused into the packed-act trace
        self._values_impl = _values  # unjitted: fused into the in-graph rollout scan
        self._greedy_impl = _greedy
        self._packed_act_fns: Dict[Any, Any] = {}

    def __call__(self, obs: Dict[str, jax.Array], key: jax.Array):
        """Returns (cat_actions, env_actions, logprobs, values, next_key) — all on device."""
        return self._act(self.params, obs, key)

    def act_raw(self, obs: Dict[str, Any], key: jax.Array):
        """Same as ``__call__`` but takes RAW host obs (mlp vectors + [0,255] cnn
        stacks, already shaped ``[n_envs, ...]``): the normalization runs inside
        the ONE jitted dispatch instead of as a separate eager prep + device_put
        per step (measured ~20% of the per-step rollout cost in the host loop).
        """
        return self._act_raw(self.params, obs, key)

    def act_packed(self, codec, packed: jax.Array, key: jax.Array):
        """Same as :meth:`act_raw` but over a ``PackedObsCodec`` transfer: the
        whole obs dict arrives as ONE packed ``device_put`` and is unpacked +
        normalized in-graph (``codec.decode_obs`` mirrors ``_normalize``
        bit-for-bit), so a steady-state step costs exactly one host->device
        transfer. One compile per codec layout (two codecs with equal-length
        buffers must not share a trace, hence the signature-keyed cache)."""
        return self.packed_act_fn(codec)(self.params, packed, key)

    def packed_act_fn(self, codec):
        """The guarded jitted packed-act entry point for ``codec`` (exposed so
        the train loop can register its AOT warmup before the rollout starts)."""
        fn = self._packed_act_fns.get(codec.signature)
        if fn is None:
            fn = jax_compile.guarded_jit(
                lambda params, packed, key: self._act_impl(params, codec.decode_obs(packed), key),
                name="ppo.act_packed",
            )
            self._packed_act_fns[codec.signature] = fn
        return fn

    def get_actions(self, obs: Dict[str, jax.Array], key: jax.Array, greedy: bool = False):
        """Returns (env-facing actions, next_key)."""
        if greedy:
            return self._greedy(self.params, obs, key)
        _, env_actions, _, _, key = self._act(self.params, obs, key)
        return env_actions, key

    def get_actions_raw(
        self, obs: Dict[str, Any], key: jax.Array, greedy: bool = False, params: Any = None
    ):
        """:meth:`get_actions` over RAW host obs (normalization fused in-graph,
        same single-dispatch rationale as :meth:`act_raw`). ``params`` overrides
        ``self.params`` so callers that swap weight generations atomically (the
        serve runtime) can pin a batch to one generation without mutating the
        shared player. Returns (env-facing actions, next_key)."""
        p = self.params if params is None else params
        if greedy:
            return self._greedy_raw(p, obs, key)
        _, env_actions, _, _, key = self._act_raw(p, obs, key)
        return env_actions, key

    def get_values(self, obs: Dict[str, jax.Array]) -> jax.Array:
        return self._values(self.params, obs)


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space: gymnasium.spaces.Dict,
    agent_state: Optional[Dict[str, Any]] = None,
) -> Tuple[PPOAgent, Any, PPOPlayer]:
    """Create the agent module, init (or restore) params, return (agent, params, player).

    Reference: build_agent sheeprl/algos/ppo/agent.py:325 (there it DDP-wraps the
    train module and clones a single-device player; here params are a single pytree
    replicated across the mesh — no wrapping needed).
    """
    distribution = cfg.distribution.get("type", "auto").lower()
    if distribution not in ("auto", "normal", "tanh_normal", "discrete"):
        raise ValueError(
            "The distribution must be on of: `auto`, `discrete`, `normal` and `tanh_normal`. "
            f"Found: {distribution}"
        )
    if distribution == "discrete" and is_continuous:
        raise ValueError("You have choose a discrete distribution but `is_continuous` is true")
    if distribution not in ("discrete", "auto") and not is_continuous:
        raise ValueError("You have choose a continuous distribution but `is_continuous` is false")
    if distribution == "auto":
        distribution = "normal" if is_continuous else "discrete"

    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    in_channels = sum(prod(obs_space[k].shape[:-2]) for k in cnn_keys)
    mlp_input_dim = sum(obs_space[k].shape[0] for k in mlp_keys)
    agent = PPOAgent(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=distribution,
        cnn_keys=tuple(cnn_keys),
        mlp_keys=tuple(mlp_keys),
        cnn_input_channels=in_channels,
        mlp_input_dim=mlp_input_dim,
        screen_size=cfg.env.screen_size,
        encoder_cfg=dict(cfg.algo.encoder),
        actor_cfg=dict(cfg.algo.actor),
        critic_cfg=dict(cfg.algo.critic),
        dtype=runtime.compute_dtype,
    )
    sample_obs = {}
    for k in cnn_keys:
        shape = obs_space[k].shape
        sample_obs[k] = jnp.zeros((1, prod(shape[:-2]), *shape[-2:]), dtype=jnp.float32)
    for k in mlp_keys:
        sample_obs[k] = jnp.zeros((1, *obs_space[k].shape), dtype=jnp.float32)
    params = agent.init(jax.random.PRNGKey(cfg.seed), sample_obs)
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
    params = runtime.place_params(params)
    # The player's copy lives on the player device (host CPU by default): per-step
    # policy calls then never pay the accelerator round-trip (reference's
    # get_single_device_fabric split, sheeprl/utils/fabric.py:8-35).
    player = PPOPlayer(agent, runtime.to_player(params), actions_dim)
    return agent, params, player
