"""PPO, coupled training (reference sheeprl/algos/ppo/ppo.py:30-442).

TPU-first structure:
- the rollout loop stays on host (gym stepping is host work); the policy forward is a
  small jitted call per step;
- the entire optimization phase — GAE + update_epochs x minibatches — is ONE jitted
  function per iteration (`lax.scan` over minibatches), instead of the reference's
  Python loop of per-minibatch backward passes;
- data parallelism: the minibatch is shard-constrained on the `data` mesh axis with
  params replicated, so XLA inserts the gradient all-reduce over ICI (the DDP
  equivalent, SURVEY §2.1). `buffer.share_data` is implicitly true: the global
  permutation spans all devices' rollouts.
"""

from __future__ import annotations

import os
import time
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.algos.ppo.agent import build_agent, evaluate_actions
from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo.utils import normalize_obs, prepare_obs, test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.core import failpoints
from sheeprl_tpu.core import health as health_mod
from sheeprl_tpu.core import resilience
from sheeprl_tpu.core.pipeline import AsyncEnvStepper, PackedObsCodec, pipeline_enabled
from sheeprl_tpu.data.factory import make_rollout_buffer
from sheeprl_tpu.envs import ingraph as ingraph_envs
from sheeprl_tpu.parallel import handoff, overlap
from sheeprl_tpu.telemetry import device as tel_device
from sheeprl_tpu.telemetry import programs as tel_programs
from sheeprl_tpu.telemetry import trace
from sheeprl_tpu.utils.env import finished_episodes, make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.optim import with_clipping
from sheeprl_tpu.utils.profiler import TraceProfiler
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import (
    PlayerParamsSync,
    gae,
    normalize_tensor,
    polynomial_decay,
    save_configs,
)


def make_update_impl(
    agent,
    tx,
    cfg,
    runtime,
    n_data: int,
    obs_keys,
    cnn_keys,
    params_sync=None,
    *,
    axis_name=None,
    shards=1,
    constrain_data=True,
    batch_size=None,
):
    """Build the raw (unjitted) per-iteration optimization function.

    Signature: (params, opt_state, data, next_values, key, coefs) ->
    (params, opt_state, flat_params, metrics). ``data`` is the whole rollout
    ``[T, B, ...]``; ``flat_params`` is the raveled post-update param vector for the
    one-transfer player refresh (None if no ``params_sync`` given).

    Two flavors share the trace:
    - default (``axis_name=None``): the jitted split-path train step AND the
      single-device fused iteration's update phase (envs/ingraph/fused.py);
    - ``axis_name="data"``/``shards=N``: the body runs shard-local inside
      ``shard_map`` — permutations index the ``n_data/N`` local rows, minibatch
      grads (and the nonfinite guard's decision scalars, so every shard takes
      the identical apply-or-skip branch) all-reduce via ``jax.lax.pmean``.
      Per-shard minibatches of ``global_bs/N`` keep the effective global batch
      identical to the split path.
    """
    update_epochs = int(cfg.algo.update_epochs)
    # the default global batch assumes the mesh is DATA-parallel (every device
    # holds a slice of one rollout); the population trainer's mesh shards
    # MEMBERS instead — each member updates locally over its own n_data rows —
    # so it pins batch_size=per_rank_batch_size explicitly
    global_bs = (
        int(batch_size) if batch_size is not None
        else int(cfg.algo.per_rank_batch_size) * runtime.world_size
    )
    shards = int(shards)
    local_n = n_data // shards
    local_bs = max(global_bs // shards, 1)
    n_minibatches = max(local_n // local_bs, 1)
    # constrain_data=False drops the explicit data-axis sharding constraint:
    # the population trainer (envs/ingraph/population.py) vmaps this body over
    # a member axis (and may run it inside shard_map), where the constraint's
    # env-batch placement no longer applies.
    data_sharding = (
        NamedSharding(runtime.mesh, P("data")) if (axis_name is None and constrain_data) else None
    )
    nonfinite_guard = resilience.guard_enabled(resilience.resolve(cfg))

    def loss_fn(params, batch, clip_coef, ent_coef):
        norm_obs = normalize_obs(batch, cnn_keys, obs_keys)
        actions = jnp.split(
            batch["actions"], np.cumsum(agent.actions_dim)[:-1].tolist(), axis=-1
        ) if len(agent.actions_dim) > 1 else [batch["actions"]]
        actor_outs, new_values = agent.apply(params, norm_obs)
        new_logprobs, entropy = evaluate_actions(actor_outs, actions, agent.is_continuous, agent.distribution)
        advantages = batch["advantages"]
        if cfg.algo.normalize_advantages:
            advantages = normalize_tensor(advantages)
        pg_loss = policy_loss(new_logprobs, batch["logprobs"], advantages, clip_coef, cfg.algo.loss_reduction)
        v_loss = value_loss(
            new_values, batch["values"], batch["returns"], clip_coef, cfg.algo.clip_vloss, cfg.algo.loss_reduction
        )
        ent_loss = entropy_loss(entropy, cfg.algo.loss_reduction)
        total = pg_loss + cfg.algo.vf_coef * v_loss + ent_coef * ent_loss
        return total, (pg_loss, v_loss, ent_loss)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    micro = overlap.microbatches(cfg)

    def train(params, opt_state, data, next_values, key, clip_coef, ent_coef, lr_scale):
        # ----- GAE on device (reverse lax.scan over T; reference utils.py:64-100)
        returns, advantages = gae(
            data["rewards"],
            data["values"],
            data["dones"],
            next_values,
            cfg.algo.rollout_steps,
            cfg.algo.gamma,
            cfg.algo.gae_lambda,
        )
        data = dict(data)
        data["returns"] = returns
        data["advantages"] = advantages
        # flatten [T, B, *] -> [N, *]
        flat = {k: v.reshape((-1,) + v.shape[2:]) for k, v in data.items()}

        if update_epochs == 1 and n_minibatches == 1 and local_bs >= local_n:
            # ONE minibatch covering every row: a permutation only reorders the
            # batch mean, so skip the O(N log N) sort and the full-data gather
            perms = None
        else:
            n_keep = n_minibatches * local_bs
            epoch_keys = jax.random.split(key, update_epochs)
            perms = jnp.stack([jax.random.permutation(k, local_n)[:n_keep] for k in epoch_keys])
            perms = perms.reshape(update_epochs * n_minibatches, local_bs)

        def minibatch_step(carry, idx):
            params, opt_state = carry
            if idx is None:
                batch = flat
                if data_sharding is not None:
                    batch = jax.tree_util.tree_map(
                        lambda v: jax.lax.with_sharding_constraint(v, data_sharding), batch
                    )
            elif data_sharding is not None:
                batch = jax.tree_util.tree_map(
                    lambda v: jax.lax.with_sharding_constraint(jnp.take(v, idx, axis=0), data_sharding), flat
                )
            else:
                # shard-local body: the rows are already this shard's block
                batch = jax.tree_util.tree_map(lambda v: jnp.take(v, idx, axis=0), flat)
            # grad_microbatches=1 is the verbatim single-batch backward + one
            # pmean; >1 runs the bucketed accumulation scan with a per-bucket
            # psum (parallel/overlap.py) — grads come back already axis-averaged
            (loss, (pg, vl, ent)), grads = overlap.accumulate_grads(
                grad_fn, params, batch, (clip_coef, ent_coef),
                microbatches=micro, axis_name=axis_name, axis_size=shards,
            )
            if axis_name is not None:
                # the loss scalars reduce too so the finite_or_skip decision
                # below is replicated across shards (a shard-local skip would
                # silently fork the param replicas)
                loss, pg, vl, ent = (jax.lax.pmean(x, axis_name) for x in (loss, pg, vl, ent))
            gnorm = optax.global_norm(grads)
            updates, new_opt_state = tx.update(grads, opt_state, params)
            # health-sentinel LR backoff: a traced scalar operand (no retrace on
            # change); the healthy value is exactly 1.0, and x * 1.0 is IEEE-
            # exact, so a disabled/quiet sentinel leaves updates bit-identical
            updates = jax.tree_util.tree_map(lambda u: u * lr_scale, updates)
            new_params = optax.apply_updates(params, updates)
            if nonfinite_guard:
                (params, opt_state), skipped = resilience.finite_or_skip(
                    (loss, gnorm), (new_params, new_opt_state), (params, opt_state)
                )
            else:
                params, opt_state, skipped = new_params, new_opt_state, jnp.float32(0.0)
            return (params, opt_state), jnp.stack([pg, vl, ent, skipped, gnorm])

        (params, opt_state), losses = jax.lax.scan(
            minibatch_step, (params, opt_state), perms, length=1 if perms is None else None
        )
        metrics = losses.mean(axis=0)
        flat = params_sync.ravel(params) if params_sync is not None else jnp.zeros(())
        return params, opt_state, flat, {
            "Loss/policy_loss": metrics[0],
            "Loss/value_loss": metrics[1],
            "Loss/entropy_loss": metrics[2],
            "Resilience/nonfinite_skips": losses[:, 3].sum(),
            "Grads/global_norm": metrics[4],
        }

    return train


def make_train_fn(
    agent, tx, cfg, runtime, n_data: int, obs_keys, cnn_keys, params_sync=None, *, donate_data=False
):
    """The jitted split-path train step (see :func:`make_update_impl`).

    ``donate_data=True`` additionally donates the rollout ``data`` tree — safe
    when every caller hands over a freshly assembled batch it never reads
    again (the decoupled trainer's per-shard handoff does exactly that; the
    coupled loop keeps the default so diagnostic spies can still read it)."""
    train = make_update_impl(agent, tx, cfg, runtime, n_data, obs_keys, cnn_keys, params_sync)
    donate = (0, 1, 2) if donate_data else (0, 1)
    return jax_compile.guarded_jit(train, name="ppo.train", donate_argnums=donate)


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    use_ingraph = ingraph_envs.env_backend(cfg) == "ingraph"
    if not use_ingraph and "minedojo" in cfg.env.wrapper._target_.lower():
        raise ValueError(
            "MineDojo is not currently supported by PPO agent, since it does not take "
            "into consideration the action masks provided by the environment, but needed "
            "in order to play correctly the game. "
            "As an alternative you can use one of the Dreamers' agents."
        )
    initial_ent_coef = float(cfg.algo.ent_coef)
    initial_clip_coef = float(cfg.algo.clip_coef)
    world_size = runtime.world_size

    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_state

        state = load_state(cfg.checkpoint.resume_from)

    logger = get_logger(runtime, cfg)
    if logger:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.logger = logger
    runtime.print(f"Log dir: {log_dir}")
    if runtime.is_global_zero and log_dir:
        # compiled-program observatory: every AOT compile below (act step,
        # fused trainer, split train fn) lands a ledger row here — unless a
        # parent pinned SHEEPRL_TPU_PROGRAMS, which wins (one ledger per tree)
        tel_programs.configure_default(os.path.join(log_dir, "telemetry", "programs.jsonl"))

    # Environment setup: one process drives world_size * num_envs envs (per-rank
    # semantics of the reference are per-device here).
    ft = resilience.resolve(cfg)
    sentinel = health_mod.HealthSentinel(
        cfg, log_dir=log_dir if runtime.is_global_zero else None, world_size=world_size
    )
    n_envs = cfg.env.num_envs * world_size
    if use_ingraph:
        # in-graph backend: no worker pool, no supervision layer — the whole
        # batch of envs is one device-resident pytree stepped inside the fused
        # rollout (envs/ingraph/). Collection runs on the accelerator even when
        # the player would normally sit on host.
        collect_device = runtime.device
        envs = ingraph_envs.make_vector_env(cfg, n_envs, cfg.seed, device=collect_device)
    else:
        envs = resilience.make_supervised_env(
            [
                make_env(
                    cfg,
                    cfg.seed + i,
                    0,
                    log_dir if runtime.is_global_zero else None,
                    "train",
                    vector_env_idx=i,
                )
                for i in range(n_envs)
            ],
            sync=cfg.env.sync_env,
            ft=ft,
        )
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if cfg.algo.cnn_keys.encoder + cfg.algo.mlp_keys.encoder == []:
        raise RuntimeError(
            "You should specify at least one CNN keys or MLP keys from the cli: "
            "`cnn_keys.encoder=[rgb]` or `mlp_keys.encoder=[state]`"
        )
    if cfg.metric.log_level > 0:
        runtime.print("Encoder CNN keys:", cfg.algo.cnn_keys.encoder)
        runtime.print("Encoder MLP keys:", cfg.algo.mlp_keys.encoder)
    obs_keys = cfg.algo.cnn_keys.encoder + cfg.algo.mlp_keys.encoder
    cnn_keys = cfg.algo.cnn_keys.encoder

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (envs.single_action_space.nvec.tolist() if is_multidiscrete else [envs.single_action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)

    agent, params, player = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["agent"] if state else None,
    )
    if use_ingraph:
        # policy forward happens inside the scan on the collect device, not on
        # the (host) player device build_agent placed the params on
        player.params = jax.device_put(player.params, collect_device)
    player_sync_device = collect_device if use_ingraph else runtime.player_device

    # Optimizer: optax chain (clipping + optional linear lr decay = PolynomialLR(power=1))
    policy_steps_per_iter = int(n_envs * cfg.algo.rollout_steps)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    n_data = cfg.algo.rollout_steps * n_envs
    global_bs = int(cfg.algo.per_rank_batch_size) * world_size
    updates_per_iter = int(cfg.algo.update_epochs) * max(n_data // global_bs, 1)
    optim_kwargs = dict(cfg.algo.optimizer)
    if cfg.algo.anneal_lr:
        lr0 = optim_kwargs.pop("lr", 1e-3)
        optim_kwargs["lr"] = optax.linear_schedule(lr0, 0.0, total_iters * updates_per_iter)
    tx = with_clipping(instantiate(optim_kwargs)(), cfg.algo.max_grad_norm)
    opt_state = tx.init(params)
    if state:
        opt_state = jax.tree_util.tree_map(jnp.asarray, state["optimizer"])
    opt_state = runtime.place_params(opt_state)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    if cfg.buffer.size < cfg.algo.rollout_steps:
        raise ValueError(
            f"The size of the buffer ({cfg.buffer.size}) cannot be lower "
            f"than the rollout steps ({cfg.algo.rollout_steps})"
        )
    rb = make_rollout_buffer(cfg, runtime, n_envs, obs_keys, log_dir)
    # device backend: the [T, B] rollout lives in HBM; policy outputs never
    # touch host and the per-step host->device traffic is one packed put
    device_rollout = getattr(rb, "backend", "host") == "device"

    # Counters (same step semantics as the reference, howto/work_with_steps.md)
    last_train = 0
    train_step = 0
    start_iter = (state["iter_num"] // world_size) + 1 if state else 1
    policy_step = state["iter_num"] * cfg.env.num_envs * cfg.algo.rollout_steps if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    if state:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter}), so "
            "the metrics will be logged at the nearest greater multiple of the policy_steps_per_iter value."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter}), so "
            "the checkpoint will be saved at the nearest greater multiple of the policy_steps_per_iter value."
        )

    params_sync = PlayerParamsSync(player.params)
    train_fn = make_train_fn(agent, tx, cfg, runtime, n_data, obs_keys, cnn_keys, params_sync)
    profiler = TraceProfiler(cfg.metric.get("profiler"), log_dir if runtime.is_global_zero else None)
    rng = jax.random.PRNGKey(cfg.seed)
    # Separate rollout key committed to the player device: the policy forward then
    # runs entirely there (mixing committed arrays across backends is an error).
    player_rng = jax.device_put(jax.random.PRNGKey(cfg.seed + 1), runtime.player_device)
    if state and "rng" in state:
        # restore the EXACT key chains so a preempted run resumes bit-identically
        # to the uninterrupted one (older checkpoints lack these: seed restart)
        rng = jnp.asarray(state["rng"])
        player_rng = jax.device_put(jnp.asarray(state["player_rng"]), runtime.player_device)

    step_data = {}
    reset_obs = envs.reset(seed=cfg.seed)[0]
    next_obs = {}
    for k in obs_keys:
        _obs = reset_obs[k]
        if k in cnn_keys:
            _obs = _obs.reshape(n_envs, -1, *_obs.shape[-2:])
        next_obs[k] = _obs
        step_data[k] = _obs[np.newaxis]

    # ----- software pipeline (core/pipeline.py): the env workers step while the
    # host closes out the PREVIOUS step and dispatches this one's device work;
    # the obs reach the device as ONE packed put per step with the previous
    # step's rewards/dones riding along for the buffer's row-close write
    stepper = AsyncEnvStepper(envs, enabled=pipeline_enabled(cfg) and not use_ingraph)
    codec = PackedObsCodec(cnn_keys=cnn_keys, device=runtime.player_device)
    collector = None
    fused_trainer = None
    if use_ingraph:
        collector = ingraph_envs.InGraphRolloutCollector(
            envs,
            player,
            rollout_steps=cfg.algo.rollout_steps,
            gamma=cfg.algo.gamma,
            clip_rewards=cfg.env.clip_rewards,
            store_logprobs=True,
            name="ppo",
        )
        if ingraph_envs.fused_enabled(cfg):
            # ----- whole-iteration fusion (envs/ingraph/fused.py): rollout scan
            # + GAE + all update epochs compile into ONE program per iteration;
            # on a multi-device mesh the env batch shards on the `data` axis and
            # gradients all-reduce in-graph (pmean inside the update impl)
            update_impl = make_update_impl(
                agent,
                tx,
                cfg,
                runtime,
                n_data,
                obs_keys,
                cnn_keys,
                params_sync,
                axis_name="data" if world_size > 1 else None,
                shards=world_size,
            )
            fused_trainer = ingraph_envs.FusedInGraphTrainer(
                collector,
                update_impl,
                n_extras=3,
                mesh=runtime.mesh if world_size > 1 else None,
                name="ppo",
            )
            fused_trainer.shard_carry()
    zero_extra = {
        "rewards": np.zeros((n_envs, 1), np.float32),
        "dones": np.zeros((n_envs, 1), np.float32),
    }

    # ----- AOT warmup (core/compile.py): compile the packed-act step, the fused
    # train step, and the metric-drain kernels on a background thread while the
    # first rollout collects; the first train call then executes a pre-built
    # executable (trace count 0 at call time, Compile/retraces stays 0).
    warmup = jax_compile.AOTWarmup(enabled=jax_compile.aot_enabled(cfg))
    if warmup.enabled and use_ingraph:
        if fused_trainer is not None:
            # ONE entry point for the whole iteration: collect + GAE + update
            # epochs. The specs come from the live (mesh-sharded, for the
            # shard_map variant) params/opt_state/carry, so the background
            # compile targets the exact steady-state placements.
            warmup.add(
                fused_trainer.step_fn,
                *fused_trainer.warmup_specs(
                    params,
                    opt_state,
                    rng,
                    jnp.float32(cfg.algo.clip_coef),
                    jnp.float32(cfg.algo.ent_coef),
                    jnp.float32(1.0),
                ),
            )
        else:
            # the whole rollout is ONE entry point (the fused scan); its abstract
            # outputs are exactly the train step's inputs, so both specs derive
            # without touching the device
            warmup.add(collector.collect_fn, *collector.warmup_specs())
            data_specs, nv_spec = collector.output_specs()
            warmup.add(
                train_fn,
                jax_compile.specs_of(params),
                jax_compile.specs_of(opt_state),
                # the handoff below assembles the batch PRE-SHARDED on the mesh
                # (env axis): the warmup specs must carry that layout or the
                # AOT executable rejects the real batch at call time
                handoff.shard_specs(data_specs, runtime.mesh, batch_axis=1),
                jax.ShapeDtypeStruct(nv_spec.shape, jnp.float32, sharding=runtime.replicated),
                jax_compile.spec_like(rng),
                jax.ShapeDtypeStruct((), jnp.float32),
                jax.ShapeDtypeStruct((), jnp.float32),
                jax.ShapeDtypeStruct((), jnp.float32),
            )
        if aggregator is not None:
            warmup.add_task(
                lambda: aggregator.precompile_drain(
                    (
                        "Loss/policy_loss",
                        "Loss/value_loss",
                        "Loss/entropy_loss",
                        "Resilience/nonfinite_skips",
                        "Grads/global_norm",
                    )
                ),
                name="metric.drain",
            )
        warmup.start()
    elif warmup.enabled:
        packed0 = codec.encode(next_obs, extra=zero_extra)
        act_fn = player.packed_act_fn(codec)
        act_specs = (
            jax_compile.specs_of(player.params),
            jax_compile.spec_like(packed0),
            jax_compile.spec_like(player_rng),
        )
        warmup.add(act_fn, *act_specs)
        if not device_rollout:
            # train-step specs from the resolved config + the act step's
            # abstract outputs (jax.eval_shape: no FLOPs, no transfers); the
            # device-backend rollout keeps JIT-on-first-call (its storage
            # layout is the buffer's concern, not derivable here)
            cat_s, _env_s, logp_s, val_s, _key_s = jax.eval_shape(act_fn.fun, *act_specs)
            T = int(cfg.algo.rollout_steps)
            data_specs = {
                k: jax.ShapeDtypeStruct((T, *next_obs[k].shape), jnp.float32) for k in obs_keys
            }
            for k, s in (("actions", cat_s), ("logprobs", logp_s), ("values", val_s)):
                data_specs[k] = jax.ShapeDtypeStruct((T, *s.shape), jnp.float32)
            for k in ("rewards", "dones"):
                data_specs[k] = jax.ShapeDtypeStruct((T, n_envs, 1), jnp.float32)
            warmup.add(
                train_fn,
                jax_compile.specs_of(params),
                jax_compile.specs_of(opt_state),
                # the host rollout enters the mesh shard-at-put (env axis) —
                # warmup against that layout, not a replicated one
                handoff.shard_specs(data_specs, runtime.mesh, batch_axis=1),
                jax.ShapeDtypeStruct(val_s.shape, jnp.float32),
                jax_compile.spec_like(rng),
                jax.ShapeDtypeStruct((), jnp.float32),
                jax.ShapeDtypeStruct((), jnp.float32),
                jax.ShapeDtypeStruct((), jnp.float32),
            )
        if aggregator is not None:
            warmup.add_task(
                lambda: aggregator.precompile_drain(
                    (
                        "Loss/policy_loss",
                        "Loss/value_loss",
                        "Loss/entropy_loss",
                        "Resilience/nonfinite_skips",
                        "Grads/global_norm",
                    )
                ),
                name="metric.drain",
            )
        warmup.start()

    pending: Dict[str, Any] = {}

    def _process_pending(cur_packed):
        """Close out the previous step while the env workers run: buffer row
        write, episode/metric accounting. ``cur_packed`` is the current step's
        packed transfer carrying the pending rewards/dones (None at the
        end-of-rollout flush, where a short extra-only put stands in)."""
        if not pending:
            return
        if device_rollout:
            if cur_packed is not None:
                extra_packed, extra_only = cur_packed, False
            else:
                extra_packed, extra_only = (
                    codec.encode_extra_only(
                        {"rewards": pending["rewards"], "dones": pending["dones"]}
                    ),
                    True,
                )
            # obs decode from the PREVIOUS step's act transfer, rewards/dones
            # from the current one: closing a row costs zero extra transfers
            rb.add_env_packed(codec, pending["packed"], extra_packed, extra_only=extra_only)
        else:
            rewards = pending["rewards"]
            step_data["dones"] = pending["dones"][np.newaxis]
            step_data["values"] = np.asarray(pending["values"])[np.newaxis]
            step_data["actions"] = np.asarray(pending["cat_actions"])[np.newaxis]
            step_data["logprobs"] = np.asarray(pending["logprobs"])[np.newaxis]
            step_data["rewards"] = rewards[np.newaxis]
            if cfg.buffer.memmap:
                step_data["returns"] = np.zeros_like(rewards, shape=(1, *rewards.shape))
                step_data["advantages"] = np.zeros_like(rewards, shape=(1, *rewards.shape))
            rb.add(step_data, validate_args=cfg.buffer.validate_args)
            # the row just written holds the obs the pending step acted on; the
            # NEXT row starts from the obs that step produced (current next_obs)
            for k in obs_keys:
                step_data[k] = next_obs[k][np.newaxis]
        if cfg.metric.log_level > 0:
            for i, (ep_rew, ep_len) in enumerate(finished_episodes(pending["info"])):
                if aggregator and "Rewards/rew_avg" in aggregator:
                    aggregator.update("Rewards/rew_avg", ep_rew)
                if aggregator and "Game/ep_len_avg" in aggregator:
                    aggregator.update("Game/ep_len_avg", ep_len)
                runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")
        pending.clear()

    def _ckpt_state():
        # shared by the periodic checkpoint and the preemption emergency save so
        # both are resumable through the identical path; the rng chains make the
        # resumed run BIT-IDENTICAL to an uninterrupted one
        return {
            "agent": jax.device_get(params),
            "optimizer": jax.device_get(opt_state),
            "iter_num": iter_num * world_size,
            "batch_size": cfg.algo.per_rank_batch_size * world_size,
            "last_log": last_log,
            "last_checkpoint": last_checkpoint,
            "rng": jax.device_get(rng),
            "player_rng": jax.device_get(player_rng),
        }

    def _drain_ingraph_episodes(roll_metrics):
        """Pull and log the [T, B] episode-metric leaves from an ingraph rollout.

        The pull is the ONLY bulk host traffic an ingraph iteration performs, so
        it is skipped outright when nothing consumes it: aggregator disabled, or
        between ``log_every`` drains (finished episodes are then sampled at the
        drain iterations rather than fetched every iteration)."""
        if cfg.metric.log_level <= 0 or aggregator is None or aggregator.disabled:
            return
        if policy_step - last_log < cfg.metric.log_every and iter_num != total_iters:
            return
        for ep_rew, ep_len in ingraph_envs.iter_finished_episodes(roll_metrics):
            if "Rewards/rew_avg" in aggregator:
                aggregator.update("Rewards/rew_avg", ep_rew)
            if "Game/ep_len_avg" in aggregator:
                aggregator.update("Game/ep_len_avg", ep_len)
            runtime.print(f"Rank-0: policy_step={policy_step}, episode_reward={ep_rew}")

    guard = resilience.PreemptionGuard(
        enabled=ft.preemption.enabled, stop_after_iters=ft.preemption.stop_after_iters
    )
    with guard:
        for iter_num in range(start_iter, total_iters + 1):
            profiler.step(policy_step)
            if fused_trainer is not None:
                # ----- whole-iteration fused step (envs/ingraph/fused.py): the
                # rollout scan, GAE, and every update epoch run as ONE compiled
                # donated-carry program; only the raveled params and metric
                # leaves return to the host. Chaos seam first, so drills and
                # the sentinel's rollback ladder cover the fused path too.
                failpoints.failpoint("train.fused_update", iter=iter_num)
                failpoints.failpoint(
                    "train.grad_sync", iter=iter_num, microbatches=overlap.microbatches(cfg)
                )
                with trace.span("train/update", fused=True, iter=iter_num), timer(
                    "Time/train_time", SumMetric()
                ):
                    if iter_num == start_iter:
                        warmup.wait()
                    policy_step += n_envs * cfg.algo.rollout_steps
                    rng, train_key = jax.random.split(rng)
                    params, opt_state, flat_params, roll_metrics, train_metrics = fused_trainer.step(
                        params,
                        opt_state,
                        fused_trainer.to_mesh(train_key),
                        fused_trainer.to_mesh(jnp.float32(cfg.algo.clip_coef)),
                        fused_trainer.to_mesh(jnp.float32(cfg.algo.ent_coef)),
                        fused_trainer.to_mesh(jnp.float32(sentinel.lr_scale)),
                    )
                    player.params = params_sync.pull(flat_params, player_sync_device)
                    if not timer.disabled:  # sync only when the phase is being timed
                        jax.block_until_ready(params)
                train_step += world_size
                envs.fire_autoreset_failpoints(roll_metrics["dones"])
                _drain_ingraph_episodes(roll_metrics)
            elif use_ingraph:
                # ----- split ingraph path (env.fused=False): the fused rollout
                # scan (envs/ingraph/rollout.py) followed by the separately
                # jitted train step below — the fused path's parity reference
                with trace.span("train/collect", iter=iter_num), timer(
                    "Time/env_interaction_time", SumMetric()
                ):
                    policy_step += n_envs * cfg.algo.rollout_steps
                    ingraph_data, roll_metrics, ingraph_next_values = collector.collect()
                # zero-cost unless an env.autoreset drill is armed (the has()
                # probe short-circuits before any device pull)
                envs.fire_autoreset_failpoints(roll_metrics["dones"])
                _drain_ingraph_episodes(roll_metrics)
            else:
                _collect_t0 = time.perf_counter()
                for _ in range(cfg.algo.rollout_steps):
                    policy_step += n_envs

                    with timer("Time/env_interaction_time", SumMetric()):
                        # ONE packed host->device transfer per step: obs plus the
                        # previous step's rewards/dones (decoded only by the buffer
                        # write), normalization runs in-graph (PPOPlayer.act_packed)
                        packed = codec.encode(
                            next_obs,
                            extra={"rewards": pending["rewards"], "dones": pending["dones"]}
                            if pending
                            else zero_extra,
                        )
                        cat_actions, env_actions, logprobs, values, player_rng = player.act_packed(
                            codec, packed, player_rng
                        )
                        # the ONE unavoidable per-step device->host sync: the env needs
                        # the actions on host to step
                        real_actions = np.asarray(env_actions)
                        stepper.step_async(real_actions.reshape(envs.action_space.shape))

                        # ---- overlap window: env workers are stepping; close out the
                        # previous step and dispatch this one's policy-row scatter
                        _process_pending(packed)
                        if device_rollout:
                            # in-graph scatter straight from the player step's outputs:
                            # values/logprobs/actions stay in HBM, no host pull
                            rb.add_policy({"actions": cat_actions, "logprobs": logprobs, "values": values})

                        obs, rewards, terminated, truncated, info = stepper.step_wait()
                        truncated_envs = np.nonzero(truncated)[0]
                        if len(truncated_envs) > 0 and "final_obs" in info:
                            # bootstrap on truncation (reference ppo.py:292-309)
                            final_obs_arr = np.asarray(info["final_obs"], dtype=object)
                            real_next_obs = {k: [] for k in obs_keys}
                            valid_idx = []
                            for te in truncated_envs:
                                fo = final_obs_arr[te]
                                if fo is None:
                                    continue
                                valid_idx.append(te)
                                for k in obs_keys:
                                    v = np.asarray(fo[k], dtype=np.float32)
                                    if k in cnn_keys:
                                        v = v.reshape(-1, *v.shape[-2:]) / 255.0 - 0.5
                                    real_next_obs[k].append(v)
                            if valid_idx:
                                # canonical shape: pad to the FULL [n_envs, ...] batch and
                                # gather the valid rows after, so the values forward keeps
                                # ONE compiled shape no matter how many envs truncated
                                # (1..n_envs distinct shapes would otherwise each compile)
                                padded = {
                                    k: np.zeros((n_envs, *np.asarray(v[0]).shape), np.float32)
                                    for k, v in real_next_obs.items()
                                }
                                for j, te in enumerate(valid_idx):
                                    for k in obs_keys:
                                        padded[k][te] = real_next_obs[k][j]
                                stacked = {
                                    k: jax.device_put(v, runtime.player_device) for k, v in padded.items()
                                }
                                vals = np.asarray(player.get_values(stacked)).reshape(n_envs)
                                rewards = np.asarray(rewards, dtype=np.float32)
                                rewards[valid_idx] += cfg.algo.gamma * vals[valid_idx]
                        dones = np.logical_or(terminated, truncated).reshape(n_envs, -1).astype(np.uint8)
                        rewards = clip_rewards_fn(np.asarray(rewards, dtype=np.float32)).reshape(n_envs, -1)

                        # env products become the next step's pending work: the row
                        # write and episode accounting run in the NEXT overlap window
                        pending.update(
                            packed=packed,
                            rewards=rewards,
                            dones=dones,
                            info=info,
                            values=values,
                            cat_actions=cat_actions,
                            logprobs=logprobs,
                        )

                        next_obs = {}
                        for k in obs_keys:
                            _obs = obs[k]
                            if k in cnn_keys:
                                _obs = _obs.reshape(n_envs, -1, *_obs.shape[-2:])
                            next_obs[k] = _obs

                with timer("Time/env_interaction_time", SumMetric()):
                    # flush: the rollout's last row has no next act transfer to ride
                    _process_pending(None)
                # whole host-rollout phase as one span (explicit timestamps: the
                # per-step loop is too hot to wrap per step)
                trace.add_span(
                    "train/collect", _collect_t0, time.perf_counter(), clock="perf", iter=iter_num
                )

            # ----- optimization phase: single jitted call (GAE + epochs x minibatches).
            # The fused path already ran its update inside the one program above.
            if fused_trainer is None:
                if not device_rollout and not use_ingraph:
                    local_data = rb.to_arrays(dtype=np.float32)
                    if cfg.buffer.size > cfg.algo.rollout_steps:
                        # keep only the last rollout in chronological order (stale/zero rows
                        # beyond the write head would corrupt GAE)
                        idx = np.arange(rb._pos - cfg.algo.rollout_steps, rb._pos) % cfg.buffer.size
                        local_data = {k: v[idx] for k, v in local_data.items()}
                with trace.span("train/update", iter=iter_num), timer(
                    "Time/train_time", SumMetric()
                ):
                    if iter_num == start_iter:
                        # every registered entry point compiled before the first
                        # train dispatch (usually already done: the whole first
                        # rollout overlapped the warmup thread)
                        warmup.wait()
                    rng, train_key = jax.random.split(rng)
                    # ----- per-shard rollout handoff (parallel/handoff.py): the
                    # bulk [T, B, *] rollout is assembled mesh-sharded on the env
                    # axis — one put per device shard, no full-batch replication,
                    # no post-put host-side copy; only the small bootstrap values
                    # still replicate. GAE then runs shard-local over B.
                    if use_ingraph:
                        device_data = handoff.shard_put(ingraph_data, runtime.mesh, batch_axis=1)
                        next_values = runtime.replicate(ingraph_next_values)
                    elif device_rollout:
                        # the completed HBM rollout and the bootstrap values move
                        # player-device -> trainer-mesh directly (ownership
                        # transfers out of the buffer, so the train fn's view is
                        # never aliased by next iteration's donated writes)
                        jax_obs = prepare_obs(runtime, next_obs, cnn_keys=cnn_keys, num_envs=n_envs)
                        device_data = handoff.shard_put(rb.rollout(), runtime.mesh, batch_axis=1)
                        next_values = runtime.replicate(player.get_values(jax_obs))
                    else:
                        # bootstrap values come from the player device; the host
                        # rollout enters the mesh shard-at-put
                        jax_obs = prepare_obs(runtime, next_obs, cnn_keys=cnn_keys, num_envs=n_envs)
                        next_values = np.asarray(player.get_values(jax_obs))
                        device_data = handoff.shard_put(
                            {k: v for k, v in local_data.items() if k not in ("returns", "advantages")},
                            runtime.mesh,
                            batch_axis=1,
                        )
                    # chaos seam for the (possibly microbatched) gradient-sync
                    # dispatch — the split-path twin of train.fused_update above
                    failpoints.failpoint(
                        "train.grad_sync", iter=iter_num, microbatches=overlap.microbatches(cfg)
                    )
                    params, opt_state, flat_params, train_metrics = train_fn(
                        params,
                        opt_state,
                        device_data,
                        next_values,
                        train_key,
                        jnp.float32(cfg.algo.clip_coef),
                        jnp.float32(cfg.algo.ent_coef),
                        jnp.float32(sentinel.lr_scale),
                    )
                    # refresh the player's copy with ONE cross-backend transfer; the next
                    # rollout implicitly waits for (only) the params it needs
                    player.params = params_sync.pull(flat_params, player_sync_device)
                    if not timer.disabled:  # sync only when the train phase is being timed
                        jax.block_until_ready(params)
                train_step += world_size

            if cfg.metric.log_level > 0:
                if aggregator:
                    aggregator.update_from_device(train_metrics)
                logger.log_metrics({"Info/clip_coef": cfg.algo.clip_coef, "Info/ent_coef": cfg.algo.ent_coef}, policy_step)
                if policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters:
                    _drain_t0 = time.perf_counter()
                    overlap_s, overlap_steps = stepper.drain_overlap()
                    if overlap_s > 0:
                        # env-step throughput absorbed into the overlap window
                        # (env time hidden behind device dispatch + host bookkeeping)
                        sps_overlap = overlap_steps * n_envs * cfg.env.action_repeat / overlap_s
                        if aggregator and "Time/sps_pipeline_overlap" in aggregator:
                            aggregator.update("Time/sps_pipeline_overlap", sps_overlap)
                        else:
                            logger.log_metrics({"Time/sps_pipeline_overlap": sps_overlap}, policy_step)
                    if aggregator and not aggregator.disabled:
                        logger.log_metrics(aggregator.compute(), policy_step)
                        aggregator.reset()
                    if not timer.disabled:
                        timer_metrics = timer.compute()
                        if timer_metrics.get("Time/train_time", 0) > 0:
                            logger.log_metrics(
                                {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                                policy_step,
                            )
                            # MFU from the compiler's own cost model: the train
                            # fn's per-call FLOPs were captured off
                            # cost_analysis() when its executable AOT-compiled
                            _train_gfn = fused_trainer.step_fn if fused_trainer is not None else train_fn
                            _mfu = tel_device.mfu(
                                getattr(_train_gfn, "last_step_flops", None),
                                timer_metrics["Time/train_time"] / max(train_step - last_train, 1),
                                runtime.device,
                            )
                            if _mfu is not None:
                                logger.log_metrics({"Time/mfu": _mfu}, policy_step)
                        if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                            logger.log_metrics(
                                {
                                    "Time/sps_env_interaction": (
                                        (policy_step - last_log) / world_size * cfg.env.action_repeat
                                    )
                                    / timer_metrics["Time/env_interaction_time"]
                                },
                                policy_step,
                            )
                        timer.reset()
                    trace.add_span(
                        "train/metric_drain",
                        _drain_t0,
                        time.perf_counter(),
                        clock="perf",
                        step=policy_step,
                    )
                    last_log = policy_step
                    last_train = train_step

            # Anneal coefficients (lr annealing lives in the optax schedule)
            if cfg.algo.anneal_clip_coef:
                cfg.algo.clip_coef = polynomial_decay(
                    iter_num, initial=initial_clip_coef, final=0.0, max_decay_steps=total_iters, power=1.0
                )
            if cfg.algo.anneal_ent_coef:
                cfg.algo.ent_coef = polynomial_decay(
                    iter_num, initial=initial_ent_coef, final=0.0, max_decay_steps=total_iters, power=1.0
                )

            resilience.enforce_nonfinite_policy(ft, train_metrics)
            env_deltas = resilience.drain_env_counters(envs, aggregator)
            jax_compile.drain_compile_counters(aggregator)
            if iter_num == start_iter:
                # steady-state watermark: everything this loop will ever compile
                # has compiled; any retrace from here is a perf cliff
                jax_compile.mark_steady()

            # ----- health sentinel (core/health.py): one check per iteration over
            # the metrics this loop already produced; detections climb the
            # warn -> backoff (lr_scale operand above) -> rollback ladder
            action = sentinel.observe(policy_step, train_metrics=train_metrics, env_counters=env_deltas)
            if action.rollback:
                rb_state = sentinel.take_rollback_state(os.path.join(log_dir, "checkpoint"))
                if rb_state is not None:
                    params = runtime.place_params(
                        jax.tree_util.tree_map(jnp.asarray, rb_state["agent"])
                    )
                    opt_state = runtime.place_params(
                        jax.tree_util.tree_map(jnp.asarray, rb_state["optimizer"])
                    )
                    if "rng" in rb_state:
                        rng = jnp.asarray(rb_state["rng"])
                        player_rng = jax.device_put(
                            jnp.asarray(rb_state["player_rng"]), runtime.player_device
                        )
                    player.params = params_sync.pull(params_sync.ravel(params), player_sync_device)
                    if sentinel.reseed_envs:
                        # drop the in-flight transition (it was produced by the
                        # poisoned policy) and restart the streams on a fresh seed
                        pending.clear()
                        reset_obs = envs.reset(seed=cfg.seed + iter_num)[0]
                        next_obs = {}
                        for k in obs_keys:
                            _obs = reset_obs[k]
                            if k in cnn_keys:
                                _obs = _obs.reshape(n_envs, -1, *_obs.shape[-2:])
                            next_obs[k] = _obs
                            step_data[k] = _obs[np.newaxis]
                        # the fused sharded step expects its carry back in the
                        # mesh layout after any reset
                        if fused_trainer is not None:
                            fused_trainer.shard_carry()
                    runtime.print(
                        f"Health rollback at policy_step={policy_step}: restored certified "
                        "checkpoint, training continues."
                    )
            sentinel.drain(aggregator)

            if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
                iter_num == total_iters and cfg.checkpoint.save_last
            ):
                last_checkpoint = policy_step
                ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{runtime.global_rank}.ckpt")
                with trace.span("train/checkpoint", step=policy_step):
                    runtime.call(
                        "on_checkpoint_coupled",
                        ckpt_path=ckpt_path,
                        state=_ckpt_state(),
                        healthy=sentinel.certifiable,
                        policy_step=policy_step,
                    )

            guard.completed_iteration()
            if guard.should_stop:
                if last_checkpoint != policy_step:  # periodic save above already covered this step
                    last_checkpoint = policy_step
                    ckpt_path = os.path.join(
                        log_dir, f"checkpoint/ckpt_{policy_step}_{runtime.global_rank}.ckpt"
                    )
                    runtime.call(
                        "on_checkpoint_coupled",
                        ckpt_path=ckpt_path,
                        state=_ckpt_state(),
                        healthy=sentinel.certifiable,
                        policy_step=policy_step,
                    )
                runtime.print(
                    f"Preemption ({guard.describe()}) at iteration {iter_num}: emergency "
                    "checkpoint saved, exiting cleanly for resume."
                )
                break

    profiler.close()
    if trace.enabled() and runtime.is_global_zero and log_dir:
        try:
            trace.export(os.path.join(log_dir, "telemetry", "trace.json"))
        except OSError:
            pass
    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        if use_ingraph:
            ingraph_envs.test(player, runtime, cfg, log_dir)
        else:
            test(player, runtime, cfg, log_dir)
    if logger:
        logger.finalize()
