"""SAC-AE utilities (reference sheeprl/algos/sac_ae/utils.py)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
    "Loss/reconstruction_loss",
}
# Compilation-management counters (core/compile.py), drained once per iteration.
AGGREGATOR_KEYS |= {
    "Compile/retraces",
    "Compile/cache_hits",
    "Compile/cache_misses",
    "Time/compile_seconds",
}
MODELS_TO_REGISTER = {"agent", "encoder", "decoder"}


def preprocess_obs(obs: jax.Array, key: jax.Array, bits: int = 8) -> jax.Array:
    """Bit-reduction + uniform dequantization noise (reference utils.py:68-76,
    from https://arxiv.org/abs/1807.03039). Input uint8-valued floats [0, 255]."""
    bins = 2**bits
    if bits < 8:
        obs = jnp.floor(obs / 2 ** (8 - bits))
    obs = obs / bins
    obs = obs + jax.random.uniform(key, obs.shape, dtype=obs.dtype) / bins
    return obs - 0.5


def prepare_obs(
    runtime, obs: Dict[str, np.ndarray], cnn_keys: Sequence[str] = [], num_envs: int = 1, **kwargs
) -> Dict[str, jax.Array]:
    """cnn keys -> [0,1] floats with stacked frames folded into channels."""
    device = runtime.player_device if runtime is not None else None
    out = {}
    for k, v in obs.items():
        arr = np.asarray(v, dtype=np.float32)
        if k in cnn_keys:
            arr = arr.reshape(num_envs, -1, *arr.shape[-2:]) / 255.0
        else:
            arr = arr.reshape(num_envs, -1)
        # committed to the player device: an uncommitted array would let the
        # policy jit follow mesh-resident leaves onto the accelerator
        out[k] = jax.device_put(arr, device) if device is not None else jnp.asarray(arr)
    return out


def test(player, runtime, cfg, log_dir: str) -> None:
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=cfg.seed)[0]
    while not done:
        jax_obs = prepare_obs(runtime, obs, cnn_keys=cfg.algo.cnn_keys.encoder, num_envs=1)
        action = np.asarray(player.get_actions(jax_obs, greedy=True))[0]
        obs, reward, terminated, truncated, _ = env.step(action.reshape(env.action_space.shape))
        done = terminated or truncated
        cumulative_rew += reward
        if cfg.dry_run:
            done = True
    if cfg.metric.log_level > 0:
        runtime.print(f"Test - Reward: {cumulative_rew}")
        if getattr(runtime, "logger", None) is not None:
            runtime.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()


def log_models_from_checkpoint(runtime, env, cfg, state) -> Dict[str, Any]:
    """Register the SAC-AE agent (+ its encoder/decoder subtrees) from a checkpoint
    (reference sac_ae/utils.py logs agent, encoder, decoder)."""
    del env
    from sheeprl_tpu.algos.sac_ae.agent import SACAEParams
    from sheeprl_tpu.utils.model_manager import log_model

    agent = state["agent"]
    if not isinstance(agent, SACAEParams):
        agent = SACAEParams(*agent) if isinstance(agent, (tuple, list)) else SACAEParams(**agent)
    return {
        "agent": log_model(runtime, cfg, "agent", agent),
        "encoder": log_model(runtime, cfg, "encoder", agent.encoder),
        "decoder": log_model(runtime, cfg, "decoder", agent.decoder),
    }
