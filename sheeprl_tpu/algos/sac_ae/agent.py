"""SAC-AE agent: pixel SAC with a reconstruction autoencoder.

Parity with reference sheeprl/algos/sac_ae/agent.py — CNNEncoder (:26, 4x conv3x3
stride [2,1,1,1] + tanh/LayerNorm fc), MLPEncoder (:89), MLPDecoder (:122),
CNNDecoder (:153), SACAEQFunction (:204), SACAECritic (:226),
SACAEContinuousActor (:240, tanh-rescaled log-std), SACAEAgent (:321),
SACAEPlayer (:453), build_agent (:505).

JAX design note: the reference ties the actor-encoder conv weights to the critic
encoder (SAC-AE paper trick). Here there is ONE encoder param tree; the actor simply
applies it under ``stop_gradient`` (``detach_encoder_features`` in the reference) —
same semantics, no weight-tying machinery.
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.algos.sac.agent import action_scale_bias, actor_action_and_log_prob
from sheeprl_tpu.models.models import CNN, MLP, DeCNN, LayerNorm
from sheeprl_tpu.utils.utils import host_float32

LOG_STD_MAX = 2
LOG_STD_MIN = -10


class SACAECNNEncoder(nn.Module):
    in_channels: int
    features_dim: int
    keys: Sequence[str]
    screen_size: int = 64
    cnn_channels_multiplier: int = 1
    dtype: Any = jnp.float32

    @property
    def conv_output_shape(self) -> Tuple[int, int, int]:
        # 4 convs k3: stride 2 then three stride 1 -> size = (s-1)//2 - 3 + 1 rules
        s = (self.screen_size - 3) // 2 + 1
        for _ in range(3):
            s = s - 3 + 1
        return (32 * self.cnn_channels_multiplier, s, s)

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array], detach_encoder_features: bool = False) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-3)
        ch = 32 * self.cnn_channels_multiplier
        x = CNN(
            input_channels=self.in_channels,
            hidden_channels=[ch, ch, ch, ch],
            layer_args=[
                {"kernel_size": 3, "stride": 2},
                {"kernel_size": 3, "stride": 1},
                {"kernel_size": 3, "stride": 1},
                {"kernel_size": 3, "stride": 1},
            ],
            dtype=self.dtype,
        )(x)
        x = x.reshape(x.shape[0], -1)
        if detach_encoder_features:
            x = jax.lax.stop_gradient(x)
        x = MLP(
            input_dims=1,
            hidden_sizes=(self.features_dim,),
            activation="tanh",
            layer_norm=True,
            dtype=self.dtype,
        )(x)
        return x.astype(jnp.float32)


class SACAEMLPEncoder(nn.Module):
    input_dim: int
    keys: Sequence[str]
    dense_units: int = 1024
    mlp_layers: int = 3
    dense_act: str = "relu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array], detach_encoder_features: bool = False) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        x = MLP(
            input_dims=self.input_dim,
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )(x)
        if detach_encoder_features:
            x = jax.lax.stop_gradient(x)
        return x.astype(jnp.float32)


class SACAEEncoder(nn.Module):
    """MultiEncoder with detach pass-through (reference MultiEncoder usage)."""

    cnn_encoder: Optional[nn.Module]
    mlp_encoder: Optional[nn.Module]

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array], detach_encoder_features: bool = False) -> jax.Array:
        outs = []
        if self.cnn_encoder is not None:
            outs.append(self.cnn_encoder(obs, detach_encoder_features))
        if self.mlp_encoder is not None:
            outs.append(self.mlp_encoder(obs, detach_encoder_features))
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=-1)


class SACAECNNDecoder(nn.Module):
    conv_output_shape: Tuple[int, int, int]
    features_dim: int
    keys: Sequence[str]
    channels: Sequence[int]
    screen_size: int = 64
    cnn_channels_multiplier: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> Dict[str, jax.Array]:
        ch = 32 * self.cnn_channels_multiplier
        x = MLP(input_dims=1, hidden_sizes=(prod(self.conv_output_shape),), dtype=self.dtype)(x)
        x = x.reshape(-1, *self.conv_output_shape)
        x = DeCNN(
            input_channels=ch,
            hidden_channels=[ch, ch, ch],
            layer_args=[
                {"kernel_size": 3, "stride": 1},
                {"kernel_size": 3, "stride": 1},
                {"kernel_size": 3, "stride": 1},
            ],
            dtype=self.dtype,
        )(x)
        x = DeCNN(
            input_channels=ch,
            hidden_channels=[sum(self.channels)],
            layer_args=[{"kernel_size": 3, "stride": 2, "output_padding": 1}],
            activation=None,
            dtype=self.dtype,
        )(x).astype(jnp.float32)
        out: Dict[str, jax.Array] = {}
        start = 0
        for k, c in zip(self.keys, self.channels):
            out[k] = x[..., start : start + c, :, :]
            start += c
        return out


class SACAEMLPDecoder(nn.Module):
    input_dim: int
    output_dims: Sequence[int]
    keys: Sequence[str]
    dense_units: int = 1024
    mlp_layers: int = 3
    dense_act: str = "relu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> Dict[str, jax.Array]:
        x = MLP(
            input_dims=self.input_dim,
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )(x)
        return {
            k: nn.Dense(d, dtype=self.dtype)(x).astype(jnp.float32) for k, d in zip(self.keys, self.output_dims)
        }


class SACAEDecoder(nn.Module):
    cnn_decoder: Optional[nn.Module]
    mlp_decoder: Optional[nn.Module]

    @nn.compact
    def __call__(self, x: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_decoder is not None:
            out.update(self.cnn_decoder(x))
        if self.mlp_decoder is not None:
            out.update(self.mlp_decoder(x))
        return out


class SACAEQFunction(nn.Module):
    hidden_size: int = 1024
    output_dim: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, features: jax.Array, action: jax.Array) -> jax.Array:
        x = jnp.concatenate([features, action], axis=-1)
        return MLP(
            input_dims=1,
            output_dim=self.output_dim,
            hidden_sizes=(self.hidden_size, self.hidden_size),
            dtype=self.dtype,
        )(x).astype(jnp.float32)


class SACAEActorHead(nn.Module):
    """Actor MLP over encoder features; tanh-rescaled log-std (reference :240-320)."""

    action_dim: int
    hidden_size: int = 1024
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, features: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = MLP(input_dims=1, hidden_sizes=(self.hidden_size, self.hidden_size), dtype=self.dtype)(features)
        mean = nn.Dense(self.action_dim, dtype=self.dtype)(x).astype(jnp.float32)
        log_std = nn.Dense(self.action_dim, dtype=self.dtype)(x).astype(jnp.float32)
        log_std = jnp.tanh(log_std)
        log_std = LOG_STD_MIN + 0.5 * (LOG_STD_MAX - LOG_STD_MIN) * (log_std + 1)
        return mean, log_std


class SACAEParams(NamedTuple):
    encoder: Any
    target_encoder: Any
    qfs: Any  # stacked ensemble
    target_qfs: Any
    actor: Any
    decoder: Any
    log_alpha: jax.Array


class SACAEPlayer:
    """Rollout/eval policy: encoder + actor head (reference SACAEPlayer :453)."""

    def __init__(self, encoder, actor_head, params: SACAEParams, action_scale, action_bias):
        self.encoder = encoder
        self.actor_head = actor_head
        self.encoder_params = params.encoder
        self.actor_params = params.actor
        self.action_scale = action_scale
        self.action_bias = action_bias

        def _act(enc_params, actor_params, obs, key):
            feats = encoder.apply(enc_params, obs)
            mean, log_std = actor_head.apply(actor_params, feats)
            action, _ = actor_action_and_log_prob(mean, log_std, key, action_scale, action_bias)
            # host_float32: actions are pulled to host / stored f32 (bf16 degrades
            # to |V2 through the remote-TPU tunnel)
            return host_float32(action)

        def _greedy(enc_params, actor_params, obs):
            feats = encoder.apply(enc_params, obs)
            mean, _ = actor_head.apply(actor_params, feats)
            return host_float32(jnp.tanh(mean) * action_scale + action_bias)

        self._act = jax_compile.guarded_jit(_act, name="sac_ae.act")
        self._greedy = jax_compile.guarded_jit(_greedy, name="sac_ae.greedy")

    def get_actions(self, obs, key=None, greedy: bool = False):
        if greedy:
            return self._greedy(self.encoder_params, self.actor_params, obs)
        return self._act(self.encoder_params, self.actor_params, obs, key)

    __call__ = get_actions


def build_agent(
    runtime,
    cfg,
    obs_space: gymnasium.spaces.Dict,
    action_space: gymnasium.spaces.Box,
    agent_state: Optional[Any] = None,
):
    """Returns (modules dict, params: SACAEParams, player). Reference: agent.py:505."""
    act_dim = prod(action_space.shape)
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_channels = [prod(obs_space[k].shape[:-2]) for k in cnn_keys]
    mlp_dims = [obs_space[k].shape[0] for k in mlp_keys]
    cnn_encoder = (
        SACAECNNEncoder(
            in_channels=sum(cnn_channels),
            features_dim=cfg.algo.encoder.features_dim,
            keys=tuple(cnn_keys),
            screen_size=cfg.env.screen_size,
            cnn_channels_multiplier=cfg.algo.encoder.cnn_channels_multiplier,
            dtype=runtime.compute_dtype,
        )
        if cnn_keys
        else None
    )
    mlp_encoder = (
        SACAEMLPEncoder(
            sum(mlp_dims),
            tuple(mlp_keys),
            cfg.algo.encoder.dense_units,
            cfg.algo.encoder.mlp_layers,
            cfg.algo.encoder.dense_act,
            cfg.algo.encoder.layer_norm,
            dtype=runtime.compute_dtype,
        )
        if mlp_keys
        else None
    )
    encoder = SACAEEncoder(cnn_encoder, mlp_encoder)
    features_dim = (cfg.algo.encoder.features_dim if cnn_keys else 0) + (
        cfg.algo.encoder.dense_units if mlp_keys else 0
    )
    cnn_decoder = (
        SACAECNNDecoder(
            cnn_encoder.conv_output_shape,
            features_dim=features_dim,
            keys=tuple(cfg.algo.cnn_keys.decoder),
            channels=tuple(cnn_channels),
            screen_size=cfg.env.screen_size,
            cnn_channels_multiplier=cfg.algo.decoder.cnn_channels_multiplier,
            dtype=runtime.compute_dtype,
        )
        if cfg.algo.cnn_keys.decoder
        else None
    )
    mlp_decoder = (
        SACAEMLPDecoder(
            features_dim,
            tuple(mlp_dims),
            tuple(cfg.algo.mlp_keys.decoder),
            cfg.algo.decoder.dense_units,
            cfg.algo.decoder.mlp_layers,
            cfg.algo.decoder.dense_act,
            cfg.algo.decoder.layer_norm,
            dtype=runtime.compute_dtype,
        )
        if cfg.algo.mlp_keys.decoder
        else None
    )
    decoder = SACAEDecoder(cnn_decoder, mlp_decoder)
    qf = SACAEQFunction(hidden_size=cfg.algo.critic.hidden_size, output_dim=1, dtype=runtime.compute_dtype)
    actor_head = SACAEActorHead(act_dim, cfg.algo.actor.hidden_size, dtype=runtime.compute_dtype)

    key = jax.random.PRNGKey(cfg.seed)
    k_enc, k_qf, k_actor, k_dec = jax.random.split(key, 4)
    sample_obs = {}
    for k in cnn_keys:
        shape = obs_space[k].shape
        sample_obs[k] = jnp.zeros((1, prod(shape[:-2]), *shape[-2:]), dtype=jnp.float32)
    for k in mlp_keys:
        sample_obs[k] = jnp.zeros((1, *obs_space[k].shape), dtype=jnp.float32)
    enc_params = encoder.init(k_enc, sample_obs)
    feats = encoder.apply(enc_params, sample_obs)
    qf_keys = jax.random.split(k_qf, cfg.algo.critic.n)
    qfs_params = jax.vmap(lambda kk: qf.init(kk, feats, jnp.zeros((1, act_dim))))(qf_keys)
    actor_params = actor_head.init(k_actor, feats)
    dec_params = decoder.init(k_dec, feats)
    params = SACAEParams(
        encoder=enc_params,
        target_encoder=jax.tree_util.tree_map(jnp.array, enc_params),
        qfs=qfs_params,
        target_qfs=jax.tree_util.tree_map(jnp.array, qfs_params),
        actor=actor_params,
        decoder=dec_params,
        log_alpha=jnp.log(jnp.asarray([cfg.algo.alpha.alpha], dtype=jnp.float32)),
    )
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
        if not isinstance(params, SACAEParams):
            params = SACAEParams(*params) if isinstance(params, (tuple, list)) else SACAEParams(**params)
    params = runtime.place_params(params)
    action_scale, action_bias = action_scale_bias(action_space.low, action_space.high)
    player = SACAEPlayer(encoder, actor_head, params, action_scale, action_bias)
    modules = {"encoder": encoder, "decoder": decoder, "qf": qf, "actor_head": actor_head}
    return modules, params, player
