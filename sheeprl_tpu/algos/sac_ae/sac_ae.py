"""SAC-AE training (reference sheeprl/algos/sac_ae/sac_ae.py:35-120 train, :120 main).

Pixel SAC + autoencoder. One jitted call scans the G gradient steps of an iteration;
each step: critic update -> conditional target/encoder EMA (freqs on the cumulative
update counter) -> conditional actor+alpha update (detached encoder features) ->
conditional decoder/encoder reconstruction update with bit-reduced + dequantized
targets (reference utils.py:68-76).
"""

from __future__ import annotations

import os
import warnings
from math import prod
from typing import Any, Dict, NamedTuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.algos.sac.agent import action_scale_bias, actor_action_and_log_prob
from sheeprl_tpu.algos.sac.loss import critic_loss, entropy_loss, policy_loss
from sheeprl_tpu.algos.sac_ae.agent import SACAEParams, build_agent
from sheeprl_tpu.algos.sac_ae.utils import prepare_obs, preprocess_obs, test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.core import resilience
from sheeprl_tpu.utils.env import finished_episodes, make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.profiler import TraceProfiler
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import PlayerParamsSync, Ratio, polyak_update, save_configs


class SACAEOptStates(NamedTuple):
    qf: Any
    actor: Any
    alpha: Any
    encoder: Any
    decoder: Any


def make_train_fn(modules, cfg, runtime, action_scale, action_bias, target_entropy, params_sync=None):
    encoder, decoder, qf, actor_head = (
        modules["encoder"],
        modules["decoder"],
        modules["qf"],
        modules["actor_head"],
    )
    n_critics = int(cfg.algo.critic.n)
    gamma = float(cfg.algo.gamma)
    tau = float(cfg.algo.tau)
    encoder_tau = float(cfg.algo.encoder.tau)
    target_freq = int(cfg.algo.critic.per_rank_target_network_update_freq)
    actor_freq = int(cfg.algo.actor.per_rank_update_freq)
    decoder_freq = int(cfg.algo.decoder.per_rank_update_freq)
    l2_lambda = float(cfg.algo.decoder.l2_lambda)
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_dec_keys = list(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = list(cfg.algo.mlp_keys.decoder)
    data_sharding = NamedSharding(runtime.mesh, P("data"))

    qf_tx = instantiate(dict(cfg.algo.critic.optimizer))()
    actor_tx = instantiate(dict(cfg.algo.actor.optimizer))()
    alpha_tx = instantiate(dict(cfg.algo.alpha.optimizer))()
    encoder_tx = instantiate(dict(cfg.algo.encoder.optimizer))()
    decoder_tx = instantiate(dict(cfg.algo.decoder.optimizer))()

    def init_opt(params: SACAEParams) -> SACAEOptStates:
        return SACAEOptStates(
            qf=qf_tx.init(params.qfs),
            actor=actor_tx.init(params.actor),
            alpha=alpha_tx.init(params.log_alpha),
            encoder=encoder_tx.init(params.encoder),
            decoder=decoder_tx.init(params.decoder),
        )

    def normalize(batch, prefix=""):
        out = {}
        for k in cnn_keys + mlp_keys:
            v = batch[prefix + k]
            out[k] = v / 255.0 if k in cnn_keys else v
        return out

    def q_ensemble(qfs_params, feats, action):
        qs = jax.vmap(lambda p: qf.apply(p, feats, action))(qfs_params)
        return jnp.moveaxis(qs[..., 0], 0, -1)

    def single_update(carry, inp):
        params, opt_states, counter = carry
        batch, key = inp
        batch = jax.tree_util.tree_map(lambda v: jax.lax.with_sharding_constraint(v, data_sharding), batch)
        obs = normalize(batch)
        next_obs = normalize(batch, prefix="next_")
        alpha = jnp.exp(params.log_alpha)
        key, k_next, k_actor, k_noise = jax.random.split(key, 4)

        # ---- critic update
        next_feats_actor = encoder.apply(params.encoder, next_obs)
        mean, log_std = actor_head.apply(params.actor, next_feats_actor)
        next_actions, next_logp = actor_action_and_log_prob(mean, log_std, k_next, action_scale, action_bias)
        next_feats_target = encoder.apply(params.target_encoder, next_obs)
        next_q = q_ensemble(params.target_qfs, next_feats_target, next_actions)
        min_next_q = jnp.min(next_q, axis=-1, keepdims=True) - alpha * next_logp
        target_q = jax.lax.stop_gradient(batch["rewards"] + (1 - batch["terminated"]) * gamma * min_next_q)

        def qf_loss_fn(trainable):
            enc_p, qfs_p = trainable
            feats = encoder.apply(enc_p, obs)
            qs = q_ensemble(qfs_p, feats, batch["actions"])
            return critic_loss(qs, target_q, n_critics)

        qf_l, (enc_grads_q, qf_grads) = jax.value_and_grad(qf_loss_fn)((params.encoder, params.qfs))
        qf_updates, qf_opt = qf_tx.update(qf_grads, opt_states.qf, params.qfs)
        new_qfs = optax.apply_updates(params.qfs, qf_updates)
        enc_updates, enc_opt = encoder_tx.update(enc_grads_q, opt_states.encoder, params.encoder)
        new_encoder = optax.apply_updates(params.encoder, enc_updates)

        # ---- conditional target EMAs
        do_ema = counter % target_freq == 0
        new_target_qfs = jax.tree_util.tree_map(
            lambda p, t: jnp.where(do_ema, tau * p + (1 - tau) * t, t), new_qfs, params.target_qfs
        )
        new_target_encoder = jax.tree_util.tree_map(
            lambda p, t: jnp.where(do_ema, encoder_tau * p + (1 - encoder_tau) * t, t),
            new_encoder,
            params.target_encoder,
        )

        # ---- conditional actor + alpha update (detached encoder features)
        do_actor = counter % actor_freq == 0

        def actor_loss_fn(actor_params):
            feats = jax.lax.stop_gradient(encoder.apply(new_encoder, obs))
            m, ls = actor_head.apply(actor_params, feats)
            acts, logp = actor_action_and_log_prob(m, ls, k_actor, action_scale, action_bias)
            qs = q_ensemble(new_qfs, feats, acts)
            min_q = jnp.min(qs, axis=-1, keepdims=True)
            return policy_loss(alpha, logp, min_q), logp

        (actor_l, logp), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(params.actor)
        actor_grads = jax.tree_util.tree_map(lambda g: jnp.where(do_actor, g, jnp.zeros_like(g)), actor_grads)
        actor_updates, actor_opt = actor_tx.update(actor_grads, opt_states.actor, params.actor)
        new_actor = jax.tree_util.tree_map(
            lambda p, u: jnp.where(do_actor, p + u, p), params.actor, actor_updates
        )

        def alpha_loss_fn(log_alpha):
            return entropy_loss(log_alpha, jax.lax.stop_gradient(logp), target_entropy)

        alpha_l, alpha_grads = jax.value_and_grad(alpha_loss_fn)(params.log_alpha)
        alpha_grads = jnp.where(do_actor, alpha_grads, jnp.zeros_like(alpha_grads))
        alpha_updates, alpha_opt = alpha_tx.update(alpha_grads, opt_states.alpha, params.log_alpha)
        new_log_alpha = jnp.where(do_actor, params.log_alpha + alpha_updates, params.log_alpha)

        # ---- conditional reconstruction update (encoder + decoder)
        do_dec = counter % decoder_freq == 0

        def recon_loss_fn(trainable):
            enc_p, dec_p = trainable
            hidden = encoder.apply(enc_p, obs)
            rec = decoder.apply(dec_p, hidden)
            loss = jnp.float32(0)
            for k in cnn_dec_keys + mlp_dec_keys:
                if k in cnn_dec_keys:
                    target = preprocess_obs(batch[k], k_noise, bits=5)
                else:
                    target = batch[k]
                loss = loss + ((target - rec[k]) ** 2).mean() + l2_lambda * (0.5 * (hidden**2).sum(1)).mean()
            return loss

        rec_l, (enc_grads_r, dec_grads) = jax.value_and_grad(recon_loss_fn)((new_encoder, params.decoder))
        enc_grads_r = jax.tree_util.tree_map(lambda g: jnp.where(do_dec, g, jnp.zeros_like(g)), enc_grads_r)
        dec_grads = jax.tree_util.tree_map(lambda g: jnp.where(do_dec, g, jnp.zeros_like(g)), dec_grads)
        enc_updates2, enc_opt = encoder_tx.update(enc_grads_r, enc_opt, new_encoder)
        new_encoder = jax.tree_util.tree_map(
            lambda p, u: jnp.where(do_dec, p + u, p), new_encoder, enc_updates2
        )
        dec_updates, dec_opt = decoder_tx.update(dec_grads, opt_states.decoder, params.decoder)
        new_decoder = jax.tree_util.tree_map(
            lambda p, u: jnp.where(do_dec, p + u, p), params.decoder, dec_updates
        )

        new_params = SACAEParams(
            encoder=new_encoder,
            target_encoder=new_target_encoder,
            qfs=new_qfs,
            target_qfs=new_target_qfs,
            actor=new_actor,
            decoder=new_decoder,
            log_alpha=new_log_alpha,
        )
        new_opt = SACAEOptStates(qf=qf_opt, actor=actor_opt, alpha=alpha_opt, encoder=enc_opt, decoder=dec_opt)
        return (new_params, new_opt, counter + 1), jnp.stack([qf_l, actor_l, alpha_l, rec_l])

    def train(params, opt_states, batches, key, counter):
        g = next(iter(batches.values())).shape[0]
        keys = jax.random.split(key, g)
        (params, opt_states, counter), losses = jax.lax.scan(
            single_update, (params, opt_states, counter), (batches, keys)
        )
        mean_losses = losses.mean(axis=0)
        # flat (encoder, actor) for the one-transfer player refresh (PlayerParamsSync)
        flat_player = params_sync.ravel((params.encoder, params.actor)) if params_sync is not None else None
        return params, opt_states, counter, flat_player, {
            "Loss/value_loss": mean_losses[0],
            "Loss/policy_loss": mean_losses[1],
            "Loss/alpha_loss": mean_losses[2],
            "Loss/reconstruction_loss": mean_losses[3],
        }

    return init_opt, jax_compile.guarded_jit(train, name="sac_ae.train", donate_argnums=(0, 1))


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    if "minedojo" in cfg.env.wrapper._target_.lower():
        raise ValueError("MineDojo is not currently supported by SAC-AE agent.")
    world_size = runtime.world_size

    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_state

        state = load_state(cfg.checkpoint.resume_from)

    logger = get_logger(runtime, cfg)
    if logger:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.logger = logger
    runtime.print(f"Log dir: {log_dir}")

    n_envs = cfg.env.num_envs * world_size
    ft = resilience.resolve(cfg)
    envs = resilience.make_supervised_env(
        [
            make_env(cfg, cfg.seed + i, 0, log_dir if runtime.is_global_zero else None, "train", vector_env_idx=i)
            for i in range(n_envs)
        ],
        sync=cfg.env.sync_env,
        ft=ft,
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC-AE agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    obs_keys = cfg.algo.cnn_keys.encoder + cfg.algo.mlp_keys.encoder
    cnn_keys = cfg.algo.cnn_keys.encoder
    if len(obs_keys) == 0:
        raise RuntimeError("You should specify at least one observation key")

    modules, params, player = build_agent(
        runtime, cfg, observation_space, action_space, state["agent"] if state else None
    )
    act_dim = prod(action_space.shape)
    target_entropy = jnp.float32(-act_dim)
    action_scale, action_bias = action_scale_bias(action_space.low, action_space.high)

    params_sync = PlayerParamsSync((player.encoder_params, player.actor_params))
    init_opt, train_fn = make_train_fn(
        modules, cfg, runtime, action_scale, action_bias, target_entropy, params_sync
    )
    # host player starts from host-resident params (see sac.py note)
    player.encoder_params, player.actor_params = params_sync.pull(
        jax.jit(params_sync.ravel)((params.encoder, params.actor)), runtime.player_device
    )
    opt_states = init_opt(params)
    if state:
        opt_states = jax.tree_util.tree_map(jnp.asarray, state["opt_states"])
    opt_states = runtime.place_params(opt_states)
    update_counter = jnp.int32(state["update_counter"]) if state else jnp.int32(1)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    buffer_size = cfg.buffer.size // n_envs if not cfg.dry_run else 1
    if bool(cfg.buffer.get("device", False)):
        raise ValueError(
            "buffer.device=True is currently supported by the Dreamer-family loops "
            "only; use the host buffer here"
        )
    rb = ReplayBuffer(
        buffer_size,
        n_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{runtime.global_rank}"),
        obs_keys=tuple(obs_keys),
    )
    if state and cfg.buffer.checkpoint and "rb" in state:
        rb.load_state_dict(state["rb"])

    last_train = 0
    train_step = 0
    start_iter = (state["iter_num"] // world_size) + 1 if state else 1
    policy_step = state["iter_num"] * cfg.env.num_envs if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(n_envs)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    player_sync_every = max(1, int(cfg.algo.get("player_sync_every", 1)))
    if state:
        ratio.load_state_dict(state["ratio"])

    profiler = TraceProfiler(cfg.metric.get("profiler"), log_dir if runtime.is_global_zero else None)
    rng = jax.random.PRNGKey(cfg.seed)
    player_rng = jax.device_put(jax.random.PRNGKey(cfg.seed + 1), runtime.player_device)

    def to_stored(o, k):
        arr = np.asarray(o[k])
        if k in cnn_keys:
            return arr.reshape(n_envs, -1, *arr.shape[-2:])
        return arr.reshape(n_envs, -1)

    last_flat_player = None
    train_calls = 0
    obs = envs.reset(seed=cfg.seed)[0]
    stored_obs = {k: to_stored(obs, k) for k in obs_keys}

    for iter_num in range(start_iter, total_iters + 1):
        profiler.step(policy_step)
        policy_step += n_envs

        with timer("Time/env_interaction_time", SumMetric()):
            if iter_num < learning_starts:
                actions = envs.action_space.sample()
            else:
                player_rng, act_key = jax.random.split(player_rng)
                jax_obs = prepare_obs(runtime, stored_obs, cnn_keys=cnn_keys, num_envs=n_envs)
                actions = np.asarray(player.get_actions(jax_obs, act_key))
            next_obs, rewards, terminated, truncated, info = envs.step(actions.reshape(envs.action_space.shape))
            stored_next = {k: to_stored(next_obs, k) for k in obs_keys}
            real_next = {k: v.copy() for k, v in stored_next.items()}
            if "final_obs" in info:
                for idx, fo in enumerate(np.asarray(info["final_obs"], dtype=object)):
                    if fo is not None:
                        for k in obs_keys:
                            arr = np.asarray(fo[k])
                            if k in cnn_keys:
                                arr = arr.reshape(-1, *arr.shape[-2:])
                            else:
                                arr = arr.reshape(-1)
                            real_next[k][idx] = arr

        step_data = {k: stored_obs[k][np.newaxis] for k in obs_keys}
        if not cfg.buffer.sample_next_obs:
            for k in obs_keys:
                step_data[f"next_{k}"] = real_next[k][np.newaxis]
        step_data["terminated"] = np.asarray(terminated).reshape(1, n_envs, -1).astype(np.float32)
        step_data["truncated"] = np.asarray(truncated).reshape(1, n_envs, -1).astype(np.float32)
        step_data["actions"] = np.asarray(actions).reshape(1, n_envs, -1).astype(np.float32)
        step_data["rewards"] = np.asarray(rewards, dtype=np.float32).reshape(1, n_envs, -1)
        rb.add(step_data, validate_args=cfg.buffer.validate_args)
        stored_obs = stored_next

        if cfg.metric.log_level > 0:
            for i, (ep_rew, ep_len) in enumerate(finished_episodes(info)):
                if aggregator and "Rewards/rew_avg" in aggregator:
                    aggregator.update("Rewards/rew_avg", ep_rew)
                if aggregator and "Game/ep_len_avg" in aggregator:
                    aggregator.update("Game/ep_len_avg", ep_len)
                runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")

        if iter_num >= learning_starts:
            per_rank_gradient_steps = ratio((policy_step - prefill_steps * n_envs) / world_size)
            if per_rank_gradient_steps > 0:
                with timer("Time/train_time", SumMetric()):
                    g = per_rank_gradient_steps
                    bs = cfg.algo.per_rank_batch_size * world_size
                    sample = rb.sample(batch_size=g * bs, sample_next_obs=cfg.buffer.sample_next_obs)
                    batches = {
                        k: jnp.asarray(np.asarray(v, dtype=np.float32).reshape(g, bs, *v.shape[2:]))
                        for k, v in sample.items()
                    }
                    rng, train_key = jax.random.split(rng)
                    params, opt_states, update_counter, flat_player, train_metrics = train_fn(
                        params, opt_states, batches, train_key, update_counter
                    )
                    # ONE flat cross-backend transfer refreshes the host player; on
                    # remote accelerators cfg.algo.player_sync_every amortizes the
                    # round-trip. The explicit block keeps Time/train_time honest on
                    # locally-attached backends (async dispatch returns instantly).
                    last_flat_player = flat_player
                    # cadence counts TRAIN calls (iter_num can skip sync forever
                    # when Ratio grants steps only on a phase-locked subset)
                    train_calls += 1
                    if train_calls % player_sync_every == 0:
                        player.encoder_params, player.actor_params = params_sync.pull(
                            flat_player, runtime.player_device
                        )
                    if not timer.disabled:
                        # fence ONLY when timing (see sac.py note)
                        jax.block_until_ready(flat_player)
                train_step += world_size * g
                if cfg.metric.log_level > 0 and aggregator:
                    aggregator.update_from_device(train_metrics)

        if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters):
            if aggregator and not aggregator.disabled:
                logger.log_metrics(aggregator.compute(), policy_step)
                aggregator.reset()
            if not timer.disabled:
                timer_metrics = timer.compute()
                if timer_metrics.get("Time/train_time", 0) > 0:
                    logger.log_metrics(
                        {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]}, policy_step
                    )
                if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                    logger.log_metrics(
                        {
                            "Time/sps_env_interaction": (
                                (policy_step - last_log) / world_size * cfg.env.action_repeat
                            )
                            / timer_metrics["Time/env_interaction_time"]
                        },
                        policy_step,
                    )
                timer.reset()
            last_log = policy_step
            last_train = train_step

        resilience.drain_env_counters(envs, aggregator)
        jax_compile.drain_compile_counters(aggregator)
        if train_calls > 0 and not jax_compile.is_steady():
            # everything reachable has compiled once: later traces are drift
            jax_compile.mark_steady()

        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            iter_num == total_iters and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": jax.device_get(params),
                "opt_states": jax.device_get(opt_states),
                "update_counter": int(update_counter),
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{runtime.global_rank}.ckpt")
            runtime.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )

    profiler.close()
    envs.close()
    if last_flat_player is not None:
        # final refresh: player_sync_every may have skipped the last iterations,
        # and test()/model registration must see the final policy
        player.encoder_params, player.actor_params = params_sync.pull(last_flat_player, runtime.player_device)
    if runtime.is_global_zero and cfg.algo.run_test:
        test(player, runtime, cfg, log_dir)
    if logger:
        logger.finalize()
