from sheeprl_tpu.algos.sac_ae import sac_ae  # noqa: F401
from sheeprl_tpu.algos.sac_ae import evaluate  # noqa: F401
