from sheeprl_tpu.algos.p2e_dv1 import p2e_dv1_exploration, p2e_dv1_finetuning  # noqa: F401
from sheeprl_tpu.algos.p2e_dv1 import evaluate  # noqa: F401  (must import after the algorithms register)
