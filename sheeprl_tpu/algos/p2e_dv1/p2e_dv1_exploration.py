"""Plan2Explore (DV1) — exploration phase (reference
sheeprl/algos/p2e_dv1/p2e_dv1_exploration.py:39-801).

One jitted train call per iteration `lax.scan`s over the G gradient steps; each step
fuses the five updates of P2E Algorithm 1: (1) DV1 world-model update, (2) ensemble
update (next-embedding log-likelihood), (3) exploration actor/critic on the
*intrinsic* reward = ensemble prediction variance, (4) zero-shot task actor/critic
on the learned reward model. The ensemble runs as a single vmapped stack (see
agent.Ensembles) rather than the reference's Python loop over N modules.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, NamedTuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.algos.dreamer_v1.loss import actor_loss, critic_loss, reconstruction_loss
from sheeprl_tpu.algos.dreamer_v1.utils import compute_lambda_values
from sheeprl_tpu.algos.dreamer_v2.agent import ActorOutputDV2, expl_amount_schedule
from sheeprl_tpu.algos.dreamer_v2.utils import prepare_obs, test
from sheeprl_tpu.algos.p2e_dv1.agent import P2EDV1Modules, build_agent
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.factory import make_sequential_replay
from sheeprl_tpu.ops.distributions import Bernoulli, Independent, Normal
from sheeprl_tpu.core import resilience
from sheeprl_tpu.utils.env import finished_episodes, final_observations, make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.optim import with_clipping
from sheeprl_tpu.utils.profiler import TraceProfiler
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.algos.dreamer_v1.dreamer_v1 import PLAYER_WM_KEYS
from sheeprl_tpu.utils.utils import DreamerPlayerSync, Ratio, save_configs


class P2EDV1OptStates(NamedTuple):
    world: Any
    ensembles: Any
    actor_task: Any
    critic_task: Any
    actor_exploration: Any
    critic_exploration: Any


METRIC_ORDER = [
    "Loss/world_model_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Loss/ensemble_loss",
    "Rewards/intrinsic",
    "Values_exploration/predicted_values",
    "Values_exploration/lambda_values",
    "Loss/policy_loss_exploration",
    "Loss/value_loss_exploration",
    "Loss/policy_loss_task",
    "Loss/value_loss_task",
    "Grads/world_model",
    "Grads/ensemble",
    "Grads/actor_exploration",
    "Grads/critic_exploration",
    "Grads/actor_task",
    "Grads/critic_task",
]


def make_train_fn(modules: P2EDV1Modules, cfg, runtime, psync=None):
    """Build (init_opt, train): jitted G-step scan over the five P2E updates."""
    rssm = modules.rssm
    ensembles = modules.ensembles
    horizon = int(cfg.algo.horizon)
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    kl_free_nats = float(cfg.algo.world_model.kl_free_nats)
    kl_regularizer = float(cfg.algo.world_model.kl_regularizer)
    continue_scale_factor = float(cfg.algo.world_model.continue_scale_factor)
    use_continues = bool(cfg.algo.world_model.use_continues) and modules.continue_model is not None
    intrinsic_reward_multiplier = float(cfg.algo.intrinsic_reward_multiplier)
    stoch_size = rssm.stoch_state_size
    recurrent_size = rssm.recurrent_model.recurrent_state_size
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_keys_dec = list(cfg.algo.cnn_keys.decoder)
    mlp_keys_dec = list(cfg.algo.mlp_keys.decoder)
    data_sharding = NamedSharding(runtime.mesh, P(None, "data"))

    world_tx = with_clipping(
        instantiate(dict(cfg.algo.world_model.optimizer))(), cfg.algo.world_model.clip_gradients
    )
    ens_tx = with_clipping(instantiate(dict(cfg.algo.ensembles.optimizer))(), cfg.algo.ensembles.clip_gradients)
    actor_tx = with_clipping(instantiate(dict(cfg.algo.actor.optimizer))(), cfg.algo.actor.clip_gradients)
    critic_tx = with_clipping(instantiate(dict(cfg.algo.critic.optimizer))(), cfg.algo.critic.clip_gradients)

    def init_opt(params) -> P2EDV1OptStates:
        return P2EDV1OptStates(
            world=world_tx.init(params["world_model"]),
            ensembles=ens_tx.init(params["ensembles"]),
            actor_task=actor_tx.init(params["actor_task"]),
            critic_task=critic_tx.init(params["critic_task"]),
            actor_exploration=actor_tx.init(params["actor_exploration"]),
            critic_exploration=critic_tx.init(params["critic_exploration"]),
        )

    def behaviour_update(
        actor_mod, critic_mod, wm_params, actor_params, critic_params, actor_opt, critic_opt,
        start_prior, start_recurrent, key, rewards_fn,
    ):
        """Shared imagination + actor/critic update; rewards_fn maps
        (trajectories, imagined_actions) -> [H, TB, 1] rewards."""
        img_keys = jax.random.split(key, horizon)

        def imagine(actor_p, keys):
            def step(carry, k):
                prior, rec_state = carry
                k_act, k_img = jax.random.split(k)
                latent = jnp.concatenate([prior, rec_state], axis=-1)
                out = ActorOutputDV2(actor_mod, actor_mod.apply(actor_p, jax.lax.stop_gradient(latent)))
                act = jnp.concatenate(out.sample_actions(k_act), axis=-1)
                prior, rec_state = rssm.imagination_step(wm_params, prior, rec_state, act, k_img)
                new_latent = jnp.concatenate([prior, rec_state], axis=-1)
                return (prior, rec_state), (new_latent, act)

            _, (latents, acts) = jax.lax.scan(step, (start_prior, start_recurrent), keys)
            return latents, acts

        def actor_loss_fn(actor_p):
            trajectories, imagined_actions = imagine(actor_p, img_keys)
            predicted_values = critic_mod.apply(critic_params, trajectories)
            rewards = rewards_fn(trajectories, imagined_actions)
            if use_continues:
                continues = jax.nn.sigmoid(modules.continue_model.apply(wm_params["continue_model"], trajectories))
            else:
                continues = jnp.ones_like(rewards) * gamma
            lambda_values = compute_lambda_values(
                rewards, predicted_values, continues, predicted_values[-1], horizon, lmbda
            )
            discount = jax.lax.stop_gradient(
                jnp.cumprod(jnp.concatenate([jnp.ones_like(continues[:1]), continues[:-2]], axis=0), axis=0)
            )
            p_loss = actor_loss(discount * lambda_values)
            aux = {
                "trajectories": trajectories,
                "lambda_values": lambda_values,
                "discount": discount,
                "rewards": rewards,
                "predicted_values": predicted_values,
            }
            return p_loss, aux

        (p_loss, aux), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(actor_params)
        actor_grad_norm = optax.global_norm(actor_grads)
        actor_updates, actor_opt = actor_tx.update(actor_grads, actor_opt, actor_params)
        new_actor = optax.apply_updates(actor_params, actor_updates)

        trajectories = jax.lax.stop_gradient(aux["trajectories"])
        lambda_values = jax.lax.stop_gradient(aux["lambda_values"])
        discount = aux["discount"]

        def critic_loss_fn(critic_p):
            qv = Independent(
                Normal(critic_mod.apply(critic_p, trajectories[:-1]), jnp.ones_like(lambda_values)), 1
            )
            return critic_loss(qv.log_prob(lambda_values), discount[..., 0])

        v_loss, critic_grads = jax.value_and_grad(critic_loss_fn)(critic_params)
        critic_grad_norm = optax.global_norm(critic_grads)
        critic_updates, critic_opt = critic_tx.update(critic_grads, critic_opt, critic_params)
        new_critic = optax.apply_updates(critic_params, critic_updates)
        return new_actor, new_critic, actor_opt, critic_opt, p_loss, v_loss, actor_grad_norm, critic_grad_norm, aux

    def one_step(carry, inp):
        params, opt_states = carry
        data, key = inp
        data = jax.tree_util.tree_map(lambda v: jax.lax.with_sharding_constraint(v, data_sharding), data)
        k_wm, k_expl, k_task = jax.random.split(key, 3)

        batch_obs = {k: data[k].astype(jnp.float32) / 255.0 - 0.5 for k in cnn_keys}
        batch_obs.update({k: data[k].astype(jnp.float32) for k in mlp_keys})
        actions = data["actions"].astype(jnp.float32)
        rewards = data["rewards"].astype(jnp.float32)
        terminated = data["terminated"].astype(jnp.float32)

        # ---- (1) world-model update (same objective as DV1, dreamer_v1/loss.py)
        def world_loss_fn(wm_params):
            embedded = modules.encoder.apply(wm_params["encoder"], batch_obs)
            recurrent_states, posteriors, post_ms, prior_ms = rssm.dynamic_scan(wm_params, embedded, actions, k_wm)
            latent_states = jnp.concatenate([posteriors, recurrent_states], axis=-1)
            reconstructed = modules.observation_model.apply(wm_params["observation_model"], latent_states)
            qo_log_probs = {
                k: Independent(
                    Normal(reconstructed[k], jnp.ones_like(reconstructed[k])), reconstructed[k].ndim - 2
                ).log_prob(batch_obs[k])
                for k in cnn_keys_dec + mlp_keys_dec
            }
            # Unlike plain DV1, P2E trains the reward/continue heads on DETACHED
            # latents so task-reward gradients cannot shape the exploration-phase
            # world model (reference p2e_dv1_exploration.py:134-136).
            detached_latents = jax.lax.stop_gradient(latent_states)
            qr_log_prob = Independent(
                Normal(
                    modules.reward_model.apply(wm_params["reward_model"], detached_latents), jnp.ones_like(rewards)
                ),
                1,
            ).log_prob(rewards)
            qc_log_prob = None
            if use_continues:
                qc_log_prob = Independent(
                    Bernoulli(logits=modules.continue_model.apply(wm_params["continue_model"], detached_latents)), 1
                ).log_prob((1.0 - terminated) * gamma)
            loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
                qo_log_probs, qr_log_prob, post_ms[0], post_ms[1], prior_ms[0], prior_ms[1],
                kl_free_nats, kl_regularizer, qc_log_prob, continue_scale_factor,
            )
            post_ent = jnp.sum(0.5 * jnp.log(2 * jnp.pi * jnp.e * post_ms[1] ** 2), axis=-1).mean()
            prior_ent = jnp.sum(0.5 * jnp.log(2 * jnp.pi * jnp.e * prior_ms[1] ** 2), axis=-1).mean()
            aux = {
                "posteriors": posteriors,
                "recurrent_states": recurrent_states,
                "embedded": embedded,
                "kl": kl,
                "state_loss": state_loss,
                "reward_loss": reward_loss,
                "observation_loss": observation_loss,
                "continue_loss": continue_loss,
                "post_entropy": post_ent,
                "prior_entropy": prior_ent,
            }
            return loss, aux

        (world_loss, aux), world_grads = jax.value_and_grad(world_loss_fn, has_aux=True)(params["world_model"])
        world_grad_norm = optax.global_norm(world_grads)
        world_updates, world_opt = world_tx.update(world_grads, opt_states.world, params["world_model"])
        new_wm = optax.apply_updates(params["world_model"], world_updates)

        posteriors = jax.lax.stop_gradient(aux["posteriors"])
        recurrent_states = jax.lax.stop_gradient(aux["recurrent_states"])
        embedded = jax.lax.stop_gradient(aux["embedded"])

        # ---- (2) ensemble update: predict embed[t+1] from (post, h, action)[t]
        # (reference p2e_dv1_exploration.py:168-185)
        ens_input = jnp.concatenate([posteriors, recurrent_states, actions], axis=-1)

        def ensemble_loss_fn(ens_params):
            out = ensembles.apply(ens_params, ens_input)[:, :-1]  # [N, T-1, B, E]
            log_prob = Independent(Normal(out, jnp.ones_like(out)), 1).log_prob(embedded[None, 1:])
            # sum over members of the per-member mean NLL (reference accumulates -=)
            return -(log_prob.mean(axis=(1, 2)).sum())

        ens_loss, ens_grads = jax.value_and_grad(ensemble_loss_fn)(params["ensembles"])
        ens_grad_norm = optax.global_norm(ens_grads)
        ens_updates, ens_opt = ens_tx.update(ens_grads, opt_states.ensembles, params["ensembles"])
        new_ens = optax.apply_updates(params["ensembles"], ens_updates)

        start_prior = posteriors.reshape(1, -1, stoch_size)[0]
        start_recurrent = recurrent_states.reshape(1, -1, recurrent_size)[0]

        # ---- (3) exploration behaviour on the intrinsic (disagreement) reward
        def intrinsic_rewards(trajectories, imagined_actions):
            ens_in = jax.lax.stop_gradient(jnp.concatenate([trajectories, imagined_actions], axis=-1))
            preds = ensembles.apply(new_ens, ens_in)  # [N, H, TB, E]
            return preds.var(axis=0).mean(axis=-1, keepdims=True) * intrinsic_reward_multiplier

        (
            new_actor_expl, new_critic_expl, actor_expl_opt, critic_expl_opt,
            policy_loss_expl, value_loss_expl, actor_expl_gn, critic_expl_gn, aux_expl,
        ) = behaviour_update(
            modules.actor_exploration, modules.critic_exploration,
            new_wm, params["actor_exploration"], params["critic_exploration"],
            opt_states.actor_exploration, opt_states.critic_exploration,
            start_prior, start_recurrent, k_expl, intrinsic_rewards,
        )

        # ---- (4) task behaviour (zero-shot) on the learned reward model
        def task_rewards(trajectories, imagined_actions):
            del imagined_actions
            return modules.reward_model.apply(new_wm["reward_model"], trajectories)

        (
            new_actor_task, new_critic_task, actor_task_opt, critic_task_opt,
            policy_loss_task, value_loss_task, actor_task_gn, critic_task_gn, _,
        ) = behaviour_update(
            modules.actor_task, modules.critic_task,
            new_wm, params["actor_task"], params["critic_task"],
            opt_states.actor_task, opt_states.critic_task,
            start_prior, start_recurrent, k_task, task_rewards,
        )

        new_params = {
            "world_model": new_wm,
            "ensembles": new_ens,
            "actor_task": new_actor_task,
            "critic_task": new_critic_task,
            "actor_exploration": new_actor_expl,
            "critic_exploration": new_critic_expl,
        }
        new_opt = P2EDV1OptStates(
            world=world_opt, ensembles=ens_opt,
            actor_task=actor_task_opt, critic_task=critic_task_opt,
            actor_exploration=actor_expl_opt, critic_exploration=critic_expl_opt,
        )
        metrics = jnp.stack(
            [
                world_loss,
                aux["observation_loss"],
                aux["reward_loss"],
                aux["state_loss"],
                aux["continue_loss"],
                aux["kl"],
                aux["post_entropy"],
                aux["prior_entropy"],
                ens_loss,
                aux_expl["rewards"].mean(),
                aux_expl["predicted_values"].mean(),
                aux_expl["lambda_values"].mean(),
                policy_loss_expl,
                value_loss_expl,
                policy_loss_task,
                value_loss_task,
                world_grad_norm,
                ens_grad_norm,
                actor_expl_gn,
                critic_expl_gn,
                actor_task_gn,
                critic_task_gn,
            ]
        )
        return (new_params, new_opt), metrics

    def train(params, opt_states, batches, key):
        g = next(iter(batches.values())).shape[0]
        keys = jax.random.split(key, g)
        (params, opt_states), metrics = jax.lax.scan(one_step, (params, opt_states), (batches, keys))
        m = metrics.mean(axis=0)
        # raveled player subset computed in-graph (one flat host-player transfer)
        flat_player = psync.ravel(params) if psync is not None else None
        return params, opt_states, flat_player, {name: m[i] for i, name in enumerate(METRIC_ORDER)}

    return init_opt, jax_compile.guarded_jit(train, name="p2e_dv1.train", donate_argnums=(0, 1))


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    world_size = runtime.world_size
    rank = runtime.global_rank

    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_state

        state = load_state(cfg.checkpoint.resume_from)

    # These arguments cannot be changed (reference p2e_dv1_exploration.py:374-377)
    cfg.env.screen_size = 64
    cfg.env.frame_stack = 1
    cfg.algo.player.actor_type = "exploration"

    logger = get_logger(runtime, cfg)
    if logger:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.logger = logger
    runtime.print(f"Log dir: {log_dir}")

    ft = resilience.resolve(cfg)
    envs = resilience.make_supervised_env(
        [
            make_env(
                cfg,
                cfg.seed + rank * cfg.env.num_envs + i,
                rank * cfg.env.num_envs,
                log_dir if runtime.is_global_zero else None,
                "train",
                vector_env_idx=i,
            )
            for i in range(cfg.env.num_envs)
        ],
        sync=cfg.env.sync_env,
        ft=ft,
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if (
        len(set(cfg.algo.cnn_keys.encoder).intersection(set(cfg.algo.cnn_keys.decoder))) == 0
        and len(set(cfg.algo.mlp_keys.encoder).intersection(set(cfg.algo.mlp_keys.decoder))) == 0
    ):
        raise RuntimeError("The CNN keys or the MLP keys of the encoder and decoder must not be disjointed")
    obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)

    modules, params, player = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["world_model"] if state else None,
        state["ensembles"] if state else None,
        state["actor_task"] if state else None,
        state["critic_task"] if state else None,
        state["actor_exploration"] if state else None,
        state["critic_exploration"] if state else None,
    )

    psync = DreamerPlayerSync(
        runtime,
        params,
        wm_keys=PLAYER_WM_KEYS,
        actor_name="actor_exploration",
        every=cfg.algo.get("player_sync_every", 1),
    )
    init_opt, train_fn = make_train_fn(modules, cfg, runtime, psync)
    opt_states = init_opt(params)
    if state:
        opt_states = jax.tree_util.tree_map(jnp.asarray, state["opt_states"])
    params = runtime.place_params(params)
    opt_states = runtime.place_params(opt_states)
    # the player must never hold mesh-resident params when it lives on the host
    # CPU backend: its per-step calls would pay per-leaf cross-backend pulls
    psync.push(player, params, force=True)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    rb, prefetcher = make_sequential_replay(cfg, runtime, log_dir, obs_keys)
    if state and cfg.buffer.checkpoint and "rb" in state:
        rb.load_state_dict(state["rb"])

    train_step = 0
    last_train = 0
    start_iter = (state["iter_num"] // world_size) + 1 if state else 1
    policy_step = state["iter_num"] * cfg.env.num_envs if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(cfg.env.num_envs * world_size)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state:
        ratio.load_state_dict(state["ratio"])

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    profiler = TraceProfiler(cfg.metric.get("profiler"), log_dir if runtime.is_global_zero else None)
    rng = jax.random.PRNGKey(cfg.seed)
    step_data: Dict[str, np.ndarray] = {}

    obs = envs.reset(seed=cfg.seed)[0]
    for k in obs_keys:
        step_data[k] = np.asarray(obs[k])[np.newaxis]
    step_data["terminated"] = np.zeros((1, cfg.env.num_envs, 1))
    step_data["truncated"] = np.zeros((1, cfg.env.num_envs, 1))
    step_data["actions"] = np.zeros((1, cfg.env.num_envs, int(np.sum(actions_dim))))
    step_data["rewards"] = np.zeros((1, cfg.env.num_envs, 1))
    step_data["is_first"] = np.ones_like(step_data["terminated"])
    with prefetcher.guard():  # no torn rows under the worker's sample
        rb.add(step_data, validate_args=cfg.buffer.validate_args)
    player.init_states()

    base_expl_amount = float(cfg.algo.actor.get("expl_amount", 0.0))
    expl_decay = float(cfg.algo.actor.get("expl_decay", 0.0))
    expl_min = float(cfg.algo.actor.get("expl_min", 0.0))

    cumulative_per_rank_gradient_steps = 0
    for iter_num in range(start_iter, total_iters + 1):
        profiler.step(policy_step)
        policy_step += policy_steps_per_iter

        with timer("Time/env_interaction_time", SumMetric()):
            if iter_num <= learning_starts and state is None:
                real_actions = actions = np.array(envs.action_space.sample())
                if not is_continuous:
                    actions = np.concatenate(
                        [
                            np.eye(act_dim, dtype=np.float32)[act.reshape(-1)]
                            for act, act_dim in zip(actions.reshape(len(actions_dim), -1), actions_dim)
                        ],
                        axis=-1,
                    )
            else:
                jax_obs = prepare_obs(runtime, obs, cnn_keys=cfg.algo.cnn_keys.encoder, num_envs=cfg.env.num_envs)
                rng, act_key = jax.random.split(rng)
                player.expl_amount = expl_amount_schedule(base_expl_amount, expl_decay, expl_min, policy_step)
                actions_list = player.get_actions(jax_obs, act_key)
                actions = np.concatenate([np.asarray(a) for a in actions_list], axis=-1)
                if is_continuous:
                    real_actions = actions
                else:
                    real_actions = np.stack([np.asarray(a).argmax(axis=-1) for a in actions_list], axis=-1)

            step_data["is_first"] = np.logical_or(step_data["terminated"], step_data["truncated"]).astype(
                np.float32
            )
            next_obs, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
            dones = np.logical_or(terminated, truncated).astype(np.uint8)

        if cfg.metric.log_level > 0:
            for i, (ep_rew, ep_len) in enumerate(finished_episodes(infos)):
                if aggregator:
                    if "Rewards/rew_avg" in aggregator:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                    if "Game/ep_len_avg" in aggregator:
                        aggregator.update("Game/ep_len_avg", ep_len)
                runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")

        real_next_obs = {k: np.asarray(v).copy() for k, v in next_obs.items() if k in obs_keys}
        finals = final_observations(infos, obs_keys)
        if finals:
            for idx, final_obs in finals.items():
                for k, v in final_obs.items():
                    real_next_obs[k][idx] = v

        for k in obs_keys:
            step_data[k] = real_next_obs[k][np.newaxis]
        obs = next_obs

        step_data["terminated"] = np.asarray(terminated, dtype=np.float32).reshape((1, cfg.env.num_envs, -1))
        step_data["truncated"] = np.asarray(truncated, dtype=np.float32).reshape((1, cfg.env.num_envs, -1))
        step_data["actions"] = actions.reshape((1, cfg.env.num_envs, -1))
        step_data["rewards"] = clip_rewards_fn(
            np.asarray(rewards, dtype=np.float32).reshape((1, cfg.env.num_envs, -1))
        )
        with prefetcher.guard():  # no torn rows under the worker's sample
            rb.add(step_data, validate_args=cfg.buffer.validate_args)

        dones_idxes = dones.nonzero()[0].tolist()
        reset_envs = len(dones_idxes)
        if reset_envs > 0:
            reset_data = {}
            for k in obs_keys:
                reset_data[k] = (np.asarray(next_obs[k])[dones_idxes])[np.newaxis]
            reset_data["terminated"] = np.zeros((1, reset_envs, 1))
            reset_data["truncated"] = np.zeros((1, reset_envs, 1))
            reset_data["actions"] = np.zeros((1, reset_envs, int(np.sum(actions_dim))))
            reset_data["rewards"] = np.zeros((1, reset_envs, 1))
            reset_data["is_first"] = np.ones_like(reset_data["terminated"])
            with prefetcher.guard():  # no torn rows under the worker's sample
                rb.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)
            for d in dones_idxes:
                step_data["terminated"][0, d] = np.zeros_like(step_data["terminated"][0, d])
                step_data["truncated"][0, d] = np.zeros_like(step_data["truncated"][0, d])
            player.init_states(dones_idxes)

        if iter_num >= learning_starts:
            ratio_steps = policy_step - prefill_steps * policy_steps_per_iter
            per_rank_gradient_steps = ratio(ratio_steps / world_size)
            if per_rank_gradient_steps > 0:
                # consumes the batch prefetched during the previous train step and
                # immediately speculates the next one
                batches = prefetcher.get(
                    batch_size=cfg.algo.per_rank_batch_size * world_size,
                    sequence_length=cfg.algo.per_rank_sequence_length,
                    n_samples=per_rank_gradient_steps,
                )
                with timer("Time/train_time", SumMetric()):
                    rng, train_key = jax.random.split(rng)
                    params, opt_states, flat_player, train_metrics = train_fn(
                        params, opt_states, batches, train_key
                    )
                    if not timer.disabled:
                        # fence ONLY when timing (Time/train_time honesty); an
                        # unconditional sync serializes on the dispatch round-trip
                        jax.block_until_ready(params)
                    psync.push(player, params, flat=flat_player)
                    cumulative_per_rank_gradient_steps += per_rank_gradient_steps
                    train_step += world_size * per_rank_gradient_steps
                if aggregator:
                    aggregator.update_from_device(train_metrics)
                    if "Params/exploration_amount_exploration" in aggregator:
                        aggregator.update("Params/exploration_amount_exploration", player.expl_amount)
                    if "Params/exploration_amount_task" in aggregator:
                        aggregator.update("Params/exploration_amount_task", player.expl_amount)

        if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters):
            if aggregator and not aggregator.disabled:
                logger.log_metrics(aggregator.compute(), policy_step)
                aggregator.reset()
            if logger and policy_step > 0:
                logger.log_metrics(
                    {"Params/replay_ratio": cumulative_per_rank_gradient_steps * world_size / policy_step},
                    policy_step,
                )
            if not timer.disabled:
                timer_metrics = timer.compute()
                if logger and timer_metrics.get("Time/train_time", 0) > 0:
                    logger.log_metrics(
                        {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                        policy_step,
                    )
                if logger and timer_metrics.get("Time/env_interaction_time", 0) > 0:
                    logger.log_metrics(
                        {
                            "Time/sps_env_interaction": (
                                (policy_step - last_log) / world_size * cfg.env.action_repeat
                            )
                            / timer_metrics["Time/env_interaction_time"]
                        },
                        policy_step,
                    )
                timer.reset()
            last_log = policy_step
            last_train = train_step

        resilience.drain_env_counters(envs, aggregator)
        jax_compile.drain_compile_counters(aggregator)
        if cumulative_per_rank_gradient_steps > 0 and not jax_compile.is_steady():
            # everything reachable has compiled once: later traces are drift
            jax_compile.mark_steady()

        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            iter_num == total_iters and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "world_model": jax.device_get(params["world_model"]),
                "ensembles": jax.device_get(params["ensembles"]),
                "actor_task": jax.device_get(params["actor_task"]),
                "critic_task": jax.device_get(params["critic_task"]),
                "actor_exploration": jax.device_get(params["actor_exploration"]),
                "critic_exploration": jax.device_get(params["critic_exploration"]),
                "opt_states": jax.device_get(opt_states),
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{rank}.ckpt")
            runtime.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
                io_lock=prefetcher.guard(),
            )

    profiler.close()
    prefetcher.close()
    envs.close()
    # Zero-shot evaluation runs with the TASK policy (reference :795-798).
    if runtime.is_global_zero and cfg.algo.run_test:
        player.actor = modules.actor_task
        # zero-shot eval swaps in the TASK actor: ship a coherent (wm, actor)
        # pair to the player device rather than mixing backends
        psync_task = DreamerPlayerSync(runtime, params, wm_keys=PLAYER_WM_KEYS, actor_name="actor_task")
        psync_task.push(player, params, force=True)
        player.actor_type = "task"
        test(player, runtime, cfg, log_dir)
    if logger:
        logger.finalize()
