"""Plan2Explore (DV1) — finetuning phase (reference
sheeprl/algos/p2e_dv1/p2e_dv1_finetuning.py:33-441).

Loads the exploration checkpoint, pins the model hyper-parameters to the
exploration run's, and finetunes the TASK actor-critic (plus world model) with the
plain DreamerV1 train step on real rewards. The player rolls out with the
exploration policy until training starts, then switches to the task policy.
"""

from __future__ import annotations

import os
import pathlib
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.algos.dreamer_v1.dreamer_v1 import DV1OptStates, PLAYER_WM_KEYS, make_train_fn
from sheeprl_tpu.algos.dreamer_v2.agent import expl_amount_schedule
from sheeprl_tpu.algos.dreamer_v2.utils import prepare_obs, test
from sheeprl_tpu.algos.p2e_dv1.agent import build_agent
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.factory import make_sequential_replay
from sheeprl_tpu.utils.checkpoint import load_state
from sheeprl_tpu.core import resilience
from sheeprl_tpu.utils.env import finished_episodes, final_observations, make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.profiler import TraceProfiler
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import DreamerPlayerSync, Ratio, save_configs


@register_algorithm()
def main(runtime, cfg: Dict[str, Any], exploration_cfg: Dict[str, Any]):
    world_size = runtime.world_size
    rank = runtime.global_rank

    ckpt_path = pathlib.Path(cfg.checkpoint.exploration_ckpt_path)
    resumed = cfg.checkpoint.resume_from is not None
    state = load_state(cfg.checkpoint.resume_from if resumed else str(ckpt_path))

    # All the models must be equal to the ones of the exploration phase
    # (reference p2e_dv1_finetuning.py:50-72).
    cfg.algo.gamma = exploration_cfg.algo.gamma
    cfg.algo.lmbda = exploration_cfg.algo.lmbda
    cfg.algo.horizon = exploration_cfg.algo.horizon
    cfg.algo.dense_units = exploration_cfg.algo.dense_units
    cfg.algo.mlp_layers = exploration_cfg.algo.mlp_layers
    cfg.algo.dense_act = exploration_cfg.algo.dense_act
    cfg.algo.cnn_act = exploration_cfg.algo.cnn_act
    cfg.algo.world_model = exploration_cfg.algo.world_model
    cfg.algo.actor = exploration_cfg.algo.actor
    cfg.algo.critic = exploration_cfg.algo.critic
    cfg.env.clip_rewards = exploration_cfg.env.clip_rewards
    if cfg.buffer.load_from_exploration and exploration_cfg.buffer.checkpoint:
        cfg.env.num_envs = exploration_cfg.env.num_envs
    cfg.algo.cnn_keys = exploration_cfg.algo.cnn_keys
    cfg.algo.mlp_keys = exploration_cfg.algo.mlp_keys

    # These arguments cannot be changed
    cfg.env.screen_size = 64
    cfg.env.frame_stack = 1

    logger = get_logger(runtime, cfg)
    if logger:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.logger = logger
    runtime.print(f"Log dir: {log_dir}")

    ft = resilience.resolve(cfg)
    envs = resilience.make_supervised_env(
        [
            make_env(
                cfg,
                cfg.seed + rank * cfg.env.num_envs + i,
                rank * cfg.env.num_envs,
                log_dir if runtime.is_global_zero else None,
                "train",
                vector_env_idx=i,
            )
            for i in range(cfg.env.num_envs)
        ],
        sync=cfg.env.sync_env,
        ft=ft,
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if (
        len(set(cfg.algo.cnn_keys.encoder).intersection(set(cfg.algo.cnn_keys.decoder))) == 0
        and len(set(cfg.algo.mlp_keys.encoder).intersection(set(cfg.algo.mlp_keys.decoder))) == 0
    ):
        raise RuntimeError("The CNN keys or the MLP keys of the encoder and decoder must not be disjointed")
    obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)

    modules, params, player = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["world_model"],
        None,
        state["actor_task"],
        state["critic_task"],
        state["actor_exploration"],
        None,
    )

    # Finetune the TASK behaviour with the plain DV1 step on real rewards.
    dv1_modules = modules.as_dv1(task=True)
    psync = DreamerPlayerSync(
        runtime,
        {"world_model": params["world_model"], "actor": params["actor_task"]},
        wm_keys=PLAYER_WM_KEYS,
        every=cfg.algo.get("player_sync_every", 1),
    )
    init_opt, train_fn = make_train_fn(dv1_modules, cfg, runtime, is_continuous, actions_dim, psync)
    fine_params = {
        "world_model": params["world_model"],
        "actor": params["actor_task"],
        "critic": params["critic_task"],
    }
    opt_states = init_opt(fine_params)
    if resumed:
        opt_states = jax.tree_util.tree_map(jnp.asarray, state["opt_states"])
    elif "opt_states" in state:
        # Carry over the world/actor_task/critic_task optimizer moments from the
        # exploration phase (reference p2e_dv1_finetuning.py:158-160). The
        # exploration checkpoint stores a P2EDV1OptStates NamedTuple.
        expl_opt = state["opt_states"]
        get = expl_opt.get if isinstance(expl_opt, dict) else lambda name, d=None: getattr(expl_opt, name, d)
        world, actor, critic = get("world"), get("actor_task"), get("critic_task")
        opt_states = DV1OptStates(
            world=jax.tree_util.tree_map(jnp.asarray, world) if world is not None else opt_states.world,
            actor=jax.tree_util.tree_map(jnp.asarray, actor) if actor is not None else opt_states.actor,
            critic=jax.tree_util.tree_map(jnp.asarray, critic) if critic is not None else opt_states.critic,
        )
    fine_params = runtime.place_params(fine_params)
    opt_states = runtime.place_params(opt_states)
    # pre-switch rollouts keep the EXPLORATION policy the checkpoint shipped;
    # commit those copies to the player device so the player never mixes backends
    player.wm_params = runtime.to_player(player.wm_params)
    player.actor_params = runtime.to_player(player.actor_params)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    rb, prefetcher = make_sequential_replay(cfg, runtime, log_dir, obs_keys)
    if "rb" in state and (resumed or (cfg.buffer.load_from_exploration and exploration_cfg.buffer.checkpoint)):
        rb.load_state_dict(state["rb"])

    train_step = 0
    last_train = 0
    start_iter = (state["iter_num"] // world_size) + 1 if resumed else 1
    policy_step = state["iter_num"] * cfg.env.num_envs if resumed else 0
    last_log = state["last_log"] if resumed else 0
    last_checkpoint = state["last_checkpoint"] if resumed else 0
    policy_steps_per_iter = int(cfg.env.num_envs * world_size)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if resumed:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if resumed:
        ratio.load_state_dict(state["ratio"])

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    profiler = TraceProfiler(cfg.metric.get("profiler"), log_dir if runtime.is_global_zero else None)
    rng = jax.random.PRNGKey(cfg.seed)
    step_data: Dict[str, np.ndarray] = {}

    obs = envs.reset(seed=cfg.seed)[0]
    for k in obs_keys:
        step_data[k] = np.asarray(obs[k])[np.newaxis]
    step_data["terminated"] = np.zeros((1, cfg.env.num_envs, 1))
    step_data["truncated"] = np.zeros((1, cfg.env.num_envs, 1))
    step_data["actions"] = np.zeros((1, cfg.env.num_envs, int(np.sum(actions_dim))))
    step_data["rewards"] = np.zeros((1, cfg.env.num_envs, 1))
    step_data["is_first"] = np.ones_like(step_data["terminated"])
    with prefetcher.guard():  # no torn rows under the worker's sample
        rb.add(step_data, validate_args=cfg.buffer.validate_args)
    player.init_states()

    base_expl_amount = float(cfg.algo.actor.get("expl_amount", 0.0))
    expl_decay = float(cfg.algo.actor.get("expl_decay", 0.0))
    expl_min = float(cfg.algo.actor.get("expl_min", 0.0))

    cumulative_per_rank_gradient_steps = 0
    for iter_num in range(start_iter, total_iters + 1):
        profiler.step(policy_step)
        policy_step += policy_steps_per_iter

        with timer("Time/env_interaction_time", SumMetric()):
            jax_obs = prepare_obs(runtime, obs, cnn_keys=cfg.algo.cnn_keys.encoder, num_envs=cfg.env.num_envs)
            rng, act_key = jax.random.split(rng)
            player.expl_amount = expl_amount_schedule(base_expl_amount, expl_decay, expl_min, policy_step)
            actions_list = player.get_actions(jax_obs, act_key)
            actions = np.concatenate([np.asarray(a) for a in actions_list], axis=-1)
            if is_continuous:
                real_actions = actions
            else:
                real_actions = np.stack([np.asarray(a).argmax(axis=-1) for a in actions_list], axis=-1)

            step_data["is_first"] = np.logical_or(step_data["terminated"], step_data["truncated"]).astype(
                np.float32
            )
            next_obs, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
            dones = np.logical_or(terminated, truncated).astype(np.uint8)

        if cfg.metric.log_level > 0:
            for i, (ep_rew, ep_len) in enumerate(finished_episodes(infos)):
                if aggregator:
                    if "Rewards/rew_avg" in aggregator:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                    if "Game/ep_len_avg" in aggregator:
                        aggregator.update("Game/ep_len_avg", ep_len)
                runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")

        real_next_obs = {k: np.asarray(v).copy() for k, v in next_obs.items() if k in obs_keys}
        finals = final_observations(infos, obs_keys)
        if finals:
            for idx, final_obs in finals.items():
                for k, v in final_obs.items():
                    real_next_obs[k][idx] = v

        for k in obs_keys:
            step_data[k] = real_next_obs[k][np.newaxis]
        obs = next_obs

        step_data["terminated"] = np.asarray(terminated, dtype=np.float32).reshape((1, cfg.env.num_envs, -1))
        step_data["truncated"] = np.asarray(truncated, dtype=np.float32).reshape((1, cfg.env.num_envs, -1))
        step_data["actions"] = actions.reshape((1, cfg.env.num_envs, -1))
        step_data["rewards"] = clip_rewards_fn(
            np.asarray(rewards, dtype=np.float32).reshape((1, cfg.env.num_envs, -1))
        )
        with prefetcher.guard():  # no torn rows under the worker's sample
            rb.add(step_data, validate_args=cfg.buffer.validate_args)

        dones_idxes = dones.nonzero()[0].tolist()
        reset_envs = len(dones_idxes)
        if reset_envs > 0:
            reset_data = {}
            for k in obs_keys:
                reset_data[k] = (np.asarray(next_obs[k])[dones_idxes])[np.newaxis]
            reset_data["terminated"] = np.zeros((1, reset_envs, 1))
            reset_data["truncated"] = np.zeros((1, reset_envs, 1))
            reset_data["actions"] = np.zeros((1, reset_envs, int(np.sum(actions_dim))))
            reset_data["rewards"] = np.zeros((1, reset_envs, 1))
            reset_data["is_first"] = np.ones_like(reset_data["terminated"])
            with prefetcher.guard():  # no torn rows under the worker's sample
                rb.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)
            for d in dones_idxes:
                step_data["terminated"][0, d] = np.zeros_like(step_data["terminated"][0, d])
                step_data["truncated"][0, d] = np.zeros_like(step_data["truncated"][0, d])
            player.init_states(dones_idxes)

        if iter_num >= learning_starts:
            ratio_steps = policy_step - prefill_steps * policy_steps_per_iter
            per_rank_gradient_steps = ratio(ratio_steps / world_size)
            if per_rank_gradient_steps > 0:
                # Switch the player to the task policy once training starts
                # (reference p2e_dv1_finetuning.py:330-334).
                if player.actor_type != "task":
                    player.actor_type = "task"
                    player.actor = modules.actor_task
                    psync.push(player, fine_params, force=True)
                # consumes the batch prefetched during the previous train step and
                # immediately speculates the next one
                batches = prefetcher.get(
                    batch_size=cfg.algo.per_rank_batch_size * world_size,
                    sequence_length=cfg.algo.per_rank_sequence_length,
                    n_samples=per_rank_gradient_steps,
                )
                with timer("Time/train_time", SumMetric()):
                    rng, train_key = jax.random.split(rng)
                    fine_params, opt_states, flat_player, train_metrics = train_fn(
                        fine_params, opt_states, batches, train_key
                    )
                    if not timer.disabled:
                        jax.block_until_ready(fine_params["actor"])
                    psync.push(player, fine_params, flat=flat_player)
                    cumulative_per_rank_gradient_steps += per_rank_gradient_steps
                    train_step += world_size * per_rank_gradient_steps
                if aggregator:
                    aggregator.update_from_device(train_metrics)
                    if "Params/exploration_amount_task" in aggregator:
                        aggregator.update("Params/exploration_amount_task", player.expl_amount)
                    if "Params/exploration_amount_exploration" in aggregator:
                        aggregator.update("Params/exploration_amount_exploration", player.expl_amount)

        if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters):
            if aggregator and not aggregator.disabled:
                logger.log_metrics(aggregator.compute(), policy_step)
                aggregator.reset()
            if logger and policy_step > 0:
                logger.log_metrics(
                    {"Params/replay_ratio": cumulative_per_rank_gradient_steps * world_size / policy_step},
                    policy_step,
                )
            if not timer.disabled:
                timer_metrics = timer.compute()
                if logger and timer_metrics.get("Time/train_time", 0) > 0:
                    logger.log_metrics(
                        {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                        policy_step,
                    )
                if logger and timer_metrics.get("Time/env_interaction_time", 0) > 0:
                    logger.log_metrics(
                        {
                            "Time/sps_env_interaction": (
                                (policy_step - last_log) / world_size * cfg.env.action_repeat
                            )
                            / timer_metrics["Time/env_interaction_time"]
                        },
                        policy_step,
                    )
                timer.reset()
            last_log = policy_step
            last_train = train_step

        resilience.drain_env_counters(envs, aggregator)
        jax_compile.drain_compile_counters(aggregator)
        if cumulative_per_rank_gradient_steps > 0 and not jax_compile.is_steady():
            # everything reachable has compiled once: later traces are drift
            jax_compile.mark_steady()

        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            iter_num == total_iters and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "world_model": jax.device_get(fine_params["world_model"]),
                "actor_task": jax.device_get(fine_params["actor"]),
                "critic_task": jax.device_get(fine_params["critic"]),
                "actor_exploration": jax.device_get(params["actor_exploration"]),
                "opt_states": jax.device_get(opt_states),
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path_out = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{rank}.ckpt")
            runtime.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path_out,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
                io_lock=prefetcher.guard(),
            )

    profiler.close()
    prefetcher.close()
    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        player.actor = modules.actor_task
        player.actor_type = "task"
        psync.push(player, fine_params, force=True)
        test(player, runtime, cfg, log_dir)
    if logger:
        logger.finalize()
