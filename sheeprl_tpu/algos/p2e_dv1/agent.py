"""Plan2Explore (DV1) agent: DV1 world model + task/exploration actor-critic pairs
plus an ensemble of next-embedding predictors.

Parity target: reference sheeprl/algos/p2e_dv1/agent.py:26-155 (build_agent returning
world model, ensembles, actor_task, critic_task, actor_exploration,
critic_exploration, player).

TPU-first design choice: the reference keeps the ensemble as an ``nn.ModuleList`` of
N independent MLPs evaluated in a Python loop (agent.py:126-143,
p2e_dv1_exploration.py:169-174). Here the ensemble is ONE module definition with
*stacked* parameters ``[N, ...]`` built by ``jax.vmap`` over N PRNG streams; the
forward pass is a single vmapped call, so all N members run as one batched matmul
set on the MXU instead of N small sequential kernels.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v1.agent import (
    DV1Modules,
    PlayerDV1,
    RSSMDV1,
    build_agent as dv1_build_agent,
)
from sheeprl_tpu.algos.dreamer_v2.agent import ActorDV2, MLPWithHeadDV2, MultiDecoderDV2, MultiEncoderDV2
from sheeprl_tpu.models.models import MLP

# Exposed for config-driven class selection (the reference aliases DV2's Actor the
# same way, p2e_dv1/agent.py:22-23).
Actor = ActorDV2


class Ensembles:
    """Vmapped ensemble of next-obs-embedding predictors (one-step models).

    ``init`` stacks N parameter pytrees (leaves get a leading ``[N]`` axis, each
    member seeded from its own PRNG fold — the analogue of the reference's
    per-member ``seed_everything(cfg.seed + i)``, agent.py:128-130); ``apply`` maps
    the same input through every member in one vmapped (MXU-batched) call,
    returning ``[N, *batch, output_dim]``.
    """

    def __init__(
        self,
        n: int,
        input_dim: int,
        output_dim: int,
        mlp_layers: int,
        dense_units: int,
        activation: str,
        layer_norm: bool = False,
        dtype: Any = jnp.float32,
        param_dtype: Any = jnp.float32,
    ):
        self.n = int(n)
        self.input_dim = int(input_dim)
        self.output_dim = int(output_dim)
        self.mlp = MLP(
            input_dims=int(input_dim),
            output_dim=int(output_dim),
            hidden_sizes=[int(dense_units)] * int(mlp_layers),
            activation=activation,
            layer_norm=bool(layer_norm),
            dtype=dtype,
            param_dtype=param_dtype,
        )

    def init(self, key: jax.Array, dummy_input: jax.Array):
        keys = jax.random.split(key, self.n)
        return jax.vmap(lambda k: self.mlp.init(k, dummy_input))(keys)

    def apply(self, stacked_params, x: jax.Array) -> jax.Array:
        return jax.vmap(lambda p: self.mlp.apply(p, x))(stacked_params)


class P2EDV1Modules(NamedTuple):
    encoder: MultiEncoderDV2
    rssm: RSSMDV1
    observation_model: MultiDecoderDV2
    reward_model: MLPWithHeadDV2
    continue_model: Optional[MLPWithHeadDV2]
    ensembles: Ensembles
    actor_task: ActorDV2
    critic_task: MLPWithHeadDV2
    actor_exploration: ActorDV2
    critic_exploration: MLPWithHeadDV2

    def as_dv1(self, task: bool) -> DV1Modules:
        """View as a DV1Modules using the task or exploration behaviour pair."""
        return DV1Modules(
            encoder=self.encoder,
            rssm=self.rssm,
            observation_model=self.observation_model,
            reward_model=self.reward_model,
            continue_model=self.continue_model,
            actor=self.actor_task if task else self.actor_exploration,
            critic=self.critic_task if task else self.critic_exploration,
        )


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Dict[str, Any]] = None,
    ensembles_state: Optional[Any] = None,
    actor_task_state: Optional[Dict[str, Any]] = None,
    critic_task_state: Optional[Dict[str, Any]] = None,
    actor_exploration_state: Optional[Dict[str, Any]] = None,
    critic_exploration_state: Optional[Dict[str, Any]] = None,
) -> Tuple[P2EDV1Modules, Dict[str, Any], PlayerDV1]:
    """Build P2E-DV1 modules + params (reference p2e_dv1/agent.py:26-155).

    ``params`` keys: world_model, ensembles, actor_task, critic_task,
    actor_exploration, critic_exploration.
    """
    world_model_cfg = cfg.algo.world_model
    actor_cfg = cfg.algo.actor
    critic_cfg = cfg.algo.critic
    latent_state_size = int(world_model_cfg.stochastic_size) + int(
        world_model_cfg.recurrent_model.recurrent_state_size
    )
    compute_dtype = runtime.compute_dtype

    dv1_modules, dv1_params, player = dv1_build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        world_model_state,
        actor_exploration_state,
        critic_exploration_state,
    )
    player.actor_type = cfg.algo.player.actor_type

    actor_task = ActorDV2(
        latent_state_size=latent_state_size,
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=cfg.distribution.get("type", "auto"),
        init_std=float(actor_cfg.init_std),
        min_std=float(actor_cfg.min_std),
        dense_units=int(actor_cfg.dense_units),
        mlp_layers=int(actor_cfg.mlp_layers),
        layer_norm=False,
        activation=actor_cfg.dense_act,
        dtype=compute_dtype,
    )
    critic_task = MLPWithHeadDV2(
        input_dim=latent_state_size,
        hidden_sizes=[int(critic_cfg.dense_units)] * int(critic_cfg.mlp_layers),
        output_dim=1,
        activation=critic_cfg.dense_act,
        layer_norm=False,
        dtype=compute_dtype,
    )
    ensembles = Ensembles(
        n=int(cfg.algo.ensembles.n),
        input_dim=int(sum(actions_dim)) + latent_state_size,
        output_dim=dv1_modules.encoder.output_dim,
        mlp_layers=int(cfg.algo.ensembles.mlp_layers),
        dense_units=int(cfg.algo.ensembles.dense_units),
        activation=cfg.algo.ensembles.dense_act,
        dtype=compute_dtype,
    )

    key = jax.random.PRNGKey(cfg.seed + 1)  # distinct stream from the DV1 init
    k_actor, k_critic, k_ens = jax.random.split(key, 3)
    dummy_latent = jnp.zeros((1, latent_state_size))
    actor_task_params = actor_task.init(k_actor, dummy_latent)
    critic_task_params = critic_task.init(k_critic, dummy_latent)
    ensembles_params = ensembles.init(k_ens, jnp.zeros((1, ensembles.input_dim)))

    if actor_task_state:
        actor_task_params = jax.tree_util.tree_map(jnp.asarray, actor_task_state)
    if critic_task_state:
        critic_task_params = jax.tree_util.tree_map(jnp.asarray, critic_task_state)
    if ensembles_state:
        ensembles_params = jax.tree_util.tree_map(jnp.asarray, ensembles_state)

    modules = P2EDV1Modules(
        encoder=dv1_modules.encoder,
        rssm=dv1_modules.rssm,
        observation_model=dv1_modules.observation_model,
        reward_model=dv1_modules.reward_model,
        continue_model=dv1_modules.continue_model,
        ensembles=ensembles,
        actor_task=actor_task,
        critic_task=critic_task,
        actor_exploration=dv1_modules.actor,
        critic_exploration=dv1_modules.critic,
    )
    params = {
        "world_model": dv1_params["world_model"],
        "ensembles": ensembles_params,
        "actor_task": actor_task_params,
        "critic_task": critic_task_params,
        "actor_exploration": dv1_params["actor"],
        "critic_exploration": dv1_params["critic"],
    }

    # Point the player at the requested behaviour policy (reference agent.py:146-153).
    if cfg.algo.player.actor_type == "task":
        player.actor = actor_task
        player.actor_params = actor_task_params
    else:
        player.actor_params = params["actor_exploration"]
    return modules, params, player
