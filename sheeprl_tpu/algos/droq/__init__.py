from sheeprl_tpu.algos.droq import droq  # noqa: F401
from sheeprl_tpu.algos.droq import evaluate  # noqa: F401
