"""DroQ utilities (reference sheeprl/algos/droq/utils.py): reuses SAC's surfaces."""

from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS, MODELS_TO_REGISTER, prepare_obs, test  # noqa: F401

# Single-'agent' registration shared with the other model-free algos.
from sheeprl_tpu.utils.model_manager import log_agent_from_checkpoint as log_models_from_checkpoint  # noqa: E402, F401
