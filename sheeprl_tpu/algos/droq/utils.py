"""DroQ utilities (reference sheeprl/algos/droq/utils.py): reuses SAC's surfaces."""

from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS, MODELS_TO_REGISTER, prepare_obs, test  # noqa: F401
