"""DroQ agent: SAC with Dropout+LayerNorm critics (reference sheeprl/algos/droq/agent.py).

DROQCritic (:20) adds per-layer Dropout + LayerNorm to the SAC critic; the actor and
player are the SAC ones. Ensemble params stay stacked (vmapped init) but training
updates critics sequentially with fresh target noise, matching the reference's
per-critic update/EMA interleaving (droq.py:95-117).
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import SACActor, SACParams, SACPlayer, action_scale_bias, init_sac_params
from sheeprl_tpu.models.models import MLP


class DROQCritic(nn.Module):
    """Q(s, a) MLP with Dropout before LayerNorm before activation (reference :20-54)."""

    hidden_size: int = 256
    num_critics: int = 1
    dropout: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array, deterministic: bool = True) -> jax.Array:
        x = jnp.concatenate([obs, action], axis=-1)
        return MLP(
            input_dims=1,
            output_dim=self.num_critics,
            hidden_sizes=(self.hidden_size, self.hidden_size),
            dropout_rate=self.dropout if self.dropout > 0 else None,
            layer_norm=True,
            dtype=self.dtype,
        )(x, deterministic=deterministic).astype(jnp.float32)


def build_agent(
    runtime,
    cfg,
    obs_space: gymnasium.spaces.Dict,
    action_space: gymnasium.spaces.Box,
    agent_state: Optional[Dict[str, Any]] = None,
):
    """Returns (actor, critic, params: SACParams, player). Reference: agent.py:222."""
    act_dim = prod(action_space.shape)
    obs_dim = sum(prod(obs_space[k].shape) for k in cfg.algo.mlp_keys.encoder)
    actor = SACActor(
        action_dim=act_dim,
        hidden_size=cfg.algo.actor.hidden_size,
        action_low=tuple(np.asarray(action_space.low, dtype=np.float32).tolist()),
        action_high=tuple(np.asarray(action_space.high, dtype=np.float32).tolist()),
        dtype=runtime.compute_dtype,
    )
    critic = DROQCritic(
        hidden_size=cfg.algo.critic.hidden_size,
        num_critics=1,
        dropout=cfg.algo.critic.dropout,
        dtype=runtime.compute_dtype,
    )
    params = init_sac_params(
        jax.random.PRNGKey(cfg.seed), actor, critic, cfg.algo.critic.n, obs_dim, act_dim, cfg.algo.alpha.alpha
    )
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
        if not isinstance(params, SACParams):
            params = SACParams(*params) if isinstance(params, (tuple, list)) else SACParams(**params)
    params = runtime.place_params(params)
    action_scale, action_bias = action_scale_bias(action_space.low, action_space.high)
    player = SACPlayer(actor, params.actor, action_scale, action_bias)
    return actor, critic, params, player
