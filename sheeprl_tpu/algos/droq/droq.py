"""DroQ training (reference sheeprl/algos/droq/droq.py:31-137 train, :140 main).

High-replay-ratio SAC with dropout critics. One jitted call scans over the G gradient
steps of an iteration; inside each step the critics are updated sequentially (each
with a fresh dropout rng and its own target-EMA, reference droq.py:95-117), then the
actor/alpha update runs on a separate actor batch using the MEAN of the Q-ensemble
(droq.py:122).
"""

from __future__ import annotations

import os
import warnings
from math import prod
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.algos.droq.agent import build_agent
from sheeprl_tpu.algos.sac.agent import SACParams, action_scale_bias, actor_action_and_log_prob
from sheeprl_tpu.algos.sac.loss import critic_loss, entropy_loss, policy_loss
from sheeprl_tpu.algos.sac.sac import SACOptStates
from sheeprl_tpu.algos.sac.utils import prepare_obs, test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.prefetch import DevicePrefetcher
from sheeprl_tpu.core import resilience
from sheeprl_tpu.utils.env import finished_episodes, make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.profiler import TraceProfiler
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import PlayerParamsSync, Ratio, polyak_update, save_configs

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
}


def _slice_tree(tree, i):
    return jax.tree_util.tree_map(lambda x: x[i], tree)


def _scatter_tree(zeros, grads, i):
    return jax.tree_util.tree_map(lambda z, g: z.at[i].set(g), zeros, grads)


def make_train_fn(actor, critic, cfg, runtime, action_scale, action_bias, target_entropy, params_sync=None):
    n_critics = int(cfg.algo.critic.n)
    gamma = float(cfg.algo.gamma)
    tau = float(cfg.algo.tau)
    data_sharding = NamedSharding(runtime.mesh, P("data"))
    qf_tx = instantiate(dict(cfg.algo.critic.optimizer))()
    actor_tx = instantiate(dict(cfg.algo.actor.optimizer))()
    alpha_tx = instantiate(dict(cfg.algo.alpha.optimizer))()

    def init_opt(params: SACParams) -> SACOptStates:
        # one optimizer state per critic so the sequential per-critic updates don't
        # leak momentum into the other critics (torch skips None-grad params)
        return SACOptStates(
            qf=tuple(qf_tx.init(_slice_tree(params.critics, i)) for i in range(n_critics)),
            actor=actor_tx.init(params.actor),
            alpha=alpha_tx.init(params.log_alpha),
        )

    def q_ensemble(critics_params, obs, action, deterministic=True, rngs=None):
        def one(p, rng):
            kw = {"rngs": {"dropout": rng}} if rng is not None else {}
            return critic.apply(p, obs, action, deterministic=deterministic, **kw)

        if rngs is None:
            qs = jax.vmap(lambda p: one(p, None))(critics_params)
        else:
            qs = jax.vmap(one)(critics_params, rngs)
        return jnp.moveaxis(qs[..., 0], 0, -1)

    def single_update(carry, inp):
        params, opt_states = carry
        critic_batch, actor_batch, key = inp
        critic_batch = jax.tree_util.tree_map(
            lambda v: jax.lax.with_sharding_constraint(v, data_sharding), critic_batch
        )
        alpha = jnp.exp(params.log_alpha)
        key, k_next, k_actor = jax.random.split(key, 3)

        # target computed once per step from the current target ensemble (min-Q)
        mean, log_std = actor.apply(params.actor, critic_batch["next_observations"])
        next_actions, next_logp = actor_action_and_log_prob(mean, log_std, k_next, action_scale, action_bias)
        next_q = q_ensemble(params.target_critics, critic_batch["next_observations"], next_actions)
        min_next_q = jnp.min(next_q, axis=-1, keepdims=True) - alpha * next_logp
        target_q = jax.lax.stop_gradient(
            critic_batch["rewards"] + (1 - critic_batch["terminated"]) * gamma * min_next_q
        )

        critics = params.critics
        targets = params.target_critics
        qf_opts = list(opt_states.qf)
        qf_loss_total = jnp.float32(0)
        for i in range(n_critics):
            key, k_drop = jax.random.split(key)

            def qf_loss_fn(ci_params):
                q = critic.apply(
                    ci_params,
                    critic_batch["observations"],
                    critic_batch["actions"],
                    deterministic=False,
                    rngs={"dropout": k_drop},
                )
                return critic_loss(q, target_q, 1)

            ci_params = _slice_tree(critics, i)
            loss_i, grads_i = jax.value_and_grad(qf_loss_fn)(ci_params)
            updates_i, qf_opts[i] = qf_tx.update(grads_i, qf_opts[i], ci_params)
            new_ci = optax.apply_updates(ci_params, updates_i)
            critics = _scatter_tree(critics, new_ci, i)
            # per-critic EMA right after its update (reference droq.py:117)
            new_target_i = jax.tree_util.tree_map(
                lambda p, t: tau * p[i] + (1 - tau) * t[i], critics, targets
            )
            targets = _scatter_tree(targets, new_target_i, i)
            qf_loss_total = qf_loss_total + loss_i

        # actor + alpha on the dedicated actor batch, mean-Q aggregation
        def actor_loss_fn(actor_params):
            m, ls = actor.apply(actor_params, actor_batch["observations"])
            acts, logp = actor_action_and_log_prob(m, ls, k_actor, action_scale, action_bias)
            qs = q_ensemble(critics, actor_batch["observations"], acts)
            mean_q = jnp.mean(qs, axis=-1, keepdims=True)
            return policy_loss(alpha, logp, mean_q), logp

        (actor_l, logp), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(params.actor)
        actor_updates, actor_opt = actor_tx.update(actor_grads, opt_states.actor, params.actor)
        new_actor = optax.apply_updates(params.actor, actor_updates)

        def alpha_loss_fn(log_alpha):
            return entropy_loss(log_alpha, jax.lax.stop_gradient(logp), target_entropy)

        alpha_l, alpha_grads = jax.value_and_grad(alpha_loss_fn)(params.log_alpha)
        alpha_updates, alpha_opt = alpha_tx.update(alpha_grads, opt_states.alpha, params.log_alpha)
        new_log_alpha = optax.apply_updates(params.log_alpha, alpha_updates)

        new_params = SACParams(actor=new_actor, critics=critics, target_critics=targets, log_alpha=new_log_alpha)
        new_opt = SACOptStates(qf=tuple(qf_opts), actor=actor_opt, alpha=alpha_opt)
        return (new_params, new_opt), jnp.stack([qf_loss_total / n_critics, actor_l, alpha_l])

    def train(params, opt_states, critic_batches, actor_batch, key):
        g = next(iter(critic_batches.values())).shape[0]
        keys = jax.random.split(key, g)
        actor_batches = jax.tree_util.tree_map(lambda v: jnp.broadcast_to(v, (g, *v.shape)), actor_batch)
        (params, opt_states), losses = jax.lax.scan(
            single_update, (params, opt_states), (critic_batches, actor_batches, keys)
        )
        mean_losses = losses.mean(axis=0)
        # flat actor for the one-transfer player refresh (see PlayerParamsSync)
        flat_actor = params_sync.ravel(params.actor) if params_sync is not None else None
        return params, opt_states, flat_actor, {
            "Loss/value_loss": mean_losses[0],
            "Loss/policy_loss": mean_losses[1],
            "Loss/alpha_loss": mean_losses[2],
        }

    return init_opt, jax_compile.guarded_jit(train, name="droq.train", donate_argnums=(0, 1))


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    if "minedojo" in cfg.env.wrapper._target_.lower():
        raise ValueError("MineDojo is not currently supported by DroQ agent.")
    world_size = runtime.world_size

    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_state

        state = load_state(cfg.checkpoint.resume_from)

    if len(cfg.algo.cnn_keys.encoder) > 0:
        warnings.warn("DroQ algorithm cannot allow to use images as observations, the CNN keys will be ignored")
        cfg.algo.cnn_keys.encoder = []

    logger = get_logger(runtime, cfg)
    if logger:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.logger = logger
    runtime.print(f"Log dir: {log_dir}")

    n_envs = cfg.env.num_envs * world_size
    ft = resilience.resolve(cfg)
    envs = resilience.make_supervised_env(
        [
            make_env(cfg, cfg.seed + i, 0, log_dir if runtime.is_global_zero else None, "train", vector_env_idx=i)
            for i in range(n_envs)
        ],
        sync=cfg.env.sync_env,
        ft=ft,
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the DroQ agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.algo.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")
    for k in cfg.algo.mlp_keys.encoder:
        if len(observation_space[k].shape) > 1:
            raise ValueError(
                "Only environments with vector-only observations are supported by the DroQ agent. "
                f"Provided environment: {cfg.env.id}"
            )

    actor, critic, params, player = build_agent(
        runtime, cfg, observation_space, action_space, state["agent"] if state else None
    )
    act_dim = prod(action_space.shape)
    target_entropy = jnp.float32(-act_dim)
    action_scale, action_bias = action_scale_bias(action_space.low, action_space.high)

    params_sync = PlayerParamsSync(player.params)
    init_opt, train_fn = make_train_fn(
        actor, critic, cfg, runtime, action_scale, action_bias, target_entropy, params_sync
    )
    # host player starts from host-resident params (see sac.py note)
    player.params = params_sync.pull(jax.jit(params_sync.ravel)(params.actor), runtime.player_device)
    opt_states = init_opt(params)
    if state:
        opt_states = jax.tree_util.tree_map(jnp.asarray, state["opt_states"])
    opt_states = runtime.place_params(opt_states)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    buffer_size = cfg.buffer.size // n_envs if not cfg.dry_run else 1
    if bool(cfg.buffer.get("device", False)):
        raise ValueError(
            "buffer.device=True is currently supported by the Dreamer-family loops "
            "only; use the host buffer here"
        )
    rb = ReplayBuffer(
        buffer_size,
        n_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{runtime.global_rank}"),
        obs_keys=("observations",),
    )
    if state and cfg.buffer.checkpoint and "rb" in state:
        rb.load_state_dict(state["rb"])

    last_train = 0
    train_step = 0
    start_iter = (state["iter_num"] // world_size) + 1 if state else 1
    policy_step = state["iter_num"] * cfg.env.num_envs if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(n_envs)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    player_sync_every = max(1, int(cfg.algo.get("player_sync_every", 1)))
    if state:
        ratio.load_state_dict(state["ratio"])

    def sample_critic_batches(g: int):
        bs = cfg.algo.per_rank_batch_size * world_size
        sample = rb.sample(batch_size=g * bs, sample_next_obs=cfg.buffer.sample_next_obs)
        return {k: np.asarray(v, dtype=np.float32).reshape(g, bs, *v.shape[2:]) for k, v in sample.items()}

    def sample_actor_batch():
        sample = rb.sample(batch_size=cfg.algo.per_rank_batch_size * world_size)
        return {k: np.asarray(v[0], dtype=np.float32) for k, v in sample.items()}

    # Double-buffered host->HBM pipelines (see sheeprl_tpu/data/prefetch.py); the
    # shared io_lock serializes the two workers' samples (one np.random.Generator)
    # and the loop's rb.add against both.
    import threading

    buffer_io_lock = threading.Lock()
    critic_prefetcher = DevicePrefetcher(
        sample_critic_batches,
        device=NamedSharding(runtime.mesh, P(None, "data")),
        io_lock=buffer_io_lock,
        chunk=int(cfg.buffer.get("prefetch_batches", 1)),
        chunk_key="g",
    )
    actor_prefetcher = DevicePrefetcher(
        sample_actor_batch, device=NamedSharding(runtime.mesh, P("data")), io_lock=buffer_io_lock
    )

    profiler = TraceProfiler(cfg.metric.get("profiler"), log_dir if runtime.is_global_zero else None)
    rng = jax.random.PRNGKey(cfg.seed)
    # rollout randomness lives on the PLAYER device: feeding mesh-resident keys/obs
    # into the host player's jit would silently move the policy step onto the
    # accelerator and pay a synchronous round-trip per env step
    player_rng = jax.device_put(jax.random.PRNGKey(cfg.seed + 1), runtime.player_device)
    mlp_keys = cfg.algo.mlp_keys.encoder

    last_flat_actor = None
    train_calls = 0
    obs = envs.reset(seed=cfg.seed)[0]
    obs_vec = np.concatenate([np.asarray(obs[k], dtype=np.float32).reshape(n_envs, -1) for k in mlp_keys], -1)

    for iter_num in range(start_iter, total_iters + 1):
        profiler.step(policy_step)
        policy_step += n_envs

        with timer("Time/env_interaction_time", SumMetric()):
            if iter_num < learning_starts:
                actions = envs.action_space.sample()
            else:
                player_rng, act_key = jax.random.split(player_rng)
                actions = np.asarray(
                    player.get_actions(jax.device_put(obs_vec, runtime.player_device), act_key)
                )
            next_obs, rewards, terminated, truncated, info = envs.step(actions.reshape(envs.action_space.shape))
            next_obs_vec = np.concatenate(
                [np.asarray(next_obs[k], dtype=np.float32).reshape(n_envs, -1) for k in mlp_keys], -1
            )
            real_next_obs = next_obs_vec.copy()
            if "final_obs" in info:
                for idx, fo in enumerate(np.asarray(info["final_obs"], dtype=object)):
                    if fo is not None:
                        real_next_obs[idx] = np.concatenate(
                            [np.asarray(fo[k], dtype=np.float32).reshape(-1) for k in mlp_keys], -1
                        )

        step_data = {
            "terminated": np.asarray(terminated).reshape(1, n_envs, -1).astype(np.uint8),
            "truncated": np.asarray(truncated).reshape(1, n_envs, -1).astype(np.uint8),
            "actions": np.asarray(actions).reshape(1, n_envs, -1).astype(np.float32),
            "observations": obs_vec[np.newaxis],
            "rewards": np.asarray(rewards, dtype=np.float32).reshape(1, n_envs, -1),
        }
        if not cfg.buffer.sample_next_obs:
            step_data["next_observations"] = real_next_obs[np.newaxis]
        with critic_prefetcher.guard():  # shared io_lock with actor_prefetcher
            rb.add(step_data, validate_args=cfg.buffer.validate_args)
        obs_vec = next_obs_vec

        if cfg.metric.log_level > 0:
            for i, (ep_rew, ep_len) in enumerate(finished_episodes(info)):
                if aggregator and "Rewards/rew_avg" in aggregator:
                    aggregator.update("Rewards/rew_avg", ep_rew)
                if aggregator and "Game/ep_len_avg" in aggregator:
                    aggregator.update("Game/ep_len_avg", ep_len)
                runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")

        if iter_num >= learning_starts:
            per_rank_gradient_steps = ratio((policy_step - prefill_steps * n_envs) / world_size)
            if per_rank_gradient_steps > 0:
                g = per_rank_gradient_steps
                # both batches prefetched during the previous train step (see
                # sheeprl_tpu/data/prefetch.py); kwargs change -> sync fallback
                critic_batches = critic_prefetcher.get(g=g)
                actor_batch = actor_prefetcher.get()
                with timer("Time/train_time", SumMetric()):
                    rng, train_key = jax.random.split(rng)
                    params, opt_states, flat_actor, train_metrics = train_fn(
                        params, opt_states, critic_batches, actor_batch, train_key
                    )
                    # ONE flat cross-backend transfer refreshes the host player; on
                    # remote accelerators cfg.algo.player_sync_every amortizes the
                    # round-trip. The explicit block keeps Time/train_time honest on
                    # locally-attached backends (async dispatch returns instantly).
                    last_flat_actor = flat_actor
                    # cadence counts TRAIN calls (iter_num can skip sync forever
                    # when Ratio grants steps only on a phase-locked subset)
                    train_calls += 1
                    if train_calls % player_sync_every == 0:
                        player.params = params_sync.pull(flat_actor, runtime.player_device)
                    if not timer.disabled:
                        # fence ONLY when timing (see sac.py note)
                        jax.block_until_ready(flat_actor)
                train_step += world_size * g
                if cfg.metric.log_level > 0 and aggregator:
                    aggregator.update_from_device(train_metrics)

        if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters):
            if aggregator and not aggregator.disabled:
                logger.log_metrics(aggregator.compute(), policy_step)
                aggregator.reset()
            if not timer.disabled:
                timer_metrics = timer.compute()
                if timer_metrics.get("Time/train_time", 0) > 0:
                    logger.log_metrics(
                        {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]}, policy_step
                    )
                if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                    logger.log_metrics(
                        {
                            "Time/sps_env_interaction": (
                                (policy_step - last_log) / world_size * cfg.env.action_repeat
                            )
                            / timer_metrics["Time/env_interaction_time"]
                        },
                        policy_step,
                    )
                timer.reset()
            last_log = policy_step
            last_train = train_step

        resilience.drain_env_counters(envs, aggregator)
        jax_compile.drain_compile_counters(aggregator)
        if train_calls > 0 and not jax_compile.is_steady():
            # everything reachable has compiled once: later traces are drift
            jax_compile.mark_steady()

        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            iter_num == total_iters and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": jax.device_get(params),
                "opt_states": jax.device_get(opt_states),
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{runtime.global_rank}.ckpt")
            runtime.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
                io_lock=critic_prefetcher.guard(),
            )

    critic_prefetcher.close()
    actor_prefetcher.close()
    profiler.close()
    envs.close()
    if last_flat_actor is not None:
        # final refresh: player_sync_every may have skipped the last iterations,
        # and test()/model registration must see the final policy
        player.params = params_sync.pull(last_flat_actor, runtime.player_device)
    if runtime.is_global_zero and cfg.algo.run_test:
        test(player, runtime, cfg, log_dir)
    if logger:
        logger.finalize()
