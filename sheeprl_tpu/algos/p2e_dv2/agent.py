"""Plan2Explore (DV2) agent: DV2 world model + task/exploration actor-critic pairs
(each with a hard-updated target critic) plus an ensemble of next-stochastic-state
predictors.

Parity target: reference sheeprl/algos/p2e_dv2/agent.py:27-221 (build_agent returning
world model, ensembles, actor_task, critic_task, target_critic_task,
actor_exploration, critic_exploration, target_critic_exploration, player).

TPU-first design: the ensemble is ONE module with vmapped stacked params (see
p2e_dv1.agent.Ensembles) — all N members run as one batched matmul set on the MXU
instead of the reference's Python loop over an ``nn.ModuleList``.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import gymnasium
import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v2.agent import (
    ActorDV2,
    MinedojoActorDV2,
    DV2Modules,
    MLPWithHeadDV2,
    MultiDecoderDV2,
    MultiEncoderDV2,
    PlayerDV2,
    RSSMDV2,
    build_agent as dv2_build_agent,
)
from sheeprl_tpu.algos.p2e_dv1.agent import Ensembles
from sheeprl_tpu.utils.utils import resolve_actor_cls

# Exposed for config-driven class selection (reference p2e_dv2/agent.py:23-24).
Actor = ActorDV2


class P2EDV2Modules(NamedTuple):
    encoder: MultiEncoderDV2
    rssm: RSSMDV2
    observation_model: MultiDecoderDV2
    reward_model: MLPWithHeadDV2
    continue_model: Optional[MLPWithHeadDV2]
    ensembles: Ensembles
    actor_task: ActorDV2
    critic_task: MLPWithHeadDV2
    actor_exploration: ActorDV2
    critic_exploration: MLPWithHeadDV2

    def as_dv2(self, task: bool) -> DV2Modules:
        """View as a DV2Modules using the task or exploration behaviour pair."""
        return DV2Modules(
            encoder=self.encoder,
            rssm=self.rssm,
            observation_model=self.observation_model,
            reward_model=self.reward_model,
            continue_model=self.continue_model,
            actor=self.actor_task if task else self.actor_exploration,
            critic=self.critic_task if task else self.critic_exploration,
        )


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Dict[str, Any]] = None,
    ensembles_state: Optional[Any] = None,
    actor_task_state: Optional[Dict[str, Any]] = None,
    critic_task_state: Optional[Dict[str, Any]] = None,
    target_critic_task_state: Optional[Dict[str, Any]] = None,
    actor_exploration_state: Optional[Dict[str, Any]] = None,
    critic_exploration_state: Optional[Dict[str, Any]] = None,
    target_critic_exploration_state: Optional[Dict[str, Any]] = None,
) -> Tuple[P2EDV2Modules, Dict[str, Any], PlayerDV2]:
    """Build P2E-DV2 modules + params (reference p2e_dv2/agent.py:27-221).

    ``params`` keys: world_model, ensembles, actor_task, critic_task,
    target_critic_task, actor_exploration, critic_exploration,
    target_critic_exploration.
    """
    world_model_cfg = cfg.algo.world_model
    actor_cfg = cfg.algo.actor
    critic_cfg = cfg.algo.critic
    stochastic_size = int(world_model_cfg.stochastic_size) * int(world_model_cfg.discrete_size)
    latent_state_size = stochastic_size + int(world_model_cfg.recurrent_model.recurrent_state_size)
    compute_dtype = runtime.compute_dtype

    dv2_modules, dv2_params, player = dv2_build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        world_model_state,
        actor_exploration_state,
        critic_exploration_state,
        target_critic_exploration_state,
    )
    player.actor_type = cfg.algo.player.actor_type

    # Config-selected actor class (MinedojoActorDV2 adds masked sampling)
    actor_cls = resolve_actor_cls(actor_cfg.get("cls"), ActorDV2, MinedojoActorDV2)
    actor_task = actor_cls(
        latent_state_size=latent_state_size,
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=cfg.distribution.get("type", "auto"),
        init_std=float(actor_cfg.init_std),
        min_std=float(actor_cfg.min_std),
        dense_units=int(actor_cfg.dense_units),
        mlp_layers=int(actor_cfg.mlp_layers),
        layer_norm=bool(actor_cfg.layer_norm),
        activation=actor_cfg.dense_act,
        dtype=compute_dtype,
    )
    critic_task = MLPWithHeadDV2(
        input_dim=latent_state_size,
        hidden_sizes=[int(critic_cfg.dense_units)] * int(critic_cfg.mlp_layers),
        output_dim=1,
        activation=critic_cfg.dense_act,
        layer_norm=bool(critic_cfg.layer_norm),
        dtype=compute_dtype,
    )
    # The ensembles predict the NEXT stochastic state from (posterior, recurrent,
    # action) — unlike DV1 where they predict the next obs embedding (reference
    # p2e_dv2/agent.py:180-198, p2e_dv2_exploration.py:197-211).
    ensembles = Ensembles(
        n=int(cfg.algo.ensembles.n),
        input_dim=int(sum(actions_dim)) + latent_state_size,
        output_dim=stochastic_size,
        mlp_layers=int(cfg.algo.ensembles.mlp_layers),
        dense_units=int(cfg.algo.ensembles.dense_units),
        activation=cfg.algo.ensembles.dense_act,
        layer_norm=bool(cfg.algo.ensembles.get("layer_norm", False)),
        dtype=compute_dtype,
    )

    key = jax.random.PRNGKey(cfg.seed + 1)  # distinct stream from the DV2 init
    k_actor, k_critic, k_ens = jax.random.split(key, 3)
    dummy_latent = jnp.zeros((1, latent_state_size))
    actor_task_params = actor_task.init(k_actor, dummy_latent)
    critic_task_params = critic_task.init(k_critic, dummy_latent)
    ensembles_params = ensembles.init(k_ens, jnp.zeros((1, ensembles.input_dim)))

    if actor_task_state:
        actor_task_params = jax.tree_util.tree_map(jnp.asarray, actor_task_state)
    if critic_task_state:
        critic_task_params = jax.tree_util.tree_map(jnp.asarray, critic_task_state)
    if ensembles_state:
        ensembles_params = jax.tree_util.tree_map(jnp.asarray, ensembles_state)
    target_critic_task_params = (
        jax.tree_util.tree_map(jnp.asarray, target_critic_task_state)
        if target_critic_task_state
        else copy.deepcopy(critic_task_params)
    )

    modules = P2EDV2Modules(
        encoder=dv2_modules.encoder,
        rssm=dv2_modules.rssm,
        observation_model=dv2_modules.observation_model,
        reward_model=dv2_modules.reward_model,
        continue_model=dv2_modules.continue_model,
        ensembles=ensembles,
        actor_task=actor_task,
        critic_task=critic_task,
        actor_exploration=dv2_modules.actor,
        critic_exploration=dv2_modules.critic,
    )
    params = {
        "world_model": dv2_params["world_model"],
        "ensembles": ensembles_params,
        "actor_task": actor_task_params,
        "critic_task": critic_task_params,
        "target_critic_task": target_critic_task_params,
        "actor_exploration": dv2_params["actor"],
        "critic_exploration": dv2_params["critic"],
        "target_critic_exploration": dv2_params["target_critic"],
    }

    # Point the player at the requested behaviour policy (reference agent.py:208-218).
    if cfg.algo.player.actor_type == "task":
        player.actor = actor_task
        player.actor_params = actor_task_params
    else:
        player.actor_params = params["actor_exploration"]
    return modules, params, player
