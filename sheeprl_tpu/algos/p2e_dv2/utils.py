"""P2E-DV2 utilities (reference sheeprl/algos/p2e_dv2/utils.py)."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.dreamer_v2.utils import AGGREGATOR_KEYS as AGGREGATOR_KEYS_DV2
from sheeprl_tpu.algos.dreamer_v2.utils import prepare_obs, test  # noqa: F401

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss_task",
    "Loss/policy_loss_task",
    "Loss/value_loss_exploration",
    "Loss/policy_loss_exploration",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "Loss/ensemble_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Params/exploration_amount_task",
    "Params/exploration_amount_exploration",
    "Rewards/intrinsic",
    "Values_exploration/predicted_values",
    "Values_exploration/lambda_values",
    "Grads/world_model",
    "Grads/ensemble",
    "Grads/actor_task",
    "Grads/critic_task",
    "Grads/actor_exploration",
    "Grads/critic_exploration",
}.union(AGGREGATOR_KEYS_DV2)
MODELS_TO_REGISTER = {
    "world_model",
    "ensembles",
    "actor_exploration",
    "critic_exploration",
    "target_critic_exploration",
    "actor_task",
    "critic_task",
    "target_critic_task",
}


def log_models_from_checkpoint(runtime, env, cfg, state) -> Dict[str, Any]:
    """Register P2E-DV2 models from a checkpoint (reference utils.py:60-121).

    Exploration checkpoints carry all eight models; finetuning checkpoints carry the
    task triple + world model + exploration actor.
    """
    import gymnasium as gym

    from sheeprl_tpu.algos.p2e_dv2.agent import build_agent
    from sheeprl_tpu.utils.model_manager import log_model

    is_continuous = isinstance(env.action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(env.action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        env.action_space.shape
        if is_continuous
        else (env.action_space.nvec.tolist() if is_multidiscrete else [env.action_space.n])
    )
    exploration = "exploration" in cfg.algo.name
    _, params, _ = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        env.observation_space,
        state["world_model"],
        state["ensembles"] if exploration else None,
        state["actor_task"],
        state["critic_task"],
        state["target_critic_task"],
        state["actor_exploration"] if "actor_exploration" in state else None,
        state["critic_exploration"] if exploration else None,
        state["target_critic_exploration"] if exploration else None,
    )
    info = {}
    names = ["world_model", "actor_task", "critic_task", "target_critic_task"]
    if exploration:
        names += ["ensembles", "actor_exploration", "critic_exploration", "target_critic_exploration"]
    for name in names:
        info[name] = log_model(runtime, cfg, name, params[name])
    return info
