from sheeprl_tpu.algos.p2e_dv2 import p2e_dv2_exploration, p2e_dv2_finetuning  # noqa: F401
from sheeprl_tpu.algos.p2e_dv2 import evaluate  # noqa: F401  (must import after the algorithms register)
