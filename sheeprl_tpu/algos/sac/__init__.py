from sheeprl_tpu.algos.sac import sac  # noqa: F401
from sheeprl_tpu.algos.sac import evaluate  # noqa: F401
