"""SAC, decoupled actor-learner (reference sheeprl/algos/sac/sac_decoupled.py:33-588).

Role split on the device mesh (see sheeprl_tpu/parallel/decoupled.py): device 0
is the PLAYER — it owns the envs AND the replay buffer (reference :116-123) and
runs policy forwards on its own chip — devices 1..N-1 are the TRAINERS. Each
training round the player samples ``G x per_rank_batch_size x (N-1)``
transitions and ships them to the trainer role, which `lax.scan`s the G fused
SAC updates over the trainer mesh and hands the refreshed parameters back
(reference :243-260 scatter + :550-554 broadcast).

Per-rank semantics: ``per_rank_batch_size`` applies per TRAINER device and the
replay ratio is computed against the trainer world size (reference :237:
``ratio(ratio_steps / (fabric.world_size - 1))``).

Multi-process worlds take the CROSS-HOST path automatically (reference
multi-node case, sac_decoupled.py:548-588): global device 0 plays and owns the
replay buffer, every other chip trains. The per-round gradient-step count is
pure ``Ratio`` arithmetic over config-derived step counters, so every process
computes it independently and stays in lockstep WITHOUT the reference's
explicit count broadcast (:237) — only the sampled batches ride the device
broadcast collective, with trainer processes joining on zero templates (see
sheeprl_tpu/parallel/decoupled.py:CrossHostTransport).
"""

from __future__ import annotations

import os
import warnings
from math import prod
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.core import failpoints
from sheeprl_tpu.core import health as health_mod
from sheeprl_tpu.core import resilience
from sheeprl_tpu.algos.sac.agent import action_scale_bias, build_agent
from sheeprl_tpu.algos.sac.sac import make_train_fn
from sheeprl_tpu.algos.sac.utils import test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.parallel import handoff, overlap, split_runtime, split_runtime_crosshost
from sheeprl_tpu.utils.env import finished_episodes, make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.profiler import TraceProfiler
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, save_configs


@register_algorithm(decoupled=True)
def main(runtime, cfg: Dict[str, Any]):
    if "minedojo" in cfg.env.wrapper._target_.lower():
        raise ValueError("MineDojo is not currently supported by SAC agent.")
    # Multi-process world -> the cross-host role split; single controller -> the
    # local device split (reference sac_decoupled.py:548-588).
    if jax.process_count() > 1:
        player_rt, trainer_rt, transport = split_runtime_crosshost(runtime)
    else:
        player_rt, trainer_rt = split_runtime(runtime)
        transport = None
    is_player = transport is None or transport.is_player_process
    trainer_world = trainer_rt.world_size

    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_state

        state = load_state(cfg.checkpoint.resume_from)

    if len(cfg.algo.cnn_keys.encoder) > 0:
        warnings.warn("SAC algorithm cannot allow to use images as observations, the CNN keys will be ignored")
        cfg.algo.cnn_keys.encoder = []

    logger = get_logger(runtime, cfg)
    if logger:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    if transport is not None:
        transport.set_scope(log_dir)  # run-scope the KV spec exchange (coordinator store outlives runs)
        if cfg.checkpoint.resume_from:
            # every process loaded its own copy of the checkpoint: verify they
            # are the SAME file before any of its state drives a collective
            transport.verify_resume_digest(cfg.checkpoint.resume_from)
    runtime.logger = logger
    runtime.print(f"Log dir: {log_dir}")
    runtime.print(
        f"Decoupled SAC: player on {player_rt.mesh.devices.ravel()[0]}, "
        f"{trainer_world} trainer device(s)"
    )

    n_envs = cfg.env.num_envs
    ft = resilience.resolve(cfg)
    # Health sentinel: the full ladder needs the trainer state in-process, so
    # cross-host worlds run warn-only (backoff would desync the lockstep
    # gradient-step arithmetic; rollback would need a coordinated restore).
    sentinel = health_mod.HealthSentinel(
        cfg,
        log_dir=log_dir if runtime.is_global_zero else None,
        world_size=runtime.world_size,
        supports=("warn", "backoff", "rollback") if transport is None else ("warn",),
    )
    if is_player:
        envs = resilience.make_supervised_env(
            [
                make_env(cfg, cfg.seed + i, 0, log_dir if runtime.is_global_zero else None, "train", vector_env_idx=i)
                for i in range(n_envs)
            ],
            sync=cfg.env.sync_env,
            ft=ft,
        )
        action_space = envs.single_action_space
        observation_space = envs.single_observation_space
    else:
        # trainer processes probe ONE env for the spaces build_agent needs (the
        # reference ships agent_args via object broadcast, sac_decoupled.py:127)
        envs = None
        probe_env = make_env(cfg, cfg.seed, 0, None, "train", vector_env_idx=0)()
        action_space = probe_env.action_space
        observation_space = probe_env.observation_space
        probe_env.close()
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.algo.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")
    for k in cfg.algo.mlp_keys.encoder:
        if len(observation_space[k].shape) > 1:
            raise ValueError(
                "Only environments with vector-only observations are supported by the SAC agent. "
                f"The observation with key '{k}' has shape {observation_space[k].shape}. "
                f"Provided environment: {cfg.env.id}"
            )

    # Trainer-side agent (params replicated over the trainer mesh); the player's
    # actor copy lives on the player device (reference :93-127).
    actor, critic, params, player = build_agent(
        trainer_rt, cfg, observation_space, action_space, state["agent"] if state else None
    )
    if transport is None:
        player.params = player_rt.replicate(params.actor)
    elif is_player:
        player.params = transport.params_to_player(params.actor)
    act_dim = prod(action_space.shape)
    target_entropy = jnp.float32(-act_dim)
    action_scale, action_bias = action_scale_bias(action_space.low, action_space.high)

    policy_steps_per_iter = int(n_envs)
    ema_every = int(cfg.algo.critic.target_network_frequency) // policy_steps_per_iter + 1
    init_opt, train_fn = make_train_fn(
        actor, critic, cfg, trainer_rt, action_scale, action_bias, target_entropy, ema_every
    )
    opt_states = init_opt(params)
    if state:
        opt_states = jax.tree_util.tree_map(jnp.asarray, state["opt_states"])
    # strategy-aware placement: replicated under DDP, parameter-sharded over the
    # trainer mesh under fabric.strategy=fsdp (core/runtime.py:place_params)
    opt_states = trainer_rt.place_params(opt_states)
    # trainer-mesh placement: in a multi-process world every train_fn input must
    # be a global array (a process-local scalar would fail device-assignment
    # checks alongside the cross-process params)
    update_counter = trainer_rt.replicate(np.int32(state["update_counter"] if state else 0))

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    # The PLAYER owns the replay buffer (reference :116-123)
    buffer_size = cfg.buffer.size // n_envs if not cfg.dry_run else 1
    rb = (
        ReplayBuffer(
            buffer_size,
            n_envs,
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{runtime.global_rank}"),
            obs_keys=("observations",),
        )
        if is_player
        else None
    )
    if rb is not None and state and cfg.buffer.checkpoint and "rb" in state:
        rb.load_state_dict(state["rb"])

    last_train = 0
    train_step = 0
    start_iter = state["iter_num"] + 1 if state else 1
    policy_step = state["iter_num"] * cfg.env.num_envs if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state:
        cfg.algo.per_rank_batch_size = state["batch_size"] // trainer_world
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state:
        ratio.load_state_dict(state["ratio"])

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    # ---- trainer role
    trainer_state = {"params": params, "opt_states": opt_states, "update_counter": update_counter}

    def trainer_step(payload):
        # Per-shard handoff onto the trainer mesh (parallel/handoff.py): the
        # [G, B, *] replay batches shard on the batch axis (B) — the G-step
        # scan peels axis 0, so the per-update [B, *] slice lands exactly in
        # the train fn's P("data") constraint with ZERO in-program reshard,
        # and each trainer device receives one put of only its block instead
        # of a full replicated copy. Cross-host: one broadcast collective
        # replaces the reference's pickled batch scatter (sac_decoupled.py
        # :243-257).
        if transport is None:
            batches = handoff.shard_put(payload[0], trainer_rt.mesh, batch_axis=1)
            train_key = trainer_rt.replicate(payload[1])
        else:
            batches, train_key = transport.rollout_to_trainers(payload)
        train_key = jnp.asarray(train_key).astype(jnp.uint32)
        # chaos seam for the gradient-sync dispatch (decoupled twin of the
        # coupled loop's train.grad_sync site)
        failpoints.failpoint("train.grad_sync", microbatches=overlap.microbatches(cfg))
        new_params, new_opt, update_end, _flat_actor, metrics = train_fn(
            trainer_state["params"], trainer_state["opt_states"], batches, train_key,
            trainer_state["update_counter"],
        )
        trainer_state["params"] = new_params
        trainer_state["opt_states"] = new_opt
        trainer_state["update_counter"] = update_end
        # Only the actor goes back to the player (reference :550-554 broadcasts
        # the actor vector); cross-host it is a LOCAL put of this process's
        # replica (None on trainer processes).
        if transport is None:
            player_params = jax.device_put(new_params.actor, player_rt.replicated)
        else:
            player_params = transport.params_to_player(new_params.actor)
        return player_params, metrics

    profiler = TraceProfiler(cfg.metric.get("profiler"), log_dir if runtime.is_global_zero else None)
    rng = jax.random.PRNGKey(cfg.seed)
    mlp_keys = cfg.algo.mlp_keys.encoder
    cumulative_grad_steps = 0

    if is_player:
        obs = envs.reset(seed=cfg.seed)[0]
        obs_vec = np.concatenate([np.asarray(obs[k], dtype=np.float32).reshape(n_envs, -1) for k in mlp_keys], -1)

    for iter_num in range(start_iter, total_iters + 1):
            profiler.step(policy_step)
            policy_step += n_envs

            if is_player:
                with timer("Time/env_interaction_time", SumMetric()):
                    if iter_num < learning_starts:
                        actions = envs.action_space.sample()
                    else:
                        rng, act_key = jax.random.split(rng)
                        actions = np.asarray(player.get_actions(obs_vec, act_key))
                    next_obs, rewards, terminated, truncated, info = envs.step(
                        actions.reshape(envs.action_space.shape)
                    )
                    next_obs_vec = np.concatenate(
                        [np.asarray(next_obs[k], dtype=np.float32).reshape(n_envs, -1) for k in mlp_keys], -1
                    )
                    real_next_obs = next_obs_vec.copy()
                    if "final_obs" in info:
                        for idx, fo in enumerate(np.asarray(info["final_obs"], dtype=object)):
                            if fo is not None:
                                real_next_obs[idx] = np.concatenate(
                                    [np.asarray(fo[k], dtype=np.float32).reshape(-1) for k in mlp_keys], -1
                                )

                if cfg.metric.log_level > 0:
                    for i, (ep_rew, ep_len) in enumerate(finished_episodes(info)):
                        if aggregator and "Rewards/rew_avg" in aggregator:
                            aggregator.update("Rewards/rew_avg", ep_rew)
                        if aggregator and "Game/ep_len_avg" in aggregator:
                            aggregator.update("Game/ep_len_avg", ep_len)
                        runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")

                step_data = {
                    "observations": obs_vec[np.newaxis],
                    "actions": np.asarray(actions, dtype=np.float32).reshape(1, n_envs, -1),
                    "rewards": np.asarray(rewards, dtype=np.float32).reshape(1, n_envs, -1),
                    "terminated": np.asarray(terminated, dtype=np.uint8).reshape(1, n_envs, -1),
                    "truncated": np.asarray(truncated, dtype=np.uint8).reshape(1, n_envs, -1),
                }
                if not cfg.buffer.sample_next_obs:
                    step_data["next_observations"] = real_next_obs[np.newaxis]
                rb.add(step_data, validate_args=cfg.buffer.validate_args)
                obs_vec = next_obs_vec

            if iter_num >= learning_starts:
                ratio_steps = policy_step - prefill_steps * policy_steps_per_iter
                # Pure arithmetic over config-derived counters, so in a
                # multi-process world EVERY process computes the same count and
                # stays in lockstep (the reference broadcasts it instead,
                # sac_decoupled.py:237).
                per_rank_gradient_steps = ratio(ratio_steps / trainer_world)
                if transport is None and per_rank_gradient_steps > 0 and sentinel.ratio_scale < 1.0:
                    # health-sentinel backoff: shrink this round's gradient
                    # grant (single-controller only — every process must
                    # compute the same count in a cross-host world)
                    per_rank_gradient_steps = max(1, int(per_rank_gradient_steps * sentinel.ratio_scale))
                if per_rank_gradient_steps > 0:
                    if is_player:
                        # The player samples and ships the batch (reference :243-257)
                        sample = rb.sample(
                            per_rank_gradient_steps * cfg.algo.per_rank_batch_size * trainer_world,
                            sample_next_obs=cfg.buffer.sample_next_obs,
                            n_samples=1,
                        )
                        batches = {
                            k: np.asarray(v, dtype=np.float32).reshape(
                                per_rank_gradient_steps,
                                cfg.algo.per_rank_batch_size * trainer_world,
                                *v.shape[2:],
                            )
                            for k, v in sample.items()
                        }
                        if transport is not None:
                            transport.sync_payload_spec("sac_batches", batches)
                    else:
                        # zero templates: feature dims from the player's one-time
                        # spec, leading dim from this round's locally-computed count
                        spec = transport.sync_payload_spec("sac_batches")
                        batches = {
                            k: np.zeros((per_rank_gradient_steps,) + tuple(s[1:]), d)
                            for k, (s, d) in spec.items()
                        }
                    with timer("Time/train_time", SumMetric()):
                        rng, train_key = jax.random.split(rng)
                        player_params, train_metrics = trainer_step((batches, np.asarray(train_key)))
                        if is_player:
                            if not timer.disabled:  # fence ONLY when the train phase is timed
                                jax.block_until_ready(player_params)
                            player.params = player_params
                        cumulative_grad_steps += per_rank_gradient_steps
                        train_step += trainer_world * per_rank_gradient_steps
                    if is_player:
                        host_metrics = (
                            transport.pull_replicated(train_metrics) if transport is not None else train_metrics
                        )
                        if aggregator:
                            aggregator.update_from_device(host_metrics)
                        jax_compile.drain_compile_counters(aggregator)

            if is_player:
                # ----- health sentinel: warn -> backoff (grant above) -> rollback
                env_deltas = resilience.drain_env_counters(envs, aggregator)
                if transport is not None:
                    env_deltas.update(resilience.drain_env_counters(transport, aggregator))
                action = sentinel.observe(
                    policy_step,
                    train_metrics=host_metrics if "host_metrics" in dir() else None,
                    env_counters=env_deltas,
                )
                if action.rollback:
                    rb_state = sentinel.take_rollback_state(os.path.join(log_dir, "checkpoint"))
                    if rb_state is not None:
                        restored = jax.tree_util.tree_map(jnp.asarray, rb_state["agent"])
                        trainer_state["params"] = trainer_rt.replicate(restored)
                        trainer_state["opt_states"] = trainer_rt.replicate(
                            jax.tree_util.tree_map(jnp.asarray, rb_state["opt_states"])
                        )
                        trainer_state["update_counter"] = trainer_rt.replicate(
                            np.int32(rb_state["update_counter"])
                        )
                        ratio.load_state_dict(rb_state["ratio"])
                        # replay rows stay valid off-policy data; only the
                        # learner rewinds to the certified snapshot
                        player.params = player_rt.replicate(restored.actor)
                        runtime.print(
                            f"Health rollback at policy_step={policy_step}: restored certified "
                            "checkpoint, training continues."
                        )
                sentinel.drain(aggregator)

            if is_player and cfg.metric.log_level > 0 and (
                policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters
            ):
                if aggregator and not aggregator.disabled:
                    logger.log_metrics(aggregator.compute(), policy_step)
                    aggregator.reset()
                if logger and policy_step > 0:
                    logger.log_metrics(
                        {"Params/replay_ratio": cumulative_grad_steps * trainer_world / policy_step},
                        policy_step,
                    )
                if not timer.disabled:
                    timer_metrics = timer.compute()
                    if timer_metrics.get("Time/train_time", 0) > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                            policy_step,
                        )
                    if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                        logger.log_metrics(
                            {
                                "Time/sps_env_interaction": (
                                    (policy_step - last_log) * cfg.env.action_repeat
                                )
                                / timer_metrics["Time/env_interaction_time"]
                            },
                            policy_step,
                        )
                    timer.reset()
                last_log = policy_step
                last_train = train_step

            if is_player and (
                (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
                or (iter_num == total_iters and cfg.checkpoint.save_last)
            ):
                last_checkpoint = policy_step
                pull = jax.device_get if transport is None else transport.pull_replicated
                ckpt_state = {
                    "agent": pull(trainer_state["params"]),
                    "opt_states": pull(trainer_state["opt_states"]),
                    "update_counter": int(np.asarray(pull(trainer_state["update_counter"]))),
                    "ratio": ratio.state_dict(),
                    "iter_num": iter_num,
                    "batch_size": cfg.algo.per_rank_batch_size * trainer_world,
                    "last_log": last_log,
                    "last_checkpoint": last_checkpoint,
                }
                ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{runtime.global_rank}.ckpt")
                runtime.call(
                    "on_checkpoint_player",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.checkpoint else None,
                    healthy=sentinel.certifiable,
                    policy_step=policy_step,
                )

    profiler.close()
    if envs is not None:
        envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        test(player, player_rt, cfg, log_dir)
    if transport is not None:
        runtime.barrier()  # leave the distributed world together
    if logger:
        logger.finalize()
