"""SAC agent (flax): tanh-squashed gaussian actor + vmapped critic ensemble.

Parity with reference sheeprl/algos/sac/agent.py (SACActor :57, SACCritic :20,
SACAgent :145, SACPlayer :270, build_agent :317). TPU-first choice: the N critics are
ONE module with a stacked (vmapped) parameter ensemble — N Q-forwards become one
batched matmul chain on the MXU instead of N sequential module calls.
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.models.models import MLP
from sheeprl_tpu.utils.utils import host_float32

LOG_STD_MAX = 2
LOG_STD_MIN = -5


def action_scale_bias(low, high) -> Tuple[jax.Array, jax.Array]:
    """tanh-squash affine from box bounds, with non-finite bounds masked.

    ``(high-low)/2`` and ``(high+low)/2`` on an unbounded dim produce inf and
    inf-inf=NaN (a RuntimeWarning factory that would drown real NaN regressions
    in CI logs); an unbounded dim gets the identity map (scale 1, bias 0)
    instead — tanh already keeps the raw action finite.
    """
    low = np.asarray(low, dtype=np.float32)
    high = np.asarray(high, dtype=np.float32)
    bounded = np.isfinite(low) & np.isfinite(high)
    with np.errstate(invalid="ignore", over="ignore"):
        scale = np.where(bounded, (high - low) / 2.0, 1.0).astype(np.float32)
        bias = np.where(bounded, (high + low) / 2.0, 0.0).astype(np.float32)
    return jnp.asarray(scale), jnp.asarray(bias)


class SACActor(nn.Module):
    action_dim: int
    hidden_size: int = 256
    action_low: Any = -1.0
    action_high: Any = 1.0
    dtype: Any = jnp.float32

    @property
    def action_scale(self):
        return action_scale_bias(self.action_low, self.action_high)[0]

    @property
    def action_bias(self):
        return action_scale_bias(self.action_low, self.action_high)[1]

    @nn.compact
    def __call__(self, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = MLP(input_dims=1, hidden_sizes=(self.hidden_size, self.hidden_size), dtype=self.dtype)(obs)
        mean = nn.Dense(self.action_dim, dtype=self.dtype)(x).astype(jnp.float32)
        log_std = nn.Dense(self.action_dim, dtype=self.dtype)(x).astype(jnp.float32)
        return mean, log_std


def actor_action_and_log_prob(mean: jax.Array, log_std: jax.Array, key, action_scale, action_bias):
    """tanh-squashed rsample + Eq. 26 log-prob (reference agent.py:111-144)."""
    std = jnp.exp(jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX))
    x_t = mean + std * jax.random.normal(key, mean.shape, dtype=mean.dtype)
    y_t = jnp.tanh(x_t)
    action = y_t * action_scale + action_bias
    var = std**2
    log_prob = -((x_t - mean) ** 2) / (2 * var) - jnp.log(std) - 0.5 * jnp.log(2 * jnp.pi)
    log_prob = log_prob - jnp.log(action_scale * (1 - y_t**2) + 1e-6)
    return action, log_prob.sum(-1, keepdims=True)


def actor_greedy_action(mean: jax.Array, action_scale, action_bias) -> jax.Array:
    return jnp.tanh(mean) * action_scale + action_bias


class SACCritic(nn.Module):
    """Q(s, a) MLP; one instance is vmapped into the ensemble (reference :20-54)."""

    hidden_size: int = 256
    num_critics: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        x = jnp.concatenate([obs, action], axis=-1)
        return MLP(
            input_dims=1,
            output_dim=self.num_critics,
            hidden_sizes=(self.hidden_size, self.hidden_size),
            dtype=self.dtype,
        )(x).astype(jnp.float32)


class SACParams(NamedTuple):
    """Trainable state pytree (replaces the reference's SACAgent nn.Module :145)."""

    actor: Any
    critics: Any  # stacked ensemble params, leading axis = n critics
    target_critics: Any
    log_alpha: jax.Array


def init_sac_params(
    key: jax.Array,
    actor: SACActor,
    critic: SACCritic,
    n_critics: int,
    obs_dim: int,
    act_dim: int,
    alpha: float,
) -> SACParams:
    k_actor, k_crit = jax.random.split(key)
    actor_params = actor.init(k_actor, jnp.zeros((1, obs_dim)))
    crit_keys = jax.random.split(k_crit, n_critics)
    critics_params = jax.vmap(lambda k: critic.init(k, jnp.zeros((1, obs_dim)), jnp.zeros((1, act_dim))))(crit_keys)
    return SACParams(
        actor=actor_params,
        critics=critics_params,
        target_critics=jax.tree_util.tree_map(jnp.array, critics_params),
        log_alpha=jnp.log(jnp.asarray([alpha], dtype=jnp.float32)),
    )


def ensemble_q_values(critic: SACCritic, critics_params, obs: jax.Array, action: jax.Array) -> jax.Array:
    """All N Q-values in one vmapped call -> [batch, N]."""
    qs = jax.vmap(lambda p: critic.apply(p, obs, action))(critics_params)  # [N, B, 1]
    return jnp.moveaxis(qs[..., 0], 0, -1)


class SACPlayer:
    """Rollout/eval-side policy (reference SACPlayer :270)."""

    def __init__(self, actor: SACActor, actor_params, action_scale, action_bias):
        self.actor = actor
        self.params = actor_params
        self.action_scale = action_scale
        self.action_bias = action_bias

        def _act(params, obs, key):
            mean, log_std = actor.apply(params, obs)
            action, _ = actor_action_and_log_prob(mean, log_std, key, action_scale, action_bias)
            # host_float32: actions are pulled to host / stored f32 (bf16 degrades
            # to |V2 through the remote-TPU tunnel)
            return host_float32(action)

        def _greedy(params, obs):
            mean, _ = actor.apply(params, obs)
            return host_float32(actor_greedy_action(mean, action_scale, action_bias))

        self._act = jax_compile.guarded_jit(_act, name="sac.act")
        self._greedy = jax_compile.guarded_jit(_greedy, name="sac.greedy")

    def get_actions(self, obs: jax.Array, key: Optional[jax.Array] = None, greedy: bool = False) -> jax.Array:
        if greedy:
            return self._greedy(self.params, obs)
        return self._act(self.params, obs, key)

    __call__ = get_actions


def build_agent(
    runtime,
    cfg,
    obs_space: gymnasium.spaces.Dict,
    action_space: gymnasium.spaces.Box,
    agent_state: Optional[Dict[str, Any]] = None,
):
    """Returns (actor, critic, params: SACParams, player). Reference: agent.py:317."""
    act_dim = prod(action_space.shape)
    obs_dim = sum(prod(obs_space[k].shape) for k in cfg.algo.mlp_keys.encoder)
    actor = SACActor(
        action_dim=act_dim,
        hidden_size=cfg.algo.actor.hidden_size,
        action_low=tuple(np.asarray(action_space.low, dtype=np.float32).tolist()),
        action_high=tuple(np.asarray(action_space.high, dtype=np.float32).tolist()),
        dtype=runtime.compute_dtype,
    )
    critic = SACCritic(hidden_size=cfg.algo.critic.hidden_size, num_critics=1, dtype=runtime.compute_dtype)
    params = init_sac_params(
        jax.random.PRNGKey(cfg.seed),
        actor,
        critic,
        cfg.algo.critic.n,
        obs_dim,
        act_dim,
        cfg.algo.alpha.alpha,
    )
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, SACParams(*agent_state) if isinstance(agent_state, (tuple, list)) else agent_state)
        if not isinstance(params, SACParams):
            params = SACParams(**params) if isinstance(params, dict) else params
    params = runtime.place_params(params)
    action_scale, action_bias = action_scale_bias(action_space.low, action_space.high)
    player = SACPlayer(actor, params.actor, action_scale, action_bias)
    return actor, critic, params, player
