"""SAC utilities (reference sheeprl/algos/sac/utils.py)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
}
# Compilation-management counters (core/compile.py), drained once per iteration.
AGGREGATOR_KEYS |= {
    "Compile/retraces",
    "Compile/cache_hits",
    "Compile/cache_misses",
    "Time/compile_seconds",
}
# Host control-plane counters (parallel/control.py), drained by the decoupled loop.
from sheeprl_tpu.parallel.control import COUNTER_KEYS as _CONTROL_COUNTER_KEYS  # noqa: E402

AGGREGATOR_KEYS |= set(_CONTROL_COUNTER_KEYS)
MODELS_TO_REGISTER = {"agent"}


def prepare_obs(runtime, obs: Dict[str, np.ndarray], *, num_envs: int = 1, **kwargs) -> jax.Array:
    """Concat mlp keys into the flat `observations` vector (reference utils.py:14-20),
    committed to the player device (an uncommitted array would let the policy jit
    follow mesh-resident leaves onto the accelerator, paying a round-trip per step)."""
    mlp_keys = kwargs.get("mlp_keys", list(obs.keys()))
    flat = np.concatenate(
        [np.asarray(obs[k], dtype=np.float32).reshape(num_envs, -1) for k in mlp_keys], axis=-1
    )
    device = runtime.player_device if runtime is not None else None
    return jax.device_put(flat, device) if device is not None else jnp.asarray(flat)


def test(player, runtime, cfg, log_dir: str) -> None:
    """Greedy evaluation episode (reference utils.py:23-51)."""
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=cfg.seed)[0]
    while not done:
        jax_obs = prepare_obs(runtime, obs, num_envs=1, mlp_keys=cfg.algo.mlp_keys.encoder)
        action = np.asarray(player.get_actions(jax_obs, greedy=True))[0]
        obs, reward, terminated, truncated, _ = env.step(action.reshape(env.action_space.shape))
        done = terminated or truncated
        cumulative_rew += reward
        if cfg.dry_run:
            done = True
    if cfg.metric.log_level > 0:
        runtime.print(f"Test - Reward: {cumulative_rew}")
        if getattr(runtime, "logger", None) is not None:
            runtime.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()

# Single-'agent' registration shared with the other model-free algos.
from sheeprl_tpu.utils.model_manager import log_agent_from_checkpoint as log_models_from_checkpoint  # noqa: E402, F401
