"""SAC, coupled training (reference sheeprl/algos/sac/sac.py:32-120 train, :82 main).

TPU-first structure: per iteration the replay buffer is sampled ONCE for all gradient
steps (G x B batch), moved to HBM, and a single jitted call `lax.scan`s over the G
minibatches — critic update, conditional target-EMA, actor update, alpha update per
step. The reference's per-minibatch Python loop with three backward passes becomes one
fused XLA program; the alpha-grad all-reduce (reference sac.py:73) happens implicitly
through the sharded batch.
"""

from __future__ import annotations

import os
import warnings
from math import prod
from typing import Any, Dict, NamedTuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.algos.sac.agent import (
    SACParams,
    action_scale_bias,
    actor_action_and_log_prob,
    build_agent,
    ensemble_q_values,
)
from sheeprl_tpu.algos.sac.loss import critic_loss, entropy_loss, policy_loss
from sheeprl_tpu.algos.sac.utils import prepare_obs, test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.core import failpoints
from sheeprl_tpu.core import health as health_mod
from sheeprl_tpu.core import resilience
from sheeprl_tpu.envs import ingraph as ingraph_envs
from sheeprl_tpu.core.pipeline import AsyncEnvStepper, pipeline_enabled
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.factory import make_replay_ring
from sheeprl_tpu.data.prefetch import DevicePrefetcher
from sheeprl_tpu.telemetry import device as tel_device
from sheeprl_tpu.telemetry import programs as tel_programs
from sheeprl_tpu.utils.env import finished_episodes, make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.profiler import TraceProfiler
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import PlayerParamsSync, Ratio, polyak_update, save_configs


class SACOptStates(NamedTuple):
    qf: Any
    actor: Any
    alpha: Any


def make_update_core(actor, critic, cfg, runtime, action_scale, action_bias, target_entropy, ema_every: int):
    """The SAC gradient-step core: ``(init_opt, single_update)``.

    ``single_update`` is the unjitted scan-body update (critic + conditional
    target-EMA + actor + alpha on one minibatch). The host train step scans it
    over a prefetched ``[G, B]`` batch stack; the fused in-graph path
    (:func:`make_ingraph_step_fns`) runs the SAME closure inside its
    whole-iteration program, sampling each minibatch from the HBM replay ring —
    one definition, so the two paths cannot drift.
    """
    n_critics = int(cfg.algo.critic.n)
    gamma = float(cfg.algo.gamma)
    tau = float(cfg.algo.tau)
    data_sharding = NamedSharding(runtime.mesh, P("data"))
    qf_tx = instantiate(dict(cfg.algo.critic.optimizer))()
    actor_tx = instantiate(dict(cfg.algo.actor.optimizer))()
    alpha_tx = instantiate(dict(cfg.algo.alpha.optimizer))()

    def init_opt(params: SACParams) -> SACOptStates:
        return SACOptStates(
            qf=qf_tx.init(params.critics),
            actor=actor_tx.init(params.actor),
            alpha=alpha_tx.init(params.log_alpha),
        )

    def single_update(carry, inp):
        params, opt_states, update_idx = carry
        batch, key = inp
        batch = jax.tree_util.tree_map(
            lambda v: jax.lax.with_sharding_constraint(v, data_sharding), batch
        )
        k_next, k_actor = jax.random.split(key)
        alpha = jnp.exp(params.log_alpha)

        # ---- critic update (Eq. 5): target from next actions under current policy
        mean, log_std = actor.apply(params.actor, batch["next_observations"])
        next_actions, next_logp = actor_action_and_log_prob(mean, log_std, k_next, action_scale, action_bias)
        next_q = ensemble_q_values(critic, params.target_critics, batch["next_observations"], next_actions)
        min_next_q = jnp.min(next_q, axis=-1, keepdims=True) - alpha * next_logp
        target_q = batch["rewards"] + (1 - batch["terminated"]) * gamma * min_next_q
        target_q = jax.lax.stop_gradient(target_q)

        def qf_loss_fn(critics_params):
            qs = ensemble_q_values(critic, critics_params, batch["observations"], batch["actions"])
            return critic_loss(qs, target_q, n_critics)

        qf_l, qf_grads = jax.value_and_grad(qf_loss_fn)(params.critics)
        qf_updates, qf_opt = qf_tx.update(qf_grads, opt_states.qf, params.critics)
        new_critics = optax.apply_updates(params.critics, qf_updates)

        # ---- target EMA every `ema_every` updates (reference sac.py:55-56)
        new_targets = jax.lax.cond(
            update_idx % ema_every == 0,
            lambda tgt: polyak_update(new_critics, tgt, tau),
            lambda tgt: tgt,
            params.target_critics,
        )

        # ---- actor update (Eq. 7)
        def actor_loss_fn(actor_params):
            m, ls = actor.apply(actor_params, batch["observations"])
            acts, logp = actor_action_and_log_prob(m, ls, k_actor, action_scale, action_bias)
            qs = ensemble_q_values(critic, new_critics, batch["observations"], acts)
            min_q = jnp.min(qs, axis=-1, keepdims=True)
            return policy_loss(alpha, logp, min_q), logp

        (actor_l, logp), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(params.actor)
        actor_updates, actor_opt = actor_tx.update(actor_grads, opt_states.actor, params.actor)
        new_actor = optax.apply_updates(params.actor, actor_updates)

        # ---- alpha update (Eq. 17)
        def alpha_loss_fn(log_alpha):
            return entropy_loss(log_alpha, jax.lax.stop_gradient(logp), target_entropy)

        alpha_l, alpha_grads = jax.value_and_grad(alpha_loss_fn)(params.log_alpha)
        alpha_updates, alpha_opt = alpha_tx.update(alpha_grads, opt_states.alpha, params.log_alpha)
        new_log_alpha = optax.apply_updates(params.log_alpha, alpha_updates)

        new_params = SACParams(
            actor=new_actor, critics=new_critics, target_critics=new_targets, log_alpha=new_log_alpha
        )
        new_opt = SACOptStates(qf=qf_opt, actor=actor_opt, alpha=alpha_opt)
        return (new_params, new_opt, update_idx + 1), jnp.stack([qf_l, actor_l, alpha_l])

    return init_opt, single_update


def make_train_fn(
    actor, critic, cfg, runtime, action_scale, action_bias, target_entropy, ema_every: int, params_sync=None
):
    if int(cfg.algo.get("grad_microbatches", 1) or 1) > 1:
        # SAC's per-gradient-step batch is already tiny (one replay sample per
        # update in the G-step scan) — no bucketed accumulation to overlap
        warnings.warn(
            "algo.grad_microbatches > 1 is not supported by SAC; falling back to 1"
        )
    init_opt, single_update = make_update_core(
        actor, critic, cfg, runtime, action_scale, action_bias, target_entropy, ema_every
    )

    def train(params, opt_states, batches, key, update_start):
        g = next(iter(batches.values())).shape[0]
        keys = jax.random.split(key, g)
        (params, opt_states, update_end), losses = jax.lax.scan(
            single_update, (params, opt_states, update_start), (batches, keys)
        )
        mean_losses = losses.mean(axis=0)
        # flatten the actor for the player refresh INSIDE the jitted step: one
        # cross-backend transfer instead of a per-leaf round-trip storm (see
        # PlayerParamsSync)
        flat_actor = params_sync.ravel(params.actor) if params_sync is not None else None
        return params, opt_states, update_end, flat_actor, {
            "Loss/value_loss": mean_losses[0],
            "Loss/policy_loss": mean_losses[1],
            "Loss/alpha_loss": mean_losses[2],
        }

    return init_opt, jax_compile.guarded_jit(train, name="sac.train", donate_argnums=(0, 1))


def make_ingraph_step_fns(
    actor,
    critic,
    cfg,
    runtime,
    venv,
    ring,
    action_scale,
    action_bias,
    target_entropy,
    ema_every: int,
    params_sync,
    collect_steps: int,
    batch_size: int,
):
    """The two jitted entry points of the fused in-graph SAC iteration.

    ``prefill_fn(ring_state, carry)`` scans ``collect_steps`` uniform-action env
    steps and scatters the rows into the HBM replay ring — the pre-
    ``learning_starts`` warm-up, entirely on device.

    ``train_fn(params, opt_states, update_counter, ring_state, carry, key, g_eff)``
    is the whole iteration in one donated-carry program: a ``collect_steps``-long
    policy rollout written to the ring, then ``g_eff`` gradient steps each
    sampling the ring in-graph and running :func:`make_update_core`'s
    ``single_update``. ``g_eff`` is a TRACED scalar driving a ``fori_loop``, so
    the Ratio's variable grants (and the health sentinel's shrinking backoff)
    never retrace. Only scalar losses, the raveled actor, and the ``[T, B]``
    episode-metric leaves come back to the host.
    """
    init_opt, single_update = make_update_core(
        actor, critic, cfg, runtime, action_scale, action_bias, target_entropy, ema_every
    )
    step_fn = ingraph_envs.autoreset_step(venv.env, venv.env_params)
    act_space = venv.single_action_space
    act_low = jnp.asarray(np.asarray(act_space.low, np.float32))
    act_high = jnp.asarray(np.asarray(act_space.high, np.float32))
    T = int(collect_steps)
    batch_size = int(batch_size)
    Carry = ingraph_envs.Carry

    # single_update closes over params positionally through the scan carry; the
    # collect scan needs the CURRENT actor — a one-slot ref, same pattern as the
    # on-policy collector (envs/ingraph/rollout.py)
    actor_params_ref = [None]

    def policy_action(obs, key):
        mean, log_std = actor.apply(actor_params_ref[0], obs)
        action, _ = actor_action_and_log_prob(mean, log_std, key, action_scale, action_bias)
        return action

    def uniform_action(obs, key):
        # the pre-learning_starts exploration policy (host loop:
        # envs.action_space.sample())
        return jax.random.uniform(
            key, (obs.shape[0],) + act_low.shape, minval=act_low, maxval=act_high
        )

    def scan_steps(carry, sample_action):
        def one_step(carry, _):
            obs = carry.obs
            key, k_act, k_step = jax.random.split(carry.key, 3)
            action = sample_action(obs, k_act)
            step_keys = jax.random.split(k_step, obs.shape[0])
            state, next_obs, reward, done, info = jax.vmap(step_fn)(
                step_keys, carry.state, action
            )
            reward = reward.astype(jnp.float32)
            ep_ret = carry.ep_ret + reward
            ep_len = carry.ep_len + 1
            rows = {
                "observations": obs,
                # true successor obs (pre-reset when the episode ended): the
                # host loop's real_next_obs / final_obs branch, in-graph
                "next_observations": info["terminal_obs"],
                "actions": action,
                "rewards": reward[:, None],
                # truncated episodes still bootstrap through (1 - terminated)
                # in the critic target — same row the host loop stores
                "terminated": info["terminated"].astype(jnp.float32)[:, None],
            }
            step_metrics = {
                "episode_returns": jnp.where(done, ep_ret, 0.0),
                "episode_lengths": jnp.where(done, ep_len, 0),
                "dones": done.astype(jnp.float32),
            }
            new_carry = Carry(
                state=state,
                obs=next_obs,
                key=key,
                ep_ret=jnp.where(done, 0.0, ep_ret),
                ep_len=jnp.where(done, 0, ep_len),
            )
            return new_carry, (rows, step_metrics)

        return jax.lax.scan(one_step, carry, None, length=T)

    def prefill(ring_state, carry):
        carry, (rows, metrics) = scan_steps(carry, uniform_action)
        return ring.write(ring_state, rows), carry, metrics

    def train(params, opt_states, update_counter, ring_state, carry, key, g_eff):
        actor_params_ref[0] = params.actor
        carry, (rows, metrics) = scan_steps(carry, policy_action)
        ring_state = ring.write(ring_state, rows)

        def update_body(i, acc):
            p, o, uc, loss_sum = acc
            k_samp, k_upd = jax.random.split(jax.random.fold_in(key, i))
            batch = ring.sample(ring_state, k_samp, batch_size)
            (p, o, uc), losses = single_update((p, o, uc), (batch, k_upd))
            return (p, o, uc, loss_sum + losses)

        params, opt_states, update_counter, loss_sum = jax.lax.fori_loop(
            0,
            g_eff,
            update_body,
            (params, opt_states, update_counter, jnp.zeros((3,), jnp.float32)),
        )
        mean_losses = loss_sum / jnp.maximum(g_eff, 1).astype(jnp.float32)
        flat_actor = params_sync.ravel(params.actor)
        train_metrics = {
            "Loss/value_loss": mean_losses[0],
            "Loss/policy_loss": mean_losses[1],
            "Loss/alpha_loss": mean_losses[2],
        }
        return params, opt_states, update_counter, ring_state, carry, flat_actor, metrics, train_metrics

    prefill_fn = jax_compile.guarded_jit(
        prefill, name="sac.ingraph_prefill", donate_argnums=(0, 1)
    )
    train_fn = jax_compile.guarded_jit(
        train, name="sac.ingraph_train", donate_argnums=(0, 1, 2, 3, 4)
    )
    return init_opt, prefill_fn, train_fn


def _main_ingraph(runtime, cfg: Dict[str, Any]):
    """SAC on the in-graph env backend: the whole iteration — a T-step policy
    rollout scanned through the vmapped envs, the replay-ring write, and the
    Ratio's grant of gradient steps sampling that ring — is ONE donated-carry
    jitted program (``sac.ingraph_train``). Transitions never leave HBM:
    buffer-write to gradient-step without a host copy, the off-policy
    counterpart of the fused PPO/A2C path (envs/ingraph/fused.py).

    Single-controller, single-device by design: the replay ring is one donated
    pytree and SAC's minibatches are tiny (a [256, obs] gather), so there is no
    batch axis worth sharding the way the on-policy fused step shards its env
    batch. The ring is NOT checkpointed — on resume (and after a health
    rollback the ring simply keeps its rows) the warm-up scan refills it with
    uniform-action transitions, the same distribution the initial prefill used.
    """
    if runtime.world_size > 1:
        raise ValueError(
            "env.backend=ingraph SAC is single-controller/single-device; "
            "use the gym backend (host replay buffer) for multi-device runs"
        )
    if not ingraph_envs.fused_enabled(cfg):
        raise ValueError(
            "env.backend=ingraph SAC always runs the fused iteration (there is "
            "no split host loop over a device ring); remove env.fused=False"
        )

    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_state

        state = load_state(cfg.checkpoint.resume_from)

    if len(cfg.algo.cnn_keys.encoder) > 0:
        warnings.warn("SAC algorithm cannot allow to use images as observations, the CNN keys will be ignored")
        cfg.algo.cnn_keys.encoder = []

    logger = get_logger(runtime, cfg)
    if logger:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.logger = logger
    runtime.print(f"Log dir: {log_dir}")
    if runtime.is_global_zero and log_dir:
        # compiled-program ledger for this run (parent-pinned env path wins)
        tel_programs.configure_default(os.path.join(log_dir, "telemetry", "programs.jsonl"))

    sentinel = health_mod.HealthSentinel(
        cfg, log_dir=log_dir if runtime.is_global_zero else None, world_size=1
    )
    n_envs = int(cfg.env.num_envs)
    venv = ingraph_envs.make_vector_env(cfg, n_envs, cfg.seed, device=runtime.device)
    action_space = venv.single_action_space
    observation_space = venv.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")

    actor, critic, params, player = build_agent(
        runtime, cfg, observation_space, action_space, state["agent"] if state else None
    )
    # policy forward happens inside the collect scan on the accelerator, not on
    # the host player device build_agent placed the params on
    player.params = jax.device_put(player.params, runtime.device)
    act_dim = prod(action_space.shape)
    obs_dim = prod(observation_space[venv.obs_key].shape)
    target_entropy = jnp.float32(-act_dim)
    action_scale, action_bias = action_scale_bias(action_space.low, action_space.high)

    T = max(1, int(cfg.algo.get("ingraph_collect_steps", 64)))
    policy_steps_per_iter = n_envs * T
    if state:
        cfg.algo.per_rank_batch_size = state["batch_size"]
    batch_size = int(cfg.algo.per_rank_batch_size)
    # EMA cadence counts gradient steps exactly like the host loop (whose
    # iteration advances n_envs policy steps)
    ema_every = int(cfg.algo.critic.target_network_frequency) // n_envs + 1
    params_sync = PlayerParamsSync(player.params)

    ring = make_replay_ring(
        cfg,
        n_envs,
        {
            "observations": ((obs_dim,), jnp.float32),
            "next_observations": ((obs_dim,), jnp.float32),
            "actions": ((act_dim,), jnp.float32),
            "rewards": ((1,), jnp.float32),
            "terminated": ((1,), jnp.float32),
        },
    )
    ring_state = ring.init_state(device=runtime.device)
    init_opt, prefill_fn, train_fn = make_ingraph_step_fns(
        actor,
        critic,
        cfg,
        runtime,
        venv,
        ring,
        action_scale,
        action_bias,
        target_entropy,
        ema_every,
        params_sync,
        T,
        batch_size,
    )
    player.params = params_sync.pull(jax.jit(params_sync.ravel)(params.actor), runtime.device)
    opt_states = init_opt(params)
    if state:
        opt_states = jax.tree_util.tree_map(jnp.asarray, state["opt_states"])
    opt_states = runtime.place_params(opt_states)
    update_counter = jnp.int32(state["update_counter"]) if state else jnp.int32(0)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter)
    prefill_iters = max(1, int(cfg.algo.learning_starts) // policy_steps_per_iter)
    if cfg.dry_run:
        prefill_iters = 1
        total_iters = 2  # one prefill + one fused train call
    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state:
        ratio.load_state_dict(state["ratio"])

    start_iter = state["iter_num"] + 1 if state else 1
    policy_step = (start_iter - 1) * policy_steps_per_iter
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    last_train = 0
    train_step = 0
    # grad-steps (train_step) advance by the ratio grant, so MFU needs the
    # number of fused-program invocations to recover the per-call wall time
    train_calls = 0
    last_train_calls = 0
    cumulative_grad_steps = 0
    # the ring is not checkpointed: a resumed run re-warms it with
    # prefill_iters of uniform-action transitions before training resumes
    prefill_remaining = prefill_iters
    prefill_policy_steps = prefill_iters * policy_steps_per_iter

    rng = jax.random.PRNGKey(cfg.seed)
    venv.reset(seed=cfg.seed)

    # ----- AOT warmup (core/compile.py): both fused entry points compile on a
    # background thread against the live carry/ring placements, so the first
    # call of each executes a pre-built executable (Compile/retraces stays 0)
    warmup = jax_compile.AOTWarmup(enabled=jax_compile.aot_enabled(cfg))
    if warmup.enabled:
        warmup.add(
            prefill_fn, jax_compile.specs_of(ring_state), jax_compile.specs_of(venv.carry)
        )
        warmup.add(
            train_fn,
            jax_compile.specs_of(params),
            jax_compile.specs_of(opt_states),
            jax_compile.spec_like(update_counter),
            jax_compile.specs_of(ring_state),
            jax_compile.specs_of(venv.carry),
            jax_compile.spec_like(rng),
            jax.ShapeDtypeStruct((), jnp.int32),
        )
        if aggregator is not None:
            warmup.add_task(
                lambda: aggregator.precompile_drain(
                    ("Loss/value_loss", "Loss/policy_loss", "Loss/alpha_loss")
                )
            )
        warmup.start()

    profiler = TraceProfiler(cfg.metric.get("profiler"), log_dir if runtime.is_global_zero else None)
    train_metrics = None

    def _drain_ingraph_episodes(roll_metrics):
        # the [T, B] episode-metric pull is the ONLY bulk host traffic of an
        # iteration; skip it outright when nothing consumes it (same sampled-
        # at-drain semantics as the fused PPO/A2C loops)
        if cfg.metric.log_level <= 0 or aggregator is None or aggregator.disabled:
            return
        if policy_step - last_log < cfg.metric.log_every and iter_num != total_iters:
            return
        for ep_rew, ep_len in ingraph_envs.iter_finished_episodes(roll_metrics):
            if "Rewards/rew_avg" in aggregator:
                aggregator.update("Rewards/rew_avg", ep_rew)
            if "Game/ep_len_avg" in aggregator:
                aggregator.update("Game/ep_len_avg", ep_len)
            runtime.print(f"Rank-0: policy_step={policy_step}, episode_reward={ep_rew}")

    for iter_num in range(start_iter, total_iters + 1):
        profiler.step(policy_step)
        policy_step += policy_steps_per_iter
        if iter_num == start_iter:
            # both fused entry points must be pre-built before their first call
            # or the call itself traces (an AOT fallback counts as a retrace)
            warmup.wait()

        if prefill_remaining > 0:
            prefill_remaining -= 1
            with timer("Time/env_interaction_time", SumMetric()):
                ring_state, carry, roll_metrics = prefill_fn(ring_state, venv.carry)
                venv.carry = carry
                if not timer.disabled:
                    jax.block_until_ready(carry.obs)
        else:
            # chaos seam first, so drills and the sentinel's rollback ladder
            # cover the fused path too
            failpoints.failpoint("train.fused_update", iter=iter_num)
            g = ratio(policy_step - prefill_policy_steps)
            if g > 0 and sentinel.ratio_scale < 1.0:
                # health-sentinel backoff: shrink this iteration's grant (the
                # dropped steps are spent, not deferred). g stays a TRACED
                # operand of the fused step, so the shrink never retraces.
                g = max(1, int(g * sentinel.ratio_scale))
            with timer("Time/train_time", SumMetric()):
                rng, train_key = jax.random.split(rng)
                (
                    params,
                    opt_states,
                    update_counter,
                    ring_state,
                    carry,
                    flat_actor,
                    roll_metrics,
                    train_metrics,
                ) = train_fn(
                    params,
                    opt_states,
                    update_counter,
                    ring_state,
                    venv.carry,
                    train_key,
                    jnp.int32(g),
                )
                venv.carry = carry
                player.params = params_sync.pull(flat_actor, runtime.device)
                if not timer.disabled:
                    jax.block_until_ready(flat_actor)
            train_step += g
            train_calls += 1
            cumulative_grad_steps += g

        venv.fire_autoreset_failpoints(roll_metrics["dones"])
        _drain_ingraph_episodes(roll_metrics)

        if cfg.metric.log_level > 0 and policy_step > 0:
            if train_metrics is not None and aggregator:
                aggregator.update_from_device(train_metrics)
            if policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters:
                if cumulative_grad_steps > 0:
                    logger.log_metrics(
                        {"Params/replay_ratio": cumulative_grad_steps / policy_step}, policy_step
                    )
                if aggregator and not aggregator.disabled:
                    logger.log_metrics(aggregator.compute(), policy_step)
                    aggregator.reset()
                if not timer.disabled:
                    timer_metrics = timer.compute()
                    if timer_metrics.get("Time/train_time", 0) > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                            policy_step,
                        )
                        _mfu = tel_device.mfu(
                            getattr(train_fn, "last_step_flops", None),
                            timer_metrics["Time/train_time"]
                            / max(train_calls - last_train_calls, 1),
                            runtime.device,
                        )
                        if _mfu is not None:
                            logger.log_metrics({"Time/mfu": _mfu}, policy_step)
                    timer.reset()
                last_log = policy_step
                last_train = train_step
                last_train_calls = train_calls

        env_deltas = resilience.drain_env_counters(venv, aggregator)
        jax_compile.drain_compile_counters(aggregator)
        if cumulative_grad_steps > 0 and not jax_compile.is_steady():
            jax_compile.mark_steady()

        action = sentinel.observe(policy_step, train_metrics=train_metrics, env_counters=env_deltas)
        if action.rollback:
            rb_state = sentinel.take_rollback_state(os.path.join(log_dir, "checkpoint"))
            if rb_state is not None:
                params = runtime.place_params(jax.tree_util.tree_map(jnp.asarray, rb_state["agent"]))
                opt_states = runtime.place_params(
                    jax.tree_util.tree_map(jnp.asarray, rb_state["opt_states"])
                )
                update_counter = jnp.int32(rb_state["update_counter"])
                ratio.load_state_dict(rb_state["ratio"])
                # the ring keeps its rows (off-policy data stays valid); only
                # the learner state rewinds to the certified snapshot
                player.params = params_sync.pull(
                    jax.jit(params_sync.ravel)(params.actor), runtime.device
                )
                runtime.print(
                    f"Health rollback at policy_step={policy_step}: restored certified "
                    "checkpoint, training continues."
                )
        sentinel.drain(aggregator)

        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            iter_num == total_iters and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": jax.device_get(params),
                "opt_states": jax.device_get(opt_states),
                "update_counter": int(update_counter),
                "ratio": ratio.state_dict(),
                "iter_num": iter_num,
                "batch_size": cfg.algo.per_rank_batch_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{runtime.global_rank}.ckpt")
            runtime.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                healthy=sentinel.certifiable,
                policy_step=policy_step,
            )

    profiler.close()
    venv.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        obs_key = venv.obs_key

        class _EvalPlayer:
            # adapt SACPlayer (flat-obs, action-only return) to the dict-obs
            # (actions, key) protocol the shared ingraph greedy eval drives
            def get_actions(self, obs, key, greedy=False):
                key, sub = jax.random.split(key)
                return player.get_actions(obs[obs_key], sub, greedy=greedy), key

        ingraph_envs.test(_EvalPlayer(), runtime, cfg, log_dir)
    if logger:
        logger.finalize()


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    if ingraph_envs.env_backend(cfg) == "ingraph":
        # in-graph backend: device-resident envs + HBM replay ring, the whole
        # iteration fused into one jitted program — a separate loop shape from
        # the per-step host interaction below
        return _main_ingraph(runtime, cfg)
    if "minedojo" in cfg.env.wrapper._target_.lower():
        raise ValueError("MineDojo is not currently supported by SAC agent.")
    world_size = runtime.world_size

    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_state

        state = load_state(cfg.checkpoint.resume_from)

    if len(cfg.algo.cnn_keys.encoder) > 0:
        warnings.warn("SAC algorithm cannot allow to use images as observations, the CNN keys will be ignored")
        cfg.algo.cnn_keys.encoder = []

    logger = get_logger(runtime, cfg)
    if logger:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.logger = logger
    runtime.print(f"Log dir: {log_dir}")

    ft = resilience.resolve(cfg)
    sentinel = health_mod.HealthSentinel(
        cfg, log_dir=log_dir if runtime.is_global_zero else None, world_size=world_size
    )
    n_envs = cfg.env.num_envs * world_size
    envs = resilience.make_supervised_env(
        [
            make_env(cfg, cfg.seed + i, 0, log_dir if runtime.is_global_zero else None, "train", vector_env_idx=i)
            for i in range(n_envs)
        ],
        sync=cfg.env.sync_env,
        ft=ft,
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.algo.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")
    for k in cfg.algo.mlp_keys.encoder:
        if len(observation_space[k].shape) > 1:
            raise ValueError(
                "Only environments with vector-only observations are supported by the SAC agent. "
                f"The observation with key '{k}' has shape {observation_space[k].shape}. "
                f"Provided environment: {cfg.env.id}"
            )

    actor, critic, params, player = build_agent(
        runtime, cfg, observation_space, action_space, state["agent"] if state else None
    )
    act_dim = prod(action_space.shape)
    target_entropy = jnp.float32(-act_dim)
    action_scale, action_bias = action_scale_bias(action_space.low, action_space.high)

    policy_steps_per_iter = int(n_envs)
    ema_every = int(cfg.algo.critic.target_network_frequency) // policy_steps_per_iter + 1
    params_sync = PlayerParamsSync(player.params)
    init_opt, train_fn = make_train_fn(
        actor, critic, cfg, runtime, action_scale, action_bias, target_entropy, ema_every, params_sync
    )
    # the host player must never hold mesh-resident params: its action pulls would
    # fail/pay per-leaf round-trips, and player_sync_every>1 defers the first refresh
    player.params = params_sync.pull(jax.jit(params_sync.ravel)(params.actor), runtime.player_device)
    opt_states = init_opt(params)
    if state:
        opt_states = jax.tree_util.tree_map(jnp.asarray, state["opt_states"])
    opt_states = runtime.place_params(opt_states)
    update_counter = jnp.int32(state["update_counter"]) if state else jnp.int32(0)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    buffer_size = cfg.buffer.size // n_envs if not cfg.dry_run else 1
    if bool(cfg.buffer.get("device", False)):
        raise ValueError(
            "buffer.device=True is currently supported by the Dreamer-family loops "
            "only; use the host buffer here"
        )
    rb = ReplayBuffer(
        buffer_size,
        n_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{runtime.global_rank}"),
        obs_keys=("observations",),
    )
    if state and cfg.buffer.checkpoint and "rb" in state:
        rb.load_state_dict(state["rb"])

    last_train = 0
    train_step = 0
    start_iter = (state["iter_num"] // world_size) + 1 if state else 1
    policy_step = state["iter_num"] * cfg.env.num_envs if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    player_sync_every = max(1, int(cfg.algo.get("player_sync_every", 1)))
    train_every = max(1, int(cfg.algo.get("train_every", 1)))
    if state:
        ratio.load_state_dict(state["ratio"])

    def sample_batches(g: int):
        bs = cfg.algo.per_rank_batch_size * world_size
        sample = rb.sample(batch_size=g * bs, sample_next_obs=cfg.buffer.sample_next_obs)
        return {k: np.asarray(v, dtype=np.float32).reshape(g, bs, *v.shape[2:]) for k, v in sample.items()}

    # Double-buffered host->HBM pipeline (see sheeprl_tpu/data/prefetch.py): the
    # [G, B] batch for the next train call transfers while the chip is still busy.
    prefetcher = DevicePrefetcher(
        sample_batches,
        device=NamedSharding(runtime.mesh, P(None, "data")),
        chunk=int(cfg.buffer.get("prefetch_batches", 1)),
        chunk_key="g",
    )

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    profiler = TraceProfiler(cfg.metric.get("profiler"), log_dir if runtime.is_global_zero else None)
    rng = jax.random.PRNGKey(cfg.seed)
    # rollout randomness lives on the PLAYER device: feeding mesh-resident keys/obs
    # into the host player's jit would silently move the policy step onto the
    # accelerator and pay a synchronous round-trip per env step
    player_rng = jax.device_put(jax.random.PRNGKey(cfg.seed + 1), runtime.player_device)
    mlp_keys = cfg.algo.mlp_keys.encoder
    cumulative_grad_steps = 0

    last_flat_actor = None
    train_calls = 0
    obs = envs.reset(seed=cfg.seed)[0]
    obs_vec = np.concatenate([np.asarray(obs[k], dtype=np.float32).reshape(n_envs, -1) for k in mlp_keys], -1)
    # software pipeline (core/pipeline.py): the env workers step while the chip
    # runs the training phase below — the prefetcher already samples one train
    # call behind, so training never depended on the in-flight row anyway
    stepper = AsyncEnvStepper(envs, enabled=pipeline_enabled(cfg))

    for iter_num in range(start_iter, total_iters + 1):
        profiler.step(policy_step)
        policy_step += n_envs

        with timer("Time/env_interaction_time", SumMetric()):
            if iter_num < learning_starts:
                actions = envs.action_space.sample()
            else:
                player_rng, act_key = jax.random.split(player_rng)
                # SAC's obs is a single flat vector: one small put per step (the
                # PPO-style packed codec would be the same single transfer)
                actions = np.asarray(
                    player.get_actions(jax.device_put(obs_vec, runtime.player_device), act_key)
                )
            stepper.step_async(actions.reshape(envs.action_space.shape))

        env_step_done = False

        def _finish_env_step():
            nonlocal env_step_done, obs_vec
            if env_step_done:
                return
            env_step_done = True
            with timer("Time/env_interaction_time", SumMetric()):
                next_obs, rewards, terminated, truncated, info = stepper.step_wait()
                next_obs_vec = np.concatenate(
                    [np.asarray(next_obs[k], dtype=np.float32).reshape(n_envs, -1) for k in mlp_keys], -1
                )
                # real next obs for terminated envs is in final_obs (SAME_STEP autoreset)
                real_next_obs = next_obs_vec.copy()
                if "final_obs" in info:
                    for idx, fo in enumerate(np.asarray(info["final_obs"], dtype=object)):
                        if fo is not None:
                            real_next_obs[idx] = np.concatenate(
                                [np.asarray(fo[k], dtype=np.float32).reshape(-1) for k in mlp_keys], -1
                            )
            step_data = {
                "terminated": np.asarray(terminated).reshape(1, n_envs, -1).astype(np.uint8),
                "truncated": np.asarray(truncated).reshape(1, n_envs, -1).astype(np.uint8),
                "actions": np.asarray(actions).reshape(1, n_envs, -1).astype(np.float32),
                "observations": obs_vec[np.newaxis],
                "rewards": np.asarray(rewards, dtype=np.float32).reshape(1, n_envs, -1),
            }
            if not cfg.buffer.sample_next_obs:
                step_data["next_observations"] = real_next_obs[np.newaxis]
            with prefetcher.guard():  # no torn rows under the worker's concurrent sample
                rb.add(step_data, validate_args=cfg.buffer.validate_args)
            obs_vec = next_obs_vec
            if cfg.metric.log_level > 0:
                for i, (ep_rew, ep_len) in enumerate(finished_episodes(info)):
                    if aggregator and "Rewards/rew_avg" in aggregator:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                    if aggregator and "Game/ep_len_avg" in aggregator:
                        aggregator.update("Game/ep_len_avg", ep_len)
                    runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")

        if not rb.full and getattr(rb, "_pos", 0) < 2:
            # too few stored rows to sample from: complete the env step serially
            # before the first train calls (startup edge only)
            _finish_env_step()

        # ---- overlap window: env workers step while the chip trains.
        # ``algo.train_every > 1`` batches several iterations' gradient steps into
        # one jitted call (Ratio keeps the step accounting exact): on remote
        # accelerators every dispatched program costs fixed round-trip overhead,
        # so fusing N iterations' updates divides that overhead by N at the
        # price of params being up to N-1 env steps staler for replay writes.
        if iter_num >= learning_starts and (
            train_every <= 1 or iter_num % train_every == 0 or iter_num == total_iters
        ):
            per_rank_gradient_steps = ratio((policy_step - prefill_steps * n_envs) / world_size)
            if per_rank_gradient_steps > 0 and sentinel.ratio_scale < 1.0:
                # health-sentinel backoff for replay-ratio loops: shrink this
                # iteration's gradient-step grant (the dropped steps are spent,
                # not deferred — a deliberate cooling-off, not bookkeeping)
                per_rank_gradient_steps = max(1, int(per_rank_gradient_steps * sentinel.ratio_scale))
            if per_rank_gradient_steps > 0:
                g = per_rank_gradient_steps
                # prefetched during the previous train step (sample + async device_put
                # overlap compute); kwargs change -> synchronous fallback inside get()
                batches = prefetcher.get(g=g)
                with timer("Time/train_time", SumMetric()):
                    rng, train_key = jax.random.split(rng)
                    params, opt_states, update_counter, flat_actor, train_metrics = train_fn(
                        params, opt_states, batches, train_key, update_counter
                    )
                    # ONE flat cross-backend transfer refreshes the host player; on
                    # remote accelerators cfg.algo.player_sync_every amortizes the
                    # round-trip. The explicit block keeps Time/train_time honest on
                    # locally-attached backends (async dispatch returns instantly).
                    last_flat_actor = flat_actor
                    # cadence counts TRAIN calls (iter_num can skip sync forever
                    # when Ratio grants steps only on a phase-locked subset)
                    train_calls += 1
                    if train_calls % player_sync_every == 0:
                        player.params = params_sync.pull(flat_actor, runtime.player_device)
                    if not timer.disabled:
                        # fence ONLY when timing: Time/train_time must include the
                        # device work, but an unconditional per-iteration sync would
                        # serialize the loop on the dispatch round-trip
                        jax.block_until_ready(flat_actor)
                    cumulative_grad_steps += g
                train_step += world_size * g

        _finish_env_step()

        if cfg.metric.log_level > 0 and policy_step > 0:
            if iter_num >= learning_starts and "train_metrics" in dir():
                if aggregator:
                    aggregator.update_from_device(train_metrics)
            if policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters:
                overlap_s, overlap_steps = stepper.drain_overlap()
                if overlap_s > 0:
                    sps_overlap = overlap_steps * n_envs * cfg.env.action_repeat / overlap_s
                    if aggregator and "Time/sps_pipeline_overlap" in aggregator:
                        aggregator.update("Time/sps_pipeline_overlap", sps_overlap)
                    else:
                        logger.log_metrics({"Time/sps_pipeline_overlap": sps_overlap}, policy_step)
                if cumulative_grad_steps > 0:
                    logger.log_metrics(
                        {"Params/replay_ratio": cumulative_grad_steps * world_size / policy_step}, policy_step
                    )
                if aggregator and not aggregator.disabled:
                    logger.log_metrics(aggregator.compute(), policy_step)
                    aggregator.reset()
                if not timer.disabled:
                    timer_metrics = timer.compute()
                    if timer_metrics.get("Time/train_time", 0) > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                            policy_step,
                        )
                    if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                        logger.log_metrics(
                            {
                                "Time/sps_env_interaction": (
                                    (policy_step - last_log) / world_size * cfg.env.action_repeat
                                )
                                / timer_metrics["Time/env_interaction_time"]
                            },
                            policy_step,
                        )
                    timer.reset()
                last_log = policy_step
                last_train = train_step

        env_deltas = resilience.drain_env_counters(envs, aggregator)
        jax_compile.drain_compile_counters(aggregator)
        if cumulative_grad_steps > 0 and not jax_compile.is_steady():
            # everything reachable has compiled once: later traces are drift
            jax_compile.mark_steady()

        # ----- health sentinel: warn -> backoff (ratio grant above) -> rollback
        action = sentinel.observe(
            policy_step,
            train_metrics=train_metrics if iter_num >= learning_starts and "train_metrics" in dir() else None,
            env_counters=env_deltas,
        )
        if action.rollback:
            rb_state = sentinel.take_rollback_state(os.path.join(log_dir, "checkpoint"))
            if rb_state is not None:
                params = runtime.place_params(
                    jax.tree_util.tree_map(jnp.asarray, rb_state["agent"])
                )
                opt_states = runtime.place_params(
                    jax.tree_util.tree_map(jnp.asarray, rb_state["opt_states"])
                )
                update_counter = jnp.int32(rb_state["update_counter"])
                ratio.load_state_dict(rb_state["ratio"])
                # the replay buffer keeps its rows (off-policy data stays valid);
                # only the learner state rewinds to the certified snapshot
                player.params = params_sync.pull(
                    params_sync.ravel(params.actor), runtime.player_device
                )
                last_flat_actor = None
                runtime.print(
                    f"Health rollback at policy_step={policy_step}: restored certified "
                    "checkpoint, training continues."
                )
        sentinel.drain(aggregator)

        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            iter_num == total_iters and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": jax.device_get(params),
                "opt_states": jax.device_get(opt_states),
                "update_counter": int(update_counter),
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{runtime.global_rank}.ckpt")
            runtime.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
                io_lock=prefetcher.guard(),
                healthy=sentinel.certifiable,
                policy_step=policy_step,
            )

    prefetcher.close()
    profiler.close()
    envs.close()
    if last_flat_actor is not None:
        # final refresh: player_sync_every may have skipped the last iterations,
        # and test()/model registration must see the final policy
        player.params = params_sync.pull(last_flat_actor, runtime.player_device)
    if runtime.is_global_zero and cfg.algo.run_test:
        test(player, runtime, cfg, log_dir)
    if logger:
        logger.finalize()
