"""DreamerV2, coupled training (reference sheeprl/algos/dreamer_v2/dreamer_v2.py:41-785).

TPU-first train step: per iteration the buffer is sampled once for all G gradient
steps ([G, T, B, *] batch) and ONE jitted call `lax.scan`s over G. Each gradient step
fuses (a) the world-model update — encoder batched over [T,B], RSSM dynamic unrolled
by `lax.scan` over T (the reference loops in Python, dreamer_v2.py:144-157) — (b) the
actor update with the H-step imagination `lax.scan` differentiated end-to-end
(objective_mix blends reinforce and dynamics backprop), and (c) the gaussian critic
update with an in-graph conditional hard target-critic copy (the reference copies
parameters on the host every `per_rank_target_network_update_freq` steps,
dreamer_v2.py:698-701). The batch axis is sharded over the `data` mesh axis; XLA
inserts the gradient all-reduce over ICI (replacing Fabric DDP).
"""

from __future__ import annotations

import os
import warnings
from functools import partial
from typing import Any, Dict, NamedTuple, Sequence

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.core import health as health_mod
from sheeprl_tpu.core import resilience
from sheeprl_tpu.algos.dreamer_v2.agent import ActorOutputDV2, DV2Modules, build_agent, expl_amount_schedule
from sheeprl_tpu.algos.dreamer_v2.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v2.utils import compute_lambda_values, test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.core.pipeline import AsyncEnvStepper, PackedObsCodec, pipeline_enabled
from sheeprl_tpu.data.factory import make_episode_replay, make_sequential_replay
from sheeprl_tpu.ops.distributions import Bernoulli, Independent, Normal, OneHotCategorical
from sheeprl_tpu.telemetry import device as tel_device
from sheeprl_tpu.utils.env import finished_episodes, final_observations, make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.optim import with_clipping
from sheeprl_tpu.utils.profiler import TraceProfiler
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import NUMPY_TO_JAX_DTYPE, DreamerPlayerSync, Ratio, save_configs

# Obs->latent->action world-model subset the rollout player needs (see
# PlayerDV2._raw_step); shipped to the player device by DreamerPlayerSync.
PLAYER_WM_KEYS = ("encoder", "recurrent_model", "representation_model")


class DV2OptStates(NamedTuple):
    world: Any
    actor: Any
    critic: Any


def make_train_fn(modules: DV2Modules, cfg, runtime, is_continuous: bool, actions_dim: Sequence[int], psync=None):
    """Build (init_opt, train) where train is a single jitted scan over G gradient steps."""
    rssm = modules.rssm
    horizon = int(cfg.algo.horizon)
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    ent_coef = float(cfg.algo.actor.ent_coef)
    objective_mix = float(cfg.algo.actor.objective_mix)
    kl_balancing_alpha = float(cfg.algo.world_model.kl_balancing_alpha)
    kl_free_nats = float(cfg.algo.world_model.kl_free_nats)
    kl_free_avg = bool(cfg.algo.world_model.kl_free_avg)
    kl_regularizer = float(cfg.algo.world_model.kl_regularizer)
    discount_scale_factor = float(cfg.algo.world_model.discount_scale_factor)
    use_continues = bool(cfg.algo.world_model.use_continues) and modules.continue_model is not None
    stoch_size = rssm.stoch_state_size
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_keys_dec = list(cfg.algo.cnn_keys.decoder)
    mlp_keys_dec = list(cfg.algo.mlp_keys.decoder)
    target_freq = int(cfg.algo.critic.per_rank_target_network_update_freq)
    data_sharding = NamedSharding(runtime.mesh, P(None, "data"))

    world_tx = with_clipping(
        instantiate(dict(cfg.algo.world_model.optimizer))(), cfg.algo.world_model.clip_gradients
    )
    actor_tx = with_clipping(instantiate(dict(cfg.algo.actor.optimizer))(), cfg.algo.actor.clip_gradients)
    critic_tx = with_clipping(instantiate(dict(cfg.algo.critic.optimizer))(), cfg.algo.critic.clip_gradients)

    def init_opt(params) -> DV2OptStates:
        return DV2OptStates(
            world=world_tx.init(params["world_model"]),
            actor=actor_tx.init(params["actor"]),
            critic=critic_tx.init(params["critic"]),
        )

    def one_step(carry, inp):
        params, opt_states, counter = carry
        data, key = inp
        data = jax.tree_util.tree_map(lambda v: jax.lax.with_sharding_constraint(v, data_sharding), data)
        k_wm, k_img = jax.random.split(key)

        # ---- hard target-critic copy (reference dreamer_v2.py:698-701)
        target_critic = jax.lax.cond(
            counter % target_freq == 0,
            lambda tc: jax.tree_util.tree_map(lambda p: p, params["critic"]),
            lambda tc: tc,
            params["target_critic"],
        )

        batch_obs = {k: data[k].astype(jnp.float32) / 255.0 - 0.5 for k in cnn_keys}
        batch_obs.update({k: data[k].astype(jnp.float32) for k in mlp_keys})
        is_first = data["is_first"].astype(jnp.float32).at[0].set(1.0)
        # Buffer rows store (o_t, a_{t-1->t}): the stored action enters the recurrent
        # model unshifted (reference dreamer_v2.py:149).
        actions = data["actions"].astype(jnp.float32)
        rewards = data["rewards"].astype(jnp.float32)
        terminated = data["terminated"].astype(jnp.float32)

        # ---- world-model update (Eq. 2)
        def world_loss_fn(wm_params):
            embedded = modules.encoder.apply(wm_params["encoder"], batch_obs)
            recurrent_states, posteriors, priors_logits, posteriors_logits = rssm.dynamic_scan(
                wm_params, embedded, actions, is_first, k_wm
            )
            latent_states = jnp.concatenate(
                [posteriors.reshape(*posteriors.shape[:-2], -1), recurrent_states], axis=-1
            )
            reconstructed = modules.observation_model.apply(wm_params["observation_model"], latent_states)
            po_log_probs = {
                k: Independent(Normal(reconstructed[k], jnp.ones_like(reconstructed[k])), reconstructed[k].ndim - 2)
                .log_prob(batch_obs[k])
                for k in cnn_keys_dec + mlp_keys_dec
            }
            pr_log_prob = Independent(
                Normal(
                    modules.reward_model.apply(wm_params["reward_model"], latent_states),
                    jnp.ones_like(rewards),
                ),
                1,
            ).log_prob(rewards)
            pc_log_prob = None
            if use_continues:
                pc_log_prob = Independent(
                    Bernoulli(logits=modules.continue_model.apply(wm_params["continue_model"], latent_states)), 1
                ).log_prob((1.0 - terminated) * gamma)
            loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
                po_log_probs,
                pr_log_prob,
                priors_logits.reshape(*priors_logits.shape[:-1], -1, rssm.discrete_size),
                posteriors_logits.reshape(*posteriors_logits.shape[:-1], -1, rssm.discrete_size),
                kl_balancing_alpha,
                kl_free_nats,
                kl_free_avg,
                kl_regularizer,
                pc_log_prob,
                discount_scale_factor,
            )
            aux = {
                "posteriors": posteriors,
                "recurrent_states": recurrent_states,
                "priors_logits": priors_logits,
                "posteriors_logits": posteriors_logits,
                "kl": kl,
                "state_loss": state_loss,
                "reward_loss": reward_loss,
                "observation_loss": observation_loss,
                "continue_loss": continue_loss,
            }
            return loss, aux

        (world_loss, aux), world_grads = jax.value_and_grad(world_loss_fn, has_aux=True)(params["world_model"])
        world_grad_norm = optax.global_norm(world_grads)
        world_updates, world_opt = world_tx.update(world_grads, opt_states.world, params["world_model"])
        new_wm = optax.apply_updates(params["world_model"], world_updates)

        # ---- behaviour learning (imagination with the freshly-updated world model)
        posteriors = jax.lax.stop_gradient(aux["posteriors"])
        recurrent_states = jax.lax.stop_gradient(aux["recurrent_states"])
        start_prior = posteriors.reshape(1, -1, stoch_size)[0]  # [T*B, S*D]
        start_recurrent = recurrent_states.reshape(1, -1, recurrent_states.shape[-1])[0]
        true_continue = (1.0 - terminated).reshape(-1, 1) * gamma  # [T*B, 1]
        img_keys = jax.random.split(k_img, horizon)

        def imagine(actor_params, keys):
            """H-step differentiable imagination; trajectories[0] is the replay latent
            and actions[0] is zeros (reference dreamer_v2.py:216-256)."""
            latent0 = jnp.concatenate([start_prior, start_recurrent], axis=-1)

            def step(carry, k):
                prior_flat, rec_state = carry
                k_act_step, k_img_step = jax.random.split(k)
                latent = jnp.concatenate([prior_flat, rec_state], axis=-1)
                out = ActorOutputDV2(
                    modules.actor, modules.actor.apply(actor_params, jax.lax.stop_gradient(latent))
                )
                act = jnp.concatenate(out.sample_actions(k_act_step), axis=-1)
                prior, rec_state = rssm.imagination_step(new_wm, prior_flat, rec_state, act, k_img_step)
                prior_flat = prior.reshape(prior_flat.shape)
                new_latent = jnp.concatenate([prior_flat, rec_state], axis=-1)
                return (prior_flat, rec_state), (new_latent, act)

            _, (latents, acts) = jax.lax.scan(step, (start_prior, start_recurrent), keys)
            trajectories = jnp.concatenate([latent0[None], latents], axis=0)  # [H+1, TB, L]
            im_actions = jnp.concatenate([jnp.zeros_like(acts[:1]), acts], axis=0)  # [H+1, TB, A]
            return trajectories, im_actions

        def actor_loss_fn(actor_params):
            trajectories, im_actions = imagine(actor_params, img_keys)
            predicted_target_values = modules.critic.apply(target_critic, trajectories)
            predicted_rewards = modules.reward_model.apply(new_wm["reward_model"], trajectories)
            if use_continues:
                continues = jax.nn.sigmoid(
                    modules.continue_model.apply(new_wm["continue_model"], trajectories)
                )
                continues = jnp.concatenate([true_continue[None], continues[1:]], axis=0)
            else:
                continues = jnp.ones_like(predicted_rewards) * gamma
            lambda_values = compute_lambda_values(
                predicted_rewards[:-1],
                predicted_target_values[:-1],
                continues[:-1],
                bootstrap=predicted_target_values[-1:],
                lmbda=lmbda,
            )
            discount = jax.lax.stop_gradient(
                jnp.cumprod(jnp.concatenate([jnp.ones_like(continues[:1]), continues[:-1]], axis=0), axis=0)
            )

            policies = ActorOutputDV2(
                modules.actor,
                modules.actor.apply(actor_params, jax.lax.stop_gradient(trajectories[:-2])),
            )
            # Dynamics backprop objective (through the imagined world-model rollout)
            dynamics = lambda_values[1:]
            # Reinforce objective with the target critic as baseline
            advantage = jax.lax.stop_gradient(lambda_values[1:] - predicted_target_values[:-2])
            splits = np.cumsum(np.asarray(actions_dim))[:-1]
            action_parts = jnp.split(jax.lax.stop_gradient(im_actions[1:-1]), splits, axis=-1)
            log_probs = sum(d.log_prob(a) for d, a in zip(policies.dists, action_parts))
            reinforce = log_probs[..., None] * advantage
            objective = objective_mix * reinforce + (1 - objective_mix) * dynamics
            try:
                entropy = ent_coef * policies.entropy()
            except NotImplementedError:
                entropy = jnp.zeros(objective.shape[:-1], dtype=jnp.float32)
            policy_loss = -jnp.mean(discount[:-2] * (objective + entropy[..., None]))
            aux_a = {
                "trajectories": trajectories,
                "lambda_values": lambda_values,
                "discount": discount,
            }
            return policy_loss, aux_a

        (policy_loss, aux_a), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(params["actor"])
        actor_grad_norm = optax.global_norm(actor_grads)
        actor_updates, actor_opt = actor_tx.update(actor_grads, opt_states.actor, params["actor"])
        new_actor = optax.apply_updates(params["actor"], actor_updates)

        # ---- critic update (Eq. 3) on the detached trajectories
        trajectories = jax.lax.stop_gradient(aux_a["trajectories"])
        lambda_values = jax.lax.stop_gradient(aux_a["lambda_values"])
        discount = aux_a["discount"]

        def critic_loss_fn(critic_params):
            qv = Independent(
                Normal(
                    modules.critic.apply(critic_params, trajectories[:-1]),
                    jnp.ones_like(lambda_values),
                ),
                1,
            )
            return -jnp.mean(discount[:-1, ..., 0] * qv.log_prob(lambda_values))

        value_loss, critic_grads = jax.value_and_grad(critic_loss_fn)(params["critic"])
        critic_grad_norm = optax.global_norm(critic_grads)
        critic_updates, critic_opt = critic_tx.update(critic_grads, opt_states.critic, params["critic"])
        new_critic = optax.apply_updates(params["critic"], critic_updates)

        post_ent = (
            Independent(
                OneHotCategorical(
                    logits=aux["posteriors_logits"].reshape(
                        *aux["posteriors_logits"].shape[:-1], -1, rssm.discrete_size
                    )
                ),
                1,
            )
            .entropy()
            .mean()
        )
        prior_ent = (
            Independent(
                OneHotCategorical(
                    logits=aux["priors_logits"].reshape(*aux["priors_logits"].shape[:-1], -1, rssm.discrete_size)
                ),
                1,
            )
            .entropy()
            .mean()
        )
        new_params = {
            "world_model": new_wm,
            "actor": new_actor,
            "critic": new_critic,
            "target_critic": target_critic,
        }
        metrics = jnp.stack(
            [
                world_loss,
                value_loss,
                policy_loss,
                aux["observation_loss"],
                aux["reward_loss"],
                aux["state_loss"],
                aux["continue_loss"],
                aux["kl"],
                post_ent,
                prior_ent,
                world_grad_norm,
                actor_grad_norm,
                critic_grad_norm,
            ]
        )
        return (new_params, DV2OptStates(world_opt, actor_opt, critic_opt), counter + 1), metrics

    def train(params, opt_states, counter, batches, key):
        g = next(iter(batches.values())).shape[0]
        keys = jax.random.split(key, g)
        (params, opt_states, counter), metrics = jax.lax.scan(
            one_step, (params, opt_states, counter), (batches, keys)
        )
        m = metrics.mean(axis=0)
        named = {
            "Loss/world_model_loss": m[0],
            "Loss/value_loss": m[1],
            "Loss/policy_loss": m[2],
            "Loss/observation_loss": m[3],
            "Loss/reward_loss": m[4],
            "Loss/state_loss": m[5],
            "Loss/continue_loss": m[6],
            "State/kl": m[7],
            "State/post_entropy": m[8],
            "State/prior_entropy": m[9],
            "Grads/world_model": m[10],
            "Grads/actor": m[11],
            "Grads/critic": m[12],
        }
        # raveled player subset computed in-graph: the host-player refresh is one
        # flat transfer, not a per-leaf pull (see DreamerPlayerSync)
        flat_player = psync.ravel(params) if psync is not None else None
        return params, opt_states, counter, flat_player, named

    return init_opt, jax_compile.guarded_jit(train, name="dv2.train", donate_argnums=(0, 1))


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    world_size = runtime.world_size
    rank = runtime.global_rank

    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_state

        state = load_state(cfg.checkpoint.resume_from)

    # These arguments cannot be changed (reference dreamer_v2.py:398-400)
    cfg.env.screen_size = 64
    cfg.env.frame_stack = 1

    logger = get_logger(runtime, cfg)
    if logger:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.logger = logger
    runtime.print(f"Log dir: {log_dir}")

    ft = resilience.resolve(cfg)
    sentinel = health_mod.HealthSentinel(
        cfg, log_dir=log_dir if runtime.is_global_zero else None, world_size=world_size
    )
    envs = resilience.make_supervised_env(
        [
            make_env(
                cfg,
                cfg.seed + rank * cfg.env.num_envs + i,
                rank * cfg.env.num_envs,
                log_dir if runtime.is_global_zero else None,
                "train",
                vector_env_idx=i,
            )
            for i in range(cfg.env.num_envs)
        ],
        sync=cfg.env.sync_env,
        ft=ft,
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if (
        len(set(cfg.algo.cnn_keys.encoder).intersection(set(cfg.algo.cnn_keys.decoder))) == 0
        and len(set(cfg.algo.mlp_keys.encoder).intersection(set(cfg.algo.mlp_keys.decoder))) == 0
    ):
        raise RuntimeError("The CNN keys or the MLP keys of the encoder and decoder must not be disjointed")
    if len(set(cfg.algo.cnn_keys.decoder) - set(cfg.algo.cnn_keys.encoder)) > 0:
        raise RuntimeError(
            "The CNN keys of the decoder must be contained in the encoder ones. "
            f"Those keys are decoded without being encoded: {list(set(cfg.algo.cnn_keys.decoder))}"
        )
    if len(set(cfg.algo.mlp_keys.decoder) - set(cfg.algo.mlp_keys.encoder)) > 0:
        raise RuntimeError(
            "The MLP keys of the decoder must be contained in the encoder ones. "
            f"Those keys are decoded without being encoded: {list(set(cfg.algo.mlp_keys.decoder))}"
        )
    if cfg.metric.log_level > 0:
        runtime.print("Encoder CNN keys:", cfg.algo.cnn_keys.encoder)
        runtime.print("Encoder MLP keys:", cfg.algo.mlp_keys.encoder)
        runtime.print("Decoder CNN keys:", cfg.algo.cnn_keys.decoder)
        runtime.print("Decoder MLP keys:", cfg.algo.mlp_keys.decoder)
    obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)

    modules, params, player = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["world_model"] if state else None,
        state["actor"] if state else None,
        state["critic"] if state else None,
        state["target_critic"] if state else None,
    )

    psync = DreamerPlayerSync(
        runtime, params, wm_keys=PLAYER_WM_KEYS, every=cfg.algo.get("player_sync_every", 1)
    )
    init_opt, train_fn = make_train_fn(modules, cfg, runtime, is_continuous, actions_dim, psync)
    opt_states = init_opt(params)
    if state:
        opt_states = jax.tree_util.tree_map(jnp.asarray, state["opt_states"])
    counter = jnp.int32(state["counter"]) if state and "counter" in state else jnp.int32(0)
    params = runtime.place_params(params)
    opt_states = runtime.place_params(opt_states)
    # the player must never hold mesh-resident params when it lives on the host
    # CPU backend: its per-step calls would pay per-leaf cross-backend pulls
    psync.push(player, params, force=True)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    buffer_type = str(cfg.buffer.type).lower()
    if buffer_type == "sequential":
        # host or HBM-resident storage + the matching sampling pipeline
        rb, prefetcher = make_sequential_replay(cfg, runtime, log_dir, obs_keys)
    elif buffer_type == "episode":
        rb, prefetcher = make_episode_replay(cfg, runtime, log_dir, obs_keys)
    else:
        raise ValueError(
            f"Unrecognized buffer type: must be one of `sequential` or `episode`, received: {buffer_type}"
        )
    if state and cfg.buffer.checkpoint and "rb" in state:
        rb.load_state_dict(state["rb"])

    train_step = 0
    last_train = 0
    train_calls = 0
    last_train_calls = 0
    start_iter = (state["iter_num"] // world_size) + 1 if state else 1
    policy_step = state["iter_num"] * cfg.env.num_envs if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(cfg.env.num_envs * world_size)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state:
        ratio.load_state_dict(state["ratio"])

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    profiler = TraceProfiler(cfg.metric.get("profiler"), log_dir if runtime.is_global_zero else None)
    rng = jax.random.PRNGKey(cfg.seed)
    step_data: Dict[str, np.ndarray] = {}
    obs = envs.reset(seed=cfg.seed)[0]
    for k in obs_keys:
        step_data[k] = np.asarray(obs[k])[np.newaxis]
    step_data["terminated"] = np.zeros((1, cfg.env.num_envs, 1))
    step_data["truncated"] = np.zeros((1, cfg.env.num_envs, 1))
    if cfg.dry_run:
        step_data["truncated"] = step_data["truncated"] + 1
        step_data["terminated"] = step_data["terminated"] + 1
    step_data["actions"] = np.zeros((1, cfg.env.num_envs, int(np.sum(actions_dim))))
    step_data["rewards"] = np.zeros((1, cfg.env.num_envs, 1))
    step_data["is_first"] = np.ones_like(step_data["terminated"])
    with prefetcher.guard():  # no torn rows under the worker's sample
        rb.add(step_data, validate_args=cfg.buffer.validate_args)
    player.init_states()

    # software pipeline (core/pipeline.py): the env workers step while the chip
    # runs the training phase below — the prefetcher samples one train call
    # behind, so training never depended on the in-flight row anyway
    stepper = AsyncEnvStepper(envs, enabled=pipeline_enabled(cfg))
    codec = PackedObsCodec(
        cnn_keys=cfg.algo.cnn_keys.encoder,
        device=runtime.player_device,
        leading_dims=(1, cfg.env.num_envs),
    )

    base_expl_amount = float(cfg.algo.actor.get("expl_amount", 0.0))
    expl_decay = float(cfg.algo.actor.get("expl_decay", 0.0))
    expl_min = float(cfg.algo.actor.get("expl_min", 0.0))

    # AOT-compile the train program off the hot path (same recipe as dv3): the
    # Ratio clone predicts the per-iteration gradient-step counts G, and each
    # [G, L, B, *feat] signature compiles in a background thread during prefill.
    # Besides hiding the compile, this is what lands the dv2.train cost-analysis
    # ledger row and `last_step_flops` for the Time/mfu metric below.
    warmup = jax_compile.AOTWarmup(enabled=jax_compile.aot_enabled(cfg))
    if warmup.enabled:
        clone = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
        clone.load_state_dict(ratio.state_dict())
        unique_g = []
        sim_policy_step = policy_step
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for sim_iter in range(start_iter, min(total_iters, start_iter + 1024) + 1):
                sim_policy_step += policy_steps_per_iter
                if sim_iter >= learning_starts:
                    g = clone((sim_policy_step - prefill_steps * policy_steps_per_iter) / world_size)
                    if g > 0 and g not in unique_g:
                        unique_g.append(g)
                        if len(unique_g) >= 4:
                            break
        seq_len = int(cfg.algo.per_rank_sequence_length)
        bsz = int(cfg.algo.per_rank_batch_size) * world_size
        batch_sharding = NamedSharding(runtime.mesh, P(None, None, "data"))
        feat = {k: tuple(step_data[k].shape[2:]) for k in obs_keys}
        store_dtype = {k: step_data[k].dtype for k in obs_keys}
        for k in ("rewards", "truncated", "terminated", "is_first"):
            feat[k] = (1,)
            store_dtype[k] = step_data[k].dtype
        feat["actions"] = (int(np.sum(actions_dim)),)
        store_dtype["actions"] = np.dtype(np.float32)
        for g in unique_g:
            batches_spec = {
                k: jax.ShapeDtypeStruct(
                    (g, seq_len, bsz, *feat[k]),
                    NUMPY_TO_JAX_DTYPE.get(np.dtype(store_dtype[k]), jnp.float32),
                    sharding=batch_sharding,
                )
                for k in feat
            }
            warmup.add(
                train_fn,
                jax_compile.specs_of(params),
                jax_compile.specs_of(opt_states),
                jax_compile.spec_like(counter),
                batches_spec,
                jax_compile.spec_like(rng),
            )
        warmup.start()

    cumulative_per_rank_gradient_steps = 0
    trained_once = False
    for iter_num in range(start_iter, total_iters + 1):
        profiler.step(policy_step)
        policy_step += policy_steps_per_iter

        with timer("Time/env_interaction_time", SumMetric()):
            if iter_num <= learning_starts and state is None and "minedojo" not in cfg.env.wrapper._target_.lower():
                real_actions = actions = np.array(envs.action_space.sample())
                if not is_continuous:
                    actions = np.concatenate(
                        [
                            np.eye(act_dim, dtype=np.float32)[act.reshape(-1)]
                            for act, act_dim in zip(actions.reshape(len(actions_dim), -1), actions_dim)
                        ],
                        axis=-1,
                    )
            else:
                # ONE packed H2D put per step; unpack, normalization, and action-mask
                # extraction run in-graph (PlayerDV2.get_actions_packed)
                packed = codec.encode(obs)
                rng, act_key = jax.random.split(rng)
                player.expl_amount = expl_amount_schedule(
                    base_expl_amount, expl_decay, expl_min, policy_step
                )
                actions_list = player.get_actions_packed(codec, packed, act_key)
                actions = np.concatenate([np.asarray(a) for a in actions_list], axis=-1)
                if is_continuous:
                    real_actions = actions
                else:
                    real_actions = np.stack([np.asarray(a).argmax(axis=-1) for a in actions_list], axis=-1)

            step_data["is_first"] = np.logical_or(step_data["terminated"], step_data["truncated"]).astype(
                np.float32
            )
            stepper.step_async(real_actions.reshape(envs.action_space.shape))

        env_step_done = False

        def _finish_env_step():
            nonlocal env_step_done, obs
            if env_step_done:
                return
            env_step_done = True
            with timer("Time/env_interaction_time", SumMetric()):
                next_obs, rewards, terminated, truncated, infos = stepper.step_wait()
            dones = np.logical_or(terminated, truncated).astype(np.uint8)
            if cfg.dry_run and buffer_type == "episode":
                dones = np.ones_like(dones)

            if cfg.metric.log_level > 0:
                for i, (ep_rew, ep_len) in enumerate(finished_episodes(infos)):
                    if aggregator:
                        if "Rewards/rew_avg" in aggregator:
                            aggregator.update("Rewards/rew_avg", ep_rew)
                        if "Game/ep_len_avg" in aggregator:
                            aggregator.update("Game/ep_len_avg", ep_len)
                    runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")

            # Save the real next observation (terminal obs for autoreset envs)
            real_next_obs = {k: np.asarray(v).copy() for k, v in next_obs.items() if k in obs_keys}
            finals = final_observations(infos, obs_keys)
            if finals:
                for idx, final_obs in finals.items():
                    for k, v in final_obs.items():
                        real_next_obs[k][idx] = v

            for k in obs_keys:
                step_data[k] = real_next_obs[k][np.newaxis]
            obs = next_obs

            step_data["terminated"] = np.asarray(terminated, dtype=np.float32).reshape((1, cfg.env.num_envs, -1))
            step_data["truncated"] = np.asarray(truncated, dtype=np.float32).reshape((1, cfg.env.num_envs, -1))
            if cfg.dry_run and buffer_type == "episode":
                step_data["terminated"] = np.ones_like(step_data["terminated"])
            step_data["actions"] = actions.reshape((1, cfg.env.num_envs, -1))
            step_data["rewards"] = clip_rewards_fn(
                np.asarray(rewards, dtype=np.float32).reshape((1, cfg.env.num_envs, -1))
            )
            with prefetcher.guard():  # no torn rows under the worker's sample
                rb.add(step_data, validate_args=cfg.buffer.validate_args)

            dones_idxes = dones.nonzero()[0].tolist()
            reset_envs = len(dones_idxes)
            if reset_envs > 0:
                reset_data = {}
                for k in obs_keys:
                    reset_data[k] = (np.asarray(next_obs[k])[dones_idxes])[np.newaxis]
                reset_data["terminated"] = np.zeros((1, reset_envs, 1))
                reset_data["truncated"] = np.zeros((1, reset_envs, 1))
                reset_data["actions"] = np.zeros((1, reset_envs, int(np.sum(actions_dim))))
                reset_data["rewards"] = np.zeros((1, reset_envs, 1))
                reset_data["is_first"] = np.ones_like(reset_data["terminated"])
                with prefetcher.guard():  # no torn rows under the worker's sample
                    rb.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)
                for d in dones_idxes:
                    step_data["terminated"][0, d] = np.zeros_like(step_data["terminated"][0, d])
                    step_data["truncated"][0, d] = np.zeros_like(step_data["truncated"][0, d])
                player.init_states(dones_idxes)

        # ---- training phase (overlap window: env workers step while the chip trains)
        if iter_num >= learning_starts:
            ratio_steps = policy_step - prefill_steps * policy_steps_per_iter
            per_rank_gradient_steps = ratio(ratio_steps / world_size)
            if per_rank_gradient_steps > 0 and sentinel.ratio_scale < 1.0:
                # health-sentinel backoff: shrink this round's gradient grant
                per_rank_gradient_steps = max(1, int(per_rank_gradient_steps * sentinel.ratio_scale))
            if per_rank_gradient_steps > 0:
                if not trained_once:
                    # first sample: complete the env step serially so the buffer
                    # holds the full prefill before the sequence sampler runs
                    _finish_env_step()
                    trained_once = True
                # consumes the batch prefetched during the previous train step and
                # immediately speculates the next one
                batches = prefetcher.get(
                    batch_size=cfg.algo.per_rank_batch_size * world_size,
                    sequence_length=cfg.algo.per_rank_sequence_length,
                    n_samples=per_rank_gradient_steps,
                )
                with timer("Time/train_time", SumMetric()):
                    # no-op once the warmup thread finished (first train call at
                    # the latest; usually hidden behind prefill)
                    warmup.wait()
                    rng, train_key = jax.random.split(rng)
                    params, opt_states, counter, flat_player, train_metrics = train_fn(
                        params, opt_states, counter, batches, train_key
                    )
                    if not timer.disabled:
                        # fence ONLY when timing (Time/train_time honesty); an
                        # unconditional sync serializes on the dispatch round-trip
                        jax.block_until_ready(params)
                    psync.push(player, params, flat=flat_player)
                    cumulative_per_rank_gradient_steps += per_rank_gradient_steps
                    train_step += world_size * per_rank_gradient_steps
                    train_calls += 1
                if aggregator:
                    aggregator.update_from_device(train_metrics)

        _finish_env_step()

        # ---- logging
        if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters):
            overlap_s, overlap_steps = stepper.drain_overlap()
            if overlap_s > 0:
                sps_overlap = overlap_steps * cfg.env.num_envs * cfg.env.action_repeat / overlap_s
                if aggregator and "Time/sps_pipeline_overlap" in aggregator:
                    aggregator.update("Time/sps_pipeline_overlap", sps_overlap)
                elif logger:
                    logger.log_metrics({"Time/sps_pipeline_overlap": sps_overlap}, policy_step)
            if aggregator and not aggregator.disabled:
                logger.log_metrics(aggregator.compute(), policy_step)
                aggregator.reset()
            if logger and policy_step > 0:
                logger.log_metrics(
                    {"Params/replay_ratio": cumulative_per_rank_gradient_steps * world_size / policy_step},
                    policy_step,
                )
            if not timer.disabled:
                timer_metrics = timer.compute()
                if logger and timer_metrics.get("Time/train_time", 0) > 0:
                    logger.log_metrics(
                        {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                        policy_step,
                    )
                    # model FLOPs utilization from the AOT cost analysis of the
                    # G-step train program (same contract as ppo/a2c/sac/dv3)
                    _mfu = tel_device.mfu(
                        getattr(train_fn, "last_step_flops", None),
                        timer_metrics["Time/train_time"] / max(train_calls - last_train_calls, 1),
                        runtime.device,
                    )
                    if _mfu is not None:
                        logger.log_metrics({"Time/mfu": _mfu}, policy_step)
                if logger and timer_metrics.get("Time/env_interaction_time", 0) > 0:
                    logger.log_metrics(
                        {
                            "Time/sps_env_interaction": (
                                (policy_step - last_log) / world_size * cfg.env.action_repeat
                            )
                            / timer_metrics["Time/env_interaction_time"]
                        },
                        policy_step,
                    )
                timer.reset()
            last_log = policy_step
            last_train = train_step
            last_train_calls = train_calls

        # ---- checkpoint
        env_deltas = resilience.drain_env_counters(envs, aggregator)
        jax_compile.drain_compile_counters(aggregator)
        if cumulative_per_rank_gradient_steps > 0 and not jax_compile.is_steady():
            # everything reachable has compiled once: later traces are drift
            jax_compile.mark_steady()

        # ----- health sentinel: warn -> backoff (ratio grant above) -> rollback
        action = sentinel.observe(
            policy_step,
            train_metrics=train_metrics if "train_metrics" in dir() else None,
            env_counters=env_deltas,
        )
        if action.rollback:
            rb_state = sentinel.take_rollback_state(os.path.join(log_dir, "checkpoint"))
            if rb_state is not None:
                params = runtime.place_params(
                    {
                        **params,
                        "world_model": jax.tree_util.tree_map(jnp.asarray, rb_state["world_model"]),
                        "actor": jax.tree_util.tree_map(jnp.asarray, rb_state["actor"]),
                        "critic": jax.tree_util.tree_map(jnp.asarray, rb_state["critic"]),
                        "target_critic": jax.tree_util.tree_map(jnp.asarray, rb_state["target_critic"]),
                    }
                )
                opt_states = runtime.place_params(
                    jax.tree_util.tree_map(jnp.asarray, rb_state["opt_states"])
                )
                counter = jnp.int32(rb_state["counter"])
                ratio.load_state_dict(rb_state["ratio"])
                # replay rows stay valid off-policy data; only the learner
                # (and the player's copy of it) rewinds to the snapshot
                psync.push(player, params, force=True)
                runtime.print(
                    f"Health rollback at policy_step={policy_step}: restored certified "
                    "checkpoint, training continues."
                )
        sentinel.drain(aggregator)

        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            iter_num == total_iters and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "world_model": jax.device_get(params["world_model"]),
                "actor": jax.device_get(params["actor"]),
                "critic": jax.device_get(params["critic"]),
                "target_critic": jax.device_get(params["target_critic"]),
                "opt_states": jax.device_get(opt_states),
                "counter": int(counter),
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{rank}.ckpt")
            runtime.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
                io_lock=prefetcher.guard(),
                healthy=sentinel.certifiable,
                policy_step=policy_step,
            )

    profiler.close()
    prefetcher.close()
    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        psync.push(player, params, force=True)  # the cadence may have left the player stale
        test(player, runtime, cfg, log_dir)
    if logger:
        logger.finalize()
