"""DreamerV2 agent: encoders/decoders, RSSM, actor, player (flax + lax.scan).

Parity targets (reference sheeprl/algos/dreamer_v2/agent.py): CNNEncoder (:39),
MLPEncoder (:93), CNNDecoder (:143), MLPDecoder (:218), RecurrentModel (:274),
RSSM (:331), Actor (:455), MinedojoActor (:626), WorldModel (:776), PlayerDV2 (:804),
build_agent (:916), xavier init (dreamer_v2/utils.py:init_weights).

TPU-first design decisions (shared with the DV3 port):
- The RSSM is composed of small flax modules driven by pure scan functions; the
  T-step dynamic unroll compiles to ONE `lax.scan` (the reference loops in Python,
  dreamer_v2.py:144-157).
- Params are plain dict pytrees so world model / actor / critic are optax leaves.
- The player's policy step is one jitted pure function over explicit
  (recurrent, stochastic, action) state.

Differences from DV3 kept for parity with DV2's semantics: ELU activations, no
unimix, zero (non-learnable) initial states, gaussian observation/reward heads
(Normal(mean, 1)), KL balancing with a single alpha, truncated-normal continuous
actor, and epsilon-greedy/gaussian exploration noise on top of the policy.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.models.models import MLP, CNN, DeCNN, LayerNormGRUCell
from sheeprl_tpu.utils.utils import host_float32, resolve_actor_cls
from sheeprl_tpu.ops.distributions import (
    Independent,
    Normal,
    OneHotCategorical,
    OneHotCategoricalStraightThrough,
    TanhNormal,
    TruncatedNormal,
)

# Reference init_weights (dreamer_v2/utils.py:64-81): xavier-normal on every
# conv/linear weight, zero biases.
xavier_normal_init = nn.initializers.glorot_normal()


def compute_stochastic_state(
    logits: jax.Array, discrete: int, key: Optional[jax.Array] = None, sample: bool = True
) -> jax.Array:
    """Straight-through sample (or mode) of the categorical stochastic state.

    Reference: sheeprl/algos/dreamer_v2/utils.py:44-61. Input ``[..., stoch*discrete]``,
    output ``[..., stoch, discrete]``.
    """
    logits = logits.reshape(*logits.shape[:-1], -1, discrete)
    dist = OneHotCategoricalStraightThrough(logits=logits)
    if sample:
        return dist.rsample(key)
    return dist.mode


class CNNEncoderDV2(nn.Module):
    """4-stage stride-2 kernel-4 VALID-padding image encoder (reference agent.py:39-91).

    64x64 -> 31 -> 14 -> 6 -> 2 spatial; output flattened.
    """

    keys: Sequence[str]
    input_channels: Sequence[int]
    image_size: Tuple[int, int]
    channels_multiplier: int
    layer_norm: bool = False
    activation: str = "elu"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @property
    def spatial_dims(self) -> Tuple[int, int]:
        h, w = self.image_size
        for _ in range(4):
            h = (h - 4) // 2 + 1
            w = (w - 4) // 2 + 1
        return h, w

    @property
    def output_dim(self) -> int:
        h, w = self.spatial_dims
        return 8 * self.channels_multiplier * h * w

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-3)
        batch_shape = x.shape[:-3]
        x = x.reshape(-1, *x.shape[-3:])
        x = CNN(
            input_channels=sum(self.input_channels),
            hidden_channels=[m * self.channels_multiplier for m in (1, 2, 4, 8)],
            layer_args={"kernel_size": 4, "stride": 2, "padding": 0, "bias": not self.layer_norm},
            activation=self.activation,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=xavier_normal_init,
        )(x)
        x = x.reshape(x.shape[0], -1)
        return x.reshape(*batch_shape, x.shape[-1])


class MLPEncoderDV2(nn.Module):
    """Vector encoder, raw inputs (no symlog; reference agent.py:93-141)."""

    keys: Sequence[str]
    input_dims: Sequence[int]
    mlp_layers: int = 4
    dense_units: int = 400
    layer_norm: bool = False
    activation: str = "elu"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @property
    def output_dim(self) -> int:
        return self.dense_units

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        return MLP(
            input_dims=sum(self.input_dims),
            output_dim=None,
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            use_bias=not self.layer_norm,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=xavier_normal_init,
        )(x)


class MultiEncoderDV2(nn.Module):
    cnn_encoder: Optional[CNNEncoderDV2]
    mlp_encoder: Optional[MLPEncoderDV2]

    @property
    def output_dim(self) -> int:
        out = 0
        if self.cnn_encoder is not None:
            out += self.cnn_encoder.output_dim
        if self.mlp_encoder is not None:
            out += self.mlp_encoder.output_dim
        return out

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        outs = []
        if self.cnn_encoder is not None:
            outs.append(self.cnn_encoder(obs))
        if self.mlp_encoder is not None:
            outs.append(self.mlp_encoder(obs))
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=-1)


class CNNDecoderDV2(nn.Module):
    """Latent -> Linear -> (C,1,1) -> 4 transposed convs (k 5,5,6,6, stride 2) ->
    image dict (reference agent.py:143-216)."""

    keys: Sequence[str]
    output_channels: Sequence[int]
    channels_multiplier: int
    cnn_encoder_output_dim: int
    image_size: Tuple[int, int]
    layer_norm: bool = False
    activation: str = "elu"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent_states: jax.Array) -> Dict[str, jax.Array]:
        batch_shape = latent_states.shape[:-1]
        x = latent_states.reshape(-1, latent_states.shape[-1])
        x = nn.Dense(
            self.cnn_encoder_output_dim,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=xavier_normal_init,
        )(x)
        out_ch = sum(self.output_channels)
        x = x.reshape(-1, self.cnn_encoder_output_dim, 1, 1)
        x = DeCNN(
            input_channels=self.cnn_encoder_output_dim,
            hidden_channels=[m * self.channels_multiplier for m in (4, 2, 1)] + [out_ch],
            layer_args=[
                {"kernel_size": 5, "stride": 2},
                {"kernel_size": 5, "stride": 2},
                {"kernel_size": 6, "stride": 2},
                {"kernel_size": 6, "stride": 2},
            ],
            activation=[self.activation] * 3 + [None],
            layer_norm=[self.layer_norm] * 3 + [False],
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=xavier_normal_init,
        )(x)
        x = x.reshape(*batch_shape, out_ch, *self.image_size)
        out: Dict[str, jax.Array] = {}
        start = 0
        for k, ch in zip(self.keys, self.output_channels):
            out[k] = x[..., start : start + ch, :, :]
            start += ch
        return out


class MLPDecoderDV2(nn.Module):
    """Latent -> MLP -> per-key linear heads (reference agent.py:218-272)."""

    keys: Sequence[str]
    output_dims: Sequence[int]
    mlp_layers: int = 4
    dense_units: int = 400
    layer_norm: bool = False
    activation: str = "elu"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent_states: jax.Array) -> Dict[str, jax.Array]:
        x = MLP(
            input_dims=latent_states.shape[-1],
            output_dim=None,
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            use_bias=not self.layer_norm,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=xavier_normal_init,
        )(latent_states)
        return {
            k: nn.Dense(
                dim,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=xavier_normal_init,
                name=f"head_{k}",
            )(x)
            for k, dim in zip(self.keys, self.output_dims)
        }


class MultiDecoderDV2(nn.Module):
    cnn_decoder: Optional[CNNDecoderDV2]
    mlp_decoder: Optional[MLPDecoderDV2]

    @nn.compact
    def __call__(self, latent_states: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_decoder is not None:
            out.update(self.cnn_decoder(latent_states))
        if self.mlp_decoder is not None:
            out.update(self.mlp_decoder(latent_states))
        return out


class RecurrentModelDV2(nn.Module):
    """MLP projection + LayerNorm GRU with bias (reference agent.py:274-329).

    The GRU always layer-norms its fused projection (the reference hard-codes
    ``layer_norm_cls=nn.LayerNorm`` in the cell); ``layer_norm`` toggles only the
    input-MLP norm.
    """

    input_size: int
    recurrent_state_size: int
    dense_units: int
    layer_norm: bool = False
    activation: str = "elu"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, recurrent_state: jax.Array) -> jax.Array:
        feat = MLP(
            input_dims=self.input_size,
            output_dim=None,
            hidden_sizes=[self.dense_units],
            activation=self.activation,
            layer_norm=self.layer_norm,
            use_bias=not self.layer_norm,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=xavier_normal_init,
        )(x)
        return LayerNormGRUCell(
            hidden_size=self.recurrent_state_size,
            bias=True,
            layer_norm=True,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=xavier_normal_init,
        )(feat, recurrent_state)


class MLPWithHeadDV2(nn.Module):
    """MLP trunk + linear head (representation/transition/reward/continue/critic)."""

    input_dim: int
    hidden_sizes: Sequence[int]
    output_dim: int
    activation: str = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if len(self.hidden_sizes) > 0:
            x = MLP(
                input_dims=self.input_dim,
                output_dim=None,
                hidden_sizes=self.hidden_sizes,
                activation=self.activation,
                layer_norm=self.layer_norm,
                use_bias=not self.layer_norm,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=xavier_normal_init,
            )(x)
        return nn.Dense(
            self.output_dim,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=xavier_normal_init,
            name="head",
        )(x)


class RSSMDV2:
    """Pure-functional DV2 RSSM (reference agent.py:331-453).

    No unimix, no learnable initial state: on ``is_first`` the carried state is
    zeroed (reference dynamic(), agent.py:398-401).
    """

    def __init__(
        self,
        recurrent_model: RecurrentModelDV2,
        representation_model: MLPWithHeadDV2,
        transition_model: MLPWithHeadDV2,
        stochastic_size: int,
        discrete_size: int = 32,
    ):
        self.recurrent_model = recurrent_model
        self.representation_model = representation_model
        self.transition_model = transition_model
        self.stochastic_size = stochastic_size
        self.discrete_size = discrete_size

    @property
    def stoch_state_size(self) -> int:
        return self.stochastic_size * self.discrete_size

    def _transition(self, wm_params, recurrent_out, key=None, sample=True):
        logits = self.transition_model.apply(wm_params["transition_model"], recurrent_out)
        return logits, compute_stochastic_state(logits, self.discrete_size, key, sample=sample)

    def _representation(self, wm_params, recurrent_state, embedded_obs, key=None, sample=True):
        logits = self.representation_model.apply(
            wm_params["representation_model"], jnp.concatenate([recurrent_state, embedded_obs], axis=-1)
        )
        return logits, compute_stochastic_state(logits, self.discrete_size, key, sample=sample)

    def _recurrent(self, wm_params, stoch_flat, action, recurrent_state):
        x = jnp.concatenate([stoch_flat, action], axis=-1)
        return self.recurrent_model.apply(wm_params["recurrent_model"], x, recurrent_state)

    def dynamic_step(self, wm_params, posterior_flat, recurrent_state, action, embedded_obs, is_first, key):
        """One step of dynamic learning (reference agent.py:363-404)."""
        k_prior, k_post = jax.random.split(key)
        action = (1 - is_first) * action
        posterior_flat = (1 - is_first) * posterior_flat
        recurrent_state = (1 - is_first) * recurrent_state
        recurrent_state = self._recurrent(wm_params, posterior_flat, action, recurrent_state)
        prior_logits, prior = self._transition(wm_params, recurrent_state, k_prior)
        posterior_logits, posterior = self._representation(wm_params, recurrent_state, embedded_obs, k_post)
        return recurrent_state, posterior, prior, posterior_logits, prior_logits

    def dynamic_scan(self, wm_params, embedded_obs, actions, is_first, key):
        """lax.scan over T (reference loops in Python, dreamer_v2.py:144-157)."""
        T, B = embedded_obs.shape[0], embedded_obs.shape[1]
        keys = jax.random.split(key, T)
        init_rec = jnp.zeros((B, self.recurrent_model.recurrent_state_size), dtype=embedded_obs.dtype)
        init_post = jnp.zeros((B, self.stoch_state_size), dtype=embedded_obs.dtype)

        def step(carry, xs):
            recurrent_state, posterior_flat = carry
            action, embedded, is_f, k = xs
            recurrent_state, posterior, _, post_logits, prior_logits = self.dynamic_step(
                wm_params, posterior_flat, recurrent_state, action, embedded, is_f, k
            )
            new_carry = (recurrent_state, posterior.reshape(*posterior.shape[:-2], -1))
            return new_carry, (recurrent_state, posterior, post_logits, prior_logits)

        _, (recurrent_states, posteriors, posteriors_logits, priors_logits) = jax.lax.scan(
            step, (init_rec, init_post), (actions, embedded_obs, is_first, keys)
        )
        return recurrent_states, posteriors, priors_logits, posteriors_logits

    def imagination_step(self, wm_params, prior_flat, recurrent_state, actions, key):
        """One-step latent imagination (reference agent.py:434-453)."""
        recurrent_state = self._recurrent(wm_params, prior_flat, actions, recurrent_state)
        _, imagined_prior = self._transition(wm_params, recurrent_state, key)
        return imagined_prior.reshape(*prior_flat.shape), recurrent_state


class ActorDV2(nn.Module):
    """DV2 actor trunk + heads (reference agent.py:455-543)."""

    latent_state_size: int
    actions_dim: Sequence[int]
    is_continuous: bool
    distribution: str = "auto"
    init_std: float = 0.0
    min_std: float = 0.1
    dense_units: int = 400
    mlp_layers: int = 4
    layer_norm: bool = False
    activation: str = "elu"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    # rollout-time masked sampling is an actor property, not a player branch
    uses_action_mask: bool = False

    def resolved_distribution(self) -> str:
        dist = self.distribution.lower()
        if dist not in ("auto", "normal", "tanh_normal", "discrete", "trunc_normal"):
            raise ValueError(
                "The distribution must be on of: `auto`, `discrete`, `normal`, `tanh_normal` and `trunc_normal`. "
                f"Found: {dist}"
            )
        if dist == "discrete" and self.is_continuous:
            raise ValueError("You have choose a discrete distribution but `is_continuous` is true")
        if dist == "auto":
            dist = "trunc_normal" if self.is_continuous else "discrete"
        return dist

    def sample(self, pre_dist: List[jax.Array], key: jax.Array, greedy: bool = False, mask=None) -> List[jax.Array]:
        """Turn raw head outputs into env actions; subclasses may consume ``mask``."""
        return ActorOutputDV2(self, pre_dist).sample_actions(key, greedy=greedy)

    def exploration_noise(
        self, actions: List[jax.Array], expl_amount: jax.Array, key: jax.Array, mask=None
    ) -> List[jax.Array]:
        return add_exploration_noise(actions, expl_amount, self.is_continuous, self.actions_dim, key)

    @nn.compact
    def __call__(self, state: jax.Array) -> List[jax.Array]:
        x = MLP(
            input_dims=self.latent_state_size,
            output_dim=None,
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            use_bias=not self.layer_norm,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=xavier_normal_init,
        )(state)
        if self.is_continuous:
            return [
                nn.Dense(
                    int(np.sum(self.actions_dim)) * 2,
                    dtype=self.dtype,
                    param_dtype=self.param_dtype,
                    kernel_init=xavier_normal_init,
                    name="head_0",
                )(x)
            ]
        return [
            nn.Dense(
                dim,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=xavier_normal_init,
                name=f"head_{i}",
            )(x)
            for i, dim in enumerate(self.actions_dim)
        ]


class ActorOutputDV2:
    """Distribution wrapper over the DV2 actor's raw outputs (reference agent.py:550-603)."""

    def __init__(self, actor: ActorDV2, pre_dist: List[jax.Array]):
        self.actor = actor
        self.dist_type = actor.resolved_distribution()
        self.pre_dist = pre_dist
        if actor.is_continuous:
            mean, std = jnp.split(pre_dist[0], 2, axis=-1)
            if self.dist_type == "tanh_normal":
                mean = 5 * jnp.tanh(mean / 5)
                std = jax.nn.softplus(std + actor.init_std) + actor.min_std
                self.dists = [Independent(TanhNormal(mean, std), 1)]
            elif self.dist_type == "normal":
                self.dists = [Independent(Normal(mean, std), 1)]
            else:  # trunc_normal
                std = 2 * jax.nn.sigmoid((std + actor.init_std) / 2) + actor.min_std
                self.dists = [Independent(TruncatedNormal(jnp.tanh(mean), std, -1.0, 1.0), 1)]
        else:
            self.dists = [OneHotCategoricalStraightThrough(logits=logits) for logits in pre_dist]

    def sample_actions(self, key: jax.Array, greedy: bool = False) -> List[jax.Array]:
        if self.actor.is_continuous:
            if greedy:
                # Reference draws 100 samples and keeps the max-log-prob one
                # (agent.py:587-590); the distribution mean is the deterministic
                # equivalent for the unimodal trunc-normal.
                return [self.dists[0].mode]
            return [self.dists[0].rsample(key)]
        keys = jax.random.split(key, len(self.dists))
        if greedy:
            return [d.mode for d in self.dists]
        return [d.rsample(k) for d, k in zip(self.dists, keys)]

    def log_prob(self, actions: List[jax.Array]) -> jax.Array:
        return sum(d.log_prob(a) for d, a in zip(self.dists, actions))

    def entropy(self) -> jax.Array:
        return sum(d.entropy() for d in self.dists)


def expl_amount_schedule(amount: float, decay: float, minimum: float, step: int) -> float:
    """Exponential half-life decay of the exploration amount.

    Reference Actor._get_expl_amount (agent.py:544-548); implemented with the
    intended half-life semantics ``amount * 0.5**(step/decay)``.
    """
    if decay:
        amount = amount * 0.5 ** (float(step) / float(decay))
    return max(amount, minimum)


def add_exploration_noise(
    actions: List[jax.Array],
    expl_amount: jax.Array,
    is_continuous: bool,
    actions_dim: Sequence[int],
    key: jax.Array,
) -> List[jax.Array]:
    """Gaussian (continuous) / epsilon-random (discrete) exploration noise.

    Reference Actor.add_exploration_noise (agent.py:605-623). ``expl_amount`` is a
    traced scalar so the decay schedule does not trigger recompiles; amount 0 is a
    no-op by construction.
    """
    if is_continuous:
        cat = jnp.concatenate(actions, axis=-1)
        noisy = jnp.clip(cat + expl_amount * jax.random.normal(key, cat.shape), -1, 1)
        # only clip when noise is actually added (reference guards with expl_amount > 0)
        return [jnp.where(expl_amount > 0.0, noisy, cat)]
    out = []
    for i, act in enumerate(actions):
        k_sample, k_mask, key = jax.random.split(key, 3)
        random_act = OneHotCategorical(logits=jnp.zeros_like(act)).sample(k_sample)
        mask = jax.random.uniform(k_mask, act.shape[:1]) < expl_amount
        out.append(jnp.where(mask[..., None], random_act, act))
    return out


def add_exploration_noise_minedojo(
    actions: List[jax.Array], expl_amount: jax.Array, key: jax.Array, mask: Dict[str, jax.Array]
) -> List[jax.Array]:
    """Mask-respecting epsilon-random exploration for the three MineDojo heads.

    Reference MinedojoActor.add_exploration_noise (dreamer_v2/agent.py:720-776):
    exploratory actions are drawn uniformly over the VALID actions, and when
    exploration flips head 0 onto a functional macro (15-18), heads 1-2 are
    forcibly resampled so the triple satisfies the env constraints. (The
    reference samples its replacement from unmasked uniform logits despite
    building the masked logits first — here the masked logits are actually
    used, which is the documented intent.)
    """
    from sheeprl_tpu.algos.dreamer_v3.agent import minedojo_mask_logits

    expl: List[jax.Array] = []
    functional_action = actions[0].argmax(axis=-1)
    for i, act in enumerate(actions):
        k_sample, k_replace, key = jax.random.split(key, 3)
        logits = minedojo_mask_logits(jnp.zeros_like(act), i, mask, functional_action)
        random_act = OneHotCategorical(logits=logits).sample(k_sample)
        replace = jax.random.uniform(k_replace, act.shape[:-1]) < expl_amount
        if i > 0:
            # head 0 was flipped onto a functional macro -> heads 1/2 must follow
            forced = (actions[0].argmax(axis=-1) != functional_action) & (
                (functional_action >= 15) & (functional_action <= 18)
            )
            replace = replace | forced
        expl.append(jnp.where(replace[..., None], random_act, act))
        if i == 0:
            functional_action = expl[0].argmax(axis=-1)
    return expl


class MinedojoActorDV2(ActorDV2):
    """DV2 actor for MineDojo (reference dreamer_v2/agent.py:626-776): same
    parameters as `ActorDV2`, with mask-aware rollout sampling and exploration
    noise. Selected via ``cfg.algo.actor.cls``."""

    uses_action_mask: bool = True

    def sample(self, pre_dist: List[jax.Array], key: jax.Array, greedy: bool = False, mask=None) -> List[jax.Array]:
        if mask is None:
            return super().sample(pre_dist, key, greedy=greedy)
        from sheeprl_tpu.algos.dreamer_v3.agent import sample_minedojo_actions

        return sample_minedojo_actions(self, pre_dist, mask, key, greedy=greedy)

    def exploration_noise(
        self, actions: List[jax.Array], expl_amount: jax.Array, key: jax.Array, mask=None
    ) -> List[jax.Array]:
        if mask is None:
            return super().exploration_noise(actions, expl_amount, key)
        return add_exploration_noise_minedojo(actions, expl_amount, key, mask)


class PlayerDV2:
    """Stateful host-side rollout policy over a single jitted step (reference agent.py:804-914)."""

    def __init__(
        self,
        encoder: MultiEncoderDV2,
        rssm: RSSMDV2,
        actor: ActorDV2,
        actions_dim: Sequence[int],
        num_envs: int,
        stochastic_size: int,
        recurrent_state_size: int,
        discrete_size: int = 32,
        actor_type: Optional[str] = None,
    ):
        self.encoder = encoder
        self.rssm = rssm
        self.actor = actor
        self.actions_dim = tuple(actions_dim)
        self.num_envs = num_envs
        self.stochastic_size = stochastic_size
        self.recurrent_state_size = recurrent_state_size
        self.discrete_size = discrete_size
        self.actor_type = actor_type
        self.expl_amount = 0.0
        self.wm_params: Any = None
        self.actor_params: Any = None
        self._step = jax_compile.guarded_jit(self._raw_step, name="dv2.step", static_argnames=("greedy",))
        self._packed_step_fns: Dict[Any, Any] = {}

    def _raw_step(self, wm_params, actor_params, state, obs, key, expl_amount, greedy: bool = False, mask=None):
        recurrent_state, stochastic_state, actions = state
        k_rep, k_act, k_expl = jax.random.split(key, 3)
        embedded = self.encoder.apply(wm_params["encoder"], obs)
        recurrent_state = self.rssm._recurrent(wm_params, stochastic_state, actions, recurrent_state)
        _, stoch = self.rssm._representation(wm_params, recurrent_state, embedded, k_rep)
        stochastic_state = stoch.reshape(*stoch.shape[:-2], self.stochastic_size * self.discrete_size)
        latent = jnp.concatenate([stochastic_state, recurrent_state], axis=-1)
        pre_dist = self.actor.apply(actor_params, latent)
        actions_list = self.actor.sample(pre_dist, k_act, greedy=greedy, mask=mask)
        if not greedy:  # exploration noise is a training-only behavior (reference get_actions adds none)
            actions_list = self.actor.exploration_noise(actions_list, expl_amount, k_expl, mask=mask)
        actions_list = host_float32(actions_list)
        actions = jnp.concatenate(actions_list, axis=-1)
        return tuple(actions_list), (recurrent_state, stochastic_state, actions)

    def init_states(self, reset_envs: Optional[Sequence[int]] = None) -> None:
        if reset_envs is None or len(reset_envs) == 0:
            self.state = (
                jnp.zeros((1, self.num_envs, self.recurrent_state_size), dtype=jnp.float32),
                jnp.zeros((1, self.num_envs, self.stochastic_size * self.discrete_size), dtype=jnp.float32),
                jnp.zeros((1, self.num_envs, int(np.sum(self.actions_dim))), dtype=jnp.float32),
            )
        else:
            recurrent_state, stochastic_state, actions = self.state
            reset = np.zeros((self.num_envs,), dtype=bool)
            reset[np.asarray(reset_envs)] = True
            mask = jnp.asarray(reset)[None, :, None]
            self.state = (
                jnp.where(mask, 0.0, recurrent_state),
                jnp.where(mask, 0.0, stochastic_state),
                jnp.where(mask, 0.0, actions),
            )

    def get_actions(self, obs: Dict[str, jax.Array], key: jax.Array, greedy: bool = False, mask=None):
        if not getattr(self.actor, "uses_action_mask", False):
            mask = None  # avoids re-tracing _step on mask presence for mask-free actors
        actions_list, self.state = self._step(
            self.wm_params,
            self.actor_params,
            self.state,
            obs,
            key,
            jnp.float32(self.expl_amount),
            greedy=greedy,
            mask=mask,
        )
        return actions_list

    def get_actions_packed(self, codec, packed: jax.Array, key: jax.Array, greedy: bool = False):
        """Act from a packed obs buffer: unpack, normalize, and extract action masks in-graph."""
        use_mask = bool(getattr(self.actor, "uses_action_mask", False))
        cache_key = (codec.signature, bool(greedy), use_mask)
        fn = self._packed_step_fns.get(cache_key)
        if fn is None:

            def _packed(wm_params, actor_params, state, packed, key, expl_amount):
                obs = codec.decode_obs(packed)
                mask = None
                if use_mask:
                    mask = {k: v for k, v in obs.items() if k.startswith("mask")} or None
                return self._raw_step(
                    wm_params, actor_params, state, obs, key, expl_amount, greedy=greedy, mask=mask
                )

            fn = jax_compile.guarded_jit(_packed, name="dv2.step_packed")
            self._packed_step_fns[cache_key] = fn
        actions_list, self.state = fn(
            self.wm_params, self.actor_params, self.state, packed, key, jnp.float32(self.expl_amount)
        )
        return actions_list


class DV2Modules(NamedTuple):
    """Static module definitions shared by the train step and the player."""

    encoder: MultiEncoderDV2
    rssm: RSSMDV2
    observation_model: MultiDecoderDV2
    reward_model: MLPWithHeadDV2
    continue_model: Optional[MLPWithHeadDV2]
    actor: ActorDV2
    critic: MLPWithHeadDV2


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Dict[str, Any]] = None,
    actor_state: Optional[Dict[str, Any]] = None,
    critic_state: Optional[Dict[str, Any]] = None,
    target_critic_state: Optional[Dict[str, Any]] = None,
) -> Tuple[DV2Modules, Dict[str, Any], PlayerDV2]:
    """Build module defs + init params (reference agent.py:916-1163).

    Returns (modules, params, player); params has keys ``world_model``, ``actor``,
    ``critic``, ``target_critic``.
    """
    world_model_cfg = cfg.algo.world_model
    actor_cfg = cfg.algo.actor
    critic_cfg = cfg.algo.critic

    recurrent_state_size = int(world_model_cfg.recurrent_model.recurrent_state_size)
    stochastic_size = int(world_model_cfg.stochastic_size) * int(world_model_cfg.discrete_size)
    latent_state_size = stochastic_size + recurrent_state_size
    compute_dtype = runtime.compute_dtype
    param_dtype = jnp.float32

    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_encoder = (
        CNNEncoderDV2(
            keys=cnn_keys,
            input_channels=[int(np.prod(obs_space[k].shape[:-2])) for k in cnn_keys],
            image_size=tuple(obs_space[cnn_keys[0]].shape[-2:]),
            channels_multiplier=int(world_model_cfg.encoder.cnn_channels_multiplier),
            layer_norm=bool(world_model_cfg.encoder.layer_norm),
            activation=world_model_cfg.encoder.cnn_act,
            dtype=compute_dtype,
            param_dtype=param_dtype,
        )
        if len(cnn_keys) > 0
        else None
    )
    mlp_encoder = (
        MLPEncoderDV2(
            keys=mlp_keys,
            input_dims=[int(obs_space[k].shape[0]) for k in mlp_keys],
            mlp_layers=int(world_model_cfg.encoder.mlp_layers),
            dense_units=int(world_model_cfg.encoder.dense_units),
            layer_norm=bool(world_model_cfg.encoder.layer_norm),
            activation=world_model_cfg.encoder.dense_act,
            dtype=compute_dtype,
            param_dtype=param_dtype,
        )
        if len(mlp_keys) > 0
        else None
    )
    encoder = MultiEncoderDV2(cnn_encoder, mlp_encoder)

    recurrent_model = RecurrentModelDV2(
        input_size=int(sum(actions_dim) + stochastic_size),
        recurrent_state_size=recurrent_state_size,
        dense_units=int(world_model_cfg.recurrent_model.dense_units),
        layer_norm=bool(world_model_cfg.recurrent_model.layer_norm),
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )
    repr_input = recurrent_state_size + encoder.output_dim
    representation_model = MLPWithHeadDV2(
        input_dim=repr_input,
        hidden_sizes=[int(world_model_cfg.representation_model.hidden_size)],
        output_dim=stochastic_size,
        activation=world_model_cfg.representation_model.dense_act,
        layer_norm=bool(world_model_cfg.representation_model.layer_norm),
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )
    transition_model = MLPWithHeadDV2(
        input_dim=recurrent_state_size,
        hidden_sizes=[int(world_model_cfg.transition_model.hidden_size)],
        output_dim=stochastic_size,
        activation=world_model_cfg.transition_model.dense_act,
        layer_norm=bool(world_model_cfg.transition_model.layer_norm),
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )
    rssm = RSSMDV2(
        recurrent_model=recurrent_model,
        representation_model=representation_model,
        transition_model=transition_model,
        stochastic_size=int(world_model_cfg.stochastic_size),
        discrete_size=int(world_model_cfg.discrete_size),
    )

    cnn_keys_dec = list(cfg.algo.cnn_keys.decoder)
    mlp_keys_dec = list(cfg.algo.mlp_keys.decoder)
    cnn_decoder = (
        CNNDecoderDV2(
            keys=cnn_keys_dec,
            output_channels=[int(np.prod(obs_space[k].shape[:-2])) for k in cnn_keys_dec],
            channels_multiplier=int(world_model_cfg.observation_model.cnn_channels_multiplier),
            cnn_encoder_output_dim=cnn_encoder.output_dim,
            image_size=tuple(obs_space[cnn_keys_dec[0]].shape[-2:]),
            layer_norm=bool(world_model_cfg.observation_model.layer_norm),
            activation=world_model_cfg.observation_model.cnn_act,
            dtype=compute_dtype,
            param_dtype=param_dtype,
        )
        if len(cnn_keys_dec) > 0
        else None
    )
    mlp_decoder = (
        MLPDecoderDV2(
            keys=mlp_keys_dec,
            output_dims=[int(obs_space[k].shape[0]) for k in mlp_keys_dec],
            mlp_layers=int(world_model_cfg.observation_model.mlp_layers),
            dense_units=int(world_model_cfg.observation_model.dense_units),
            layer_norm=bool(world_model_cfg.observation_model.layer_norm),
            activation=world_model_cfg.observation_model.dense_act,
            dtype=compute_dtype,
            param_dtype=param_dtype,
        )
        if len(mlp_keys_dec) > 0
        else None
    )
    observation_model = MultiDecoderDV2(cnn_decoder, mlp_decoder)

    reward_model = MLPWithHeadDV2(
        input_dim=latent_state_size,
        hidden_sizes=[int(world_model_cfg.reward_model.dense_units)] * int(world_model_cfg.reward_model.mlp_layers),
        output_dim=1,
        activation=world_model_cfg.reward_model.dense_act,
        layer_norm=bool(world_model_cfg.reward_model.layer_norm),
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )
    continue_model = (
        MLPWithHeadDV2(
            input_dim=latent_state_size,
            hidden_sizes=[int(world_model_cfg.discount_model.dense_units)]
            * int(world_model_cfg.discount_model.mlp_layers),
            output_dim=1,
            activation=world_model_cfg.discount_model.dense_act,
            layer_norm=bool(world_model_cfg.discount_model.layer_norm),
            dtype=compute_dtype,
            param_dtype=param_dtype,
        )
        if world_model_cfg.use_continues
        else None
    )

    # Config-selected actor class (reference hydra.utils.get_class on
    # cfg.algo.actor.cls, agent.py:1022): MinedojoActorDV2 adds masked sampling
    actor_cls = resolve_actor_cls(actor_cfg.get("cls"), ActorDV2, MinedojoActorDV2)
    actor = actor_cls(
        latent_state_size=latent_state_size,
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=cfg.distribution.get("type", "auto"),
        init_std=float(actor_cfg.init_std),
        min_std=float(actor_cfg.min_std),
        dense_units=int(actor_cfg.dense_units),
        mlp_layers=int(actor_cfg.mlp_layers),
        layer_norm=bool(actor_cfg.layer_norm),
        activation=actor_cfg.dense_act,
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )
    critic = MLPWithHeadDV2(
        input_dim=latent_state_size,
        hidden_sizes=[int(critic_cfg.dense_units)] * int(critic_cfg.mlp_layers),
        output_dim=1,
        activation=critic_cfg.dense_act,
        layer_norm=bool(critic_cfg.layer_norm),
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )

    # ---- init params
    key = jax.random.PRNGKey(cfg.seed)
    keys = jax.random.split(key, 10)
    dummy_obs: Dict[str, jax.Array] = {}
    for k in cnn_keys:
        dummy_obs[k] = jnp.zeros((1, int(np.prod(obs_space[k].shape[:-2])), *obs_space[k].shape[-2:]))
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((1, int(obs_space[k].shape[0])))
    wm_params: Dict[str, Any] = {}
    wm_params["encoder"] = encoder.init(keys[0], dummy_obs)
    wm_params["recurrent_model"] = recurrent_model.init(
        keys[1], jnp.zeros((1, int(sum(actions_dim)) + stochastic_size)), jnp.zeros((1, recurrent_state_size))
    )
    wm_params["representation_model"] = representation_model.init(keys[2], jnp.zeros((1, repr_input)))
    wm_params["transition_model"] = transition_model.init(keys[3], jnp.zeros((1, recurrent_state_size)))
    wm_params["observation_model"] = observation_model.init(keys[4], jnp.zeros((1, latent_state_size)))
    wm_params["reward_model"] = reward_model.init(keys[5], jnp.zeros((1, latent_state_size)))
    if continue_model is not None:
        wm_params["continue_model"] = continue_model.init(keys[6], jnp.zeros((1, latent_state_size)))
    actor_params = actor.init(keys[7], jnp.zeros((1, latent_state_size)))
    critic_params = critic.init(keys[8], jnp.zeros((1, latent_state_size)))

    if world_model_state:
        wm_params = jax.tree_util.tree_map(jnp.asarray, world_model_state)
    if actor_state:
        actor_params = jax.tree_util.tree_map(jnp.asarray, actor_state)
    if critic_state:
        critic_params = jax.tree_util.tree_map(jnp.asarray, critic_state)
    target_critic_params = (
        jax.tree_util.tree_map(jnp.asarray, target_critic_state)
        if target_critic_state
        else copy.deepcopy(critic_params)
    )

    modules = DV2Modules(
        encoder=encoder,
        rssm=rssm,
        observation_model=observation_model,
        reward_model=reward_model,
        continue_model=continue_model,
        actor=actor,
        critic=critic,
    )
    params = {
        "world_model": wm_params,
        "actor": actor_params,
        "critic": critic_params,
        "target_critic": target_critic_params,
    }

    player = PlayerDV2(
        encoder=encoder,
        rssm=rssm,
        actor=actor,
        actions_dim=actions_dim,
        num_envs=cfg.env.num_envs,
        stochastic_size=int(world_model_cfg.stochastic_size),
        recurrent_state_size=recurrent_state_size,
        discrete_size=int(world_model_cfg.discrete_size),
    )
    player.expl_amount = float(actor_cfg.get("expl_amount", 0.0))
    player.wm_params = wm_params
    player.actor_params = actor_params
    return modules, params, player
