"""DreamerV2 utilities (reference sheeprl/algos/dreamer_v2/utils.py).

`compute_lambda_values` follows the DV2 formulation (:85-103): explicit bootstrap
value appended, reverse `lax.scan` instead of a Python loop.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.utils import get_action_masks

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}
# Compilation-management counters (core/compile.py), drained once per iteration.
AGGREGATOR_KEYS |= {
    "Compile/retraces",
    "Compile/cache_hits",
    "Compile/cache_misses",
    "Time/compile_seconds",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic"}


def compute_lambda_values(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    bootstrap: Optional[jax.Array] = None,
    lmbda: float = 0.95,
) -> jax.Array:
    """TD(lambda) targets with explicit bootstrap (reference utils.py:85-103).

    Inputs ``[H, B, 1]``; ``bootstrap`` is ``[1, B, 1]`` (defaults to zeros);
    output ``[H, B, 1]``.
    """
    if bootstrap is None:
        bootstrap = jnp.zeros_like(values[-1:])
    next_values = jnp.concatenate([values[1:], bootstrap], axis=0)
    inputs = rewards + continues * next_values * (1 - lmbda)

    def body(carry, xs):
        inp_t, cont_t = xs
        val = inp_t + cont_t * lmbda * carry
        return val, val

    _, out = jax.lax.scan(body, bootstrap[0], (inputs[::-1], continues[::-1]))
    return out[::-1]


def prepare_obs(
    runtime, obs: Dict[str, np.ndarray], *, cnn_keys: Sequence[str] = (), num_envs: int = 1, **kwargs
) -> Dict[str, jax.Array]:
    """Host obs -> device arrays shaped [1, num_envs, ...] (reference utils.py:106-120)."""
    out = {}
    device = runtime.player_device if runtime is not None else None
    for k, v in obs.items():
        arr = np.asarray(v, dtype=np.float32)
        if k in cnn_keys:
            arr = arr.reshape(1, num_envs, -1, *arr.shape[-2:]) / 255.0 - 0.5
        else:
            arr = arr.reshape(1, num_envs, -1)
        # commit to the player's device: an uncommitted jnp.asarray would land on
        # the mesh default device and bounce host->mesh->host for a host player
        out[k] = jnp.asarray(arr) if device is None else jax.device_put(arr, device)
    return out


def test(player, runtime, cfg, log_dir: str, test_name: str = "", greedy: bool = True) -> None:
    """Play one episode on a fresh env (reference utils.py:123-168)."""
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test" + (f"_{test_name}" if test_name else ""))()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=cfg.seed)[0]
    player.num_envs = 1
    player.init_states()
    key = jax.random.PRNGKey(cfg.seed)
    while not done:
        key, step_key = jax.random.split(key)
        jax_obs = prepare_obs(runtime, obs, cnn_keys=cfg.algo.cnn_keys.encoder)
        mask = get_action_masks(jax_obs)
        actions_list = player.get_actions(jax_obs, step_key, greedy=greedy, mask=mask)
        if player.actor.is_continuous:
            real_actions = np.concatenate([np.asarray(a) for a in actions_list], axis=-1)
        else:
            real_actions = np.stack([np.asarray(a).argmax(axis=-1) for a in actions_list], axis=-1)
        obs, reward, terminated, truncated, _ = env.step(real_actions.reshape(env.action_space.shape))
        done = bool(terminated) or bool(truncated) or cfg.dry_run
        cumulative_rew += float(reward)
    runtime.print("Test - Reward:", cumulative_rew)
    if cfg.metric.log_level > 0 and getattr(runtime, "logger", None) is not None:
        runtime.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()


def log_models_from_checkpoint(runtime, env, cfg, state) -> Dict[str, Any]:
    """Register DV2 models from a checkpoint into the local model registry
    (reference dreamer_v1/utils.py:log_models pattern)."""
    import gymnasium as gym

    from sheeprl_tpu.algos.dreamer_v2.agent import build_agent
    from sheeprl_tpu.utils.model_manager import log_model

    is_continuous = isinstance(env.action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(env.action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        env.action_space.shape
        if is_continuous
        else (env.action_space.nvec.tolist() if is_multidiscrete else [env.action_space.n])
    )
    _, params, _ = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        env.observation_space,
        state["world_model"],
        state["actor"],
        state["critic"],
        state["target_critic"],
    )
    info = {}
    for name in ("world_model", "actor", "critic", "target_critic"):
        info[name] = log_model(runtime, cfg, name, params[name])
    return info
