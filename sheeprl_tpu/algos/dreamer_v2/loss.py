"""DreamerV2 world-model loss (reference sheeprl/algos/dreamer_v2/loss.py:9-89).

KL balancing with a single alpha (Eq. 2 of the DV2 paper) plus gaussian
observation/reward log-likelihoods and an optional Bernoulli continue term.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def categorical_kl(p_logits: jax.Array, q_logits: jax.Array) -> jax.Array:
    """KL(p || q) for factorized categoricals ``[..., stoch, discrete]`` -> ``[...]``."""
    p_log = jax.nn.log_softmax(p_logits, axis=-1)
    q_log = jax.nn.log_softmax(q_logits, axis=-1)
    p = jnp.exp(p_log)
    return jnp.sum(p * (p_log - q_log), axis=(-2, -1))


def reconstruction_loss(
    po_log_probs: Dict[str, jax.Array],
    pr_log_prob: jax.Array,
    priors_logits: jax.Array,
    posteriors_logits: jax.Array,
    kl_balancing_alpha: float = 0.8,
    kl_free_nats: float = 0.0,
    kl_free_avg: bool = True,
    kl_regularizer: float = 1.0,
    pc_log_prob: Optional[jax.Array] = None,
    discount_scale_factor: float = 1.0,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Total DV2 world-model loss.

    Args take precomputed per-element log-probs (each ``[T, B]``); the logits are
    ``[T, B, stoch, discrete]``. Returns
    (loss, kl, state_loss, reward_loss, observation_loss, continue_loss).
    """
    observation_loss = -sum(lp.mean() for lp in po_log_probs.values())
    reward_loss = -pr_log_prob.mean()
    # KL balancing (reference loss.py:62-84): lhs trains the prior toward the
    # (stopped) posterior, rhs regularizes the posterior toward the (stopped) prior.
    lhs = kl = categorical_kl(jax.lax.stop_gradient(posteriors_logits), priors_logits)
    rhs = categorical_kl(posteriors_logits, jax.lax.stop_gradient(priors_logits))
    if kl_free_avg:
        loss_lhs = jnp.maximum(lhs.mean(), kl_free_nats)
        loss_rhs = jnp.maximum(rhs.mean(), kl_free_nats)
    else:
        loss_lhs = jnp.maximum(lhs, kl_free_nats).mean()
        loss_rhs = jnp.maximum(rhs, kl_free_nats).mean()
    kl_loss = kl_balancing_alpha * loss_lhs + (1 - kl_balancing_alpha) * loss_rhs
    if pc_log_prob is not None:
        continue_loss = discount_scale_factor * -pc_log_prob.mean()
    else:
        continue_loss = jnp.zeros_like(reward_loss)
    loss = kl_regularizer * kl_loss + observation_loss + reward_loss + continue_loss
    return loss, kl.mean(), kl_loss, reward_loss, observation_loss, continue_loss
