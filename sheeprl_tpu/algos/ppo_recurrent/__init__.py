from sheeprl_tpu.algos.ppo_recurrent import ppo_recurrent  # noqa: F401
from sheeprl_tpu.algos.ppo_recurrent import evaluate  # noqa: F401
