"""Recurrent PPO agent (reference sheeprl/algos/ppo_recurrent/agent.py).

RecurrentModel (:18): optional pre-MLP -> single-layer LSTM -> optional post-MLP.
RecurrentPPOAgent (:83): encoder + rnn(features ++ prev_actions) -> actor heads +
critic. TPU design: the LSTM is a flax LSTMCell scanned with ``lax.scan`` over time;
padded timesteps freeze the carry via the mask (replaces torch pack_padded_sequence).
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.algos.ppo.agent import CNNEncoder, MLPEncoder, evaluate_actions, sample_actions
from sheeprl_tpu.models.models import MLP, MultiEncoder
from sheeprl_tpu.utils.utils import host_float32


class RecurrentModel(nn.Module):
    lstm_hidden_size: int
    pre_rnn_mlp_cfg: Dict[str, Any]
    post_rnn_mlp_cfg: Dict[str, Any]
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: jax.Array,  # [T, B, D]
        states: Tuple[jax.Array, jax.Array],  # (hx, cx) each [B, H]
        mask: Optional[jax.Array] = None,  # [T, B, 1]
    ) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
        if self.pre_rnn_mlp_cfg["apply"]:
            x = MLP(
                input_dims=1,
                hidden_sizes=[self.pre_rnn_mlp_cfg["dense_units"]],
                activation=self.pre_rnn_mlp_cfg["activation"],
                layer_norm=self.pre_rnn_mlp_cfg["layer_norm"],
                dtype=self.dtype,
            )(x)
        cell = nn.OptimizedLSTMCell(self.lstm_hidden_size, dtype=self.dtype, param_dtype=jnp.float32)
        rnn = nn.RNN(cell, time_major=True, return_carry=True)
        hx, cx = states
        carry0 = (cx.astype(self.dtype), hx.astype(self.dtype))
        # seq_lengths freezes the carry past each sequence's end — the in-graph
        # analogue of torch pack_padded_sequence (reference agent.py:74-80).
        seq_lengths = None
        if mask is not None:
            seq_lengths = mask[..., 0].sum(axis=0).astype(jnp.int32)
        (cx_f, hx_f), out = rnn(x.astype(self.dtype), initial_carry=carry0, seq_lengths=seq_lengths)
        if mask is not None:
            out = out * mask.astype(out.dtype)
        if self.post_rnn_mlp_cfg["apply"]:
            out = MLP(
                input_dims=1,
                hidden_sizes=[self.post_rnn_mlp_cfg["dense_units"]],
                activation=self.post_rnn_mlp_cfg["activation"],
                layer_norm=self.post_rnn_mlp_cfg["layer_norm"],
                dtype=self.dtype,
            )(out)
        return out.astype(jnp.float32), (hx_f.astype(jnp.float32), cx_f.astype(jnp.float32))


class RecurrentPPOAgent(nn.Module):
    """Encoder + RNN(features ++ prev_actions) + actor/critic heads (reference :83)."""

    actions_dim: Sequence[int]
    is_continuous: bool
    distribution: str
    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_input_channels: int
    mlp_input_dim: int
    screen_size: int
    encoder_cfg: Dict[str, Any]
    rnn_cfg: Dict[str, Any]
    actor_cfg: Dict[str, Any]
    critic_cfg: Dict[str, Any]
    dtype: Any = jnp.float32

    @property
    def rnn_hidden_size(self) -> int:
        return self.rnn_cfg["lstm"]["hidden_size"]

    def setup(self) -> None:
        cnn_encoder = (
            CNNEncoder(
                self.cnn_input_channels,
                self.encoder_cfg["cnn_features_dim"],
                self.screen_size,
                self.cnn_keys,
                dtype=self.dtype,
            )
            if len(self.cnn_keys) > 0
            else None
        )
        mlp_encoder = (
            MLPEncoder(
                self.mlp_input_dim,
                self.encoder_cfg["mlp_features_dim"],
                self.mlp_keys,
                self.encoder_cfg["dense_units"],
                self.encoder_cfg["mlp_layers"],
                self.encoder_cfg["dense_act"],
                self.encoder_cfg["layer_norm"],
                dtype=self.dtype,
            )
            if len(self.mlp_keys) > 0
            else None
        )
        self.feature_extractor = MultiEncoder(cnn_encoder, mlp_encoder)
        self.rnn = RecurrentModel(
            lstm_hidden_size=self.rnn_cfg["lstm"]["hidden_size"],
            pre_rnn_mlp_cfg=dict(self.rnn_cfg["pre_rnn_mlp"]),
            post_rnn_mlp_cfg=dict(self.rnn_cfg["post_rnn_mlp"]),
            dtype=self.dtype,
        )
        self.critic = MLP(
            input_dims=1,
            output_dim=1,
            hidden_sizes=[self.critic_cfg["dense_units"]] * self.critic_cfg["mlp_layers"],
            activation=self.critic_cfg["dense_act"],
            layer_norm=self.critic_cfg["layer_norm"],
        )
        self.actor_backbone = MLP(
            input_dims=1,
            output_dim=None,
            hidden_sizes=[self.actor_cfg["dense_units"]] * self.actor_cfg["mlp_layers"],
            activation=self.actor_cfg["dense_act"],
            layer_norm=self.actor_cfg["layer_norm"],
        )
        if self.is_continuous:
            self.actor_heads = [nn.Dense(sum(self.actions_dim) * 2)]
        else:
            self.actor_heads = [nn.Dense(d) for d in self.actions_dim]

    def __call__(
        self,
        obs: Dict[str, jax.Array],  # values [T, B, ...]
        prev_actions: jax.Array,  # [T, B, sum(actions_dim)]
        prev_states: Tuple[jax.Array, jax.Array],  # (hx, cx) each [B, H]
        mask: Optional[jax.Array] = None,  # [T, B, 1]
    ) -> Tuple[List[jax.Array], jax.Array, jax.Array, Tuple[jax.Array, jax.Array]]:
        """Returns (actor_outs [T,B,*], values [T,B,1], rnn_out, new_states)."""
        feats = self.feature_extractor(obs)
        out, states = self.rnn(jnp.concatenate([feats, prev_actions.astype(feats.dtype)], -1), prev_states, mask)
        values = self.critic(out).astype(jnp.float32)
        x = self.actor_backbone(out)
        actor_outs = [head(x).astype(jnp.float32) for head in self.actor_heads]
        return actor_outs, values, states


class RecurrentPPOPlayer:
    """Single-step rollout policy with carried LSTM state (reference :265)."""

    def __init__(self, agent: RecurrentPPOAgent, params: Any, actions_dim: Sequence[int], num_envs: int):
        self.agent = agent
        self.params = params
        self.actions_dim = tuple(actions_dim)
        self.num_envs = num_envs

        def _env_actions(actions):
            if agent.is_continuous:
                return jnp.concatenate(actions, -1)
            return jnp.concatenate([a.argmax(-1, keepdims=True).astype(jnp.int32) for a in actions], -1)

        def _act(params, obs, prev_actions, prev_states, key, greedy):
            key, sub = jax.random.split(key)
            actor_outs, values, states = agent.apply(params, obs, prev_actions, prev_states)
            # single timestep: T == 1
            actions = sample_actions(
                [a[0] for a in actor_outs], sub, agent.is_continuous, agent.distribution, greedy=greedy
            )
            logp, _ = evaluate_actions(
                [a[0] for a in actor_outs], actions, agent.is_continuous, agent.distribution
            )
            cat = jnp.concatenate(actions, -1)
            # host_float32: rollout products are pulled to host / stored f32 (bf16
            # degrades to |V2 through the remote-TPU tunnel); states stay native.
            return host_float32((cat[None], _env_actions(actions), logp[None], values)) + (states, key)

        def _values(params, obs, prev_actions, prev_states):
            _, values, states = agent.apply(params, obs, prev_actions, prev_states)
            return host_float32(values[0]), states

        def _act_raw(params, obs, prev_actions, prev_states, key, greedy):
            # raw host obs [n_envs, ...] -> normalized [T=1, n_envs, ...] in-graph
            # (one dispatch per env step; see PPOPlayer.act_raw for the pattern)
            prepped = {}
            for k, v in obs.items():
                v = jnp.asarray(v, jnp.float32)
                if k in agent.cnn_keys:
                    v = v.reshape(v.shape[0], -1, *v.shape[-2:]) / 255.0 - 0.5
                else:
                    v = v.reshape(v.shape[0], -1)
                prepped[k] = v[None]
            return _act(params, prepped, prev_actions[None], prev_states, key, greedy)

        self._act = jax_compile.guarded_jit(_act, name="ppo_recurrent.act", static_argnums=(5,))
        self._act_raw = jax_compile.guarded_jit(_act_raw, name="ppo_recurrent.act_raw", static_argnums=(5,))
        self._values = jax_compile.guarded_jit(_values, name="ppo_recurrent.values")
        self._act_impl = _act
        self._packed_act_fns: Dict[Any, Any] = {}

    def initial_states(self, hidden_size: int):
        return (
            jnp.zeros((self.num_envs, hidden_size), dtype=jnp.float32),
            jnp.zeros((self.num_envs, hidden_size), dtype=jnp.float32),
        )

    def __call__(self, obs, prev_actions, prev_states, key, greedy: bool = False):
        return self._act(self.params, obs, prev_actions, prev_states, key, greedy)

    def act_raw(self, obs, prev_actions, prev_states, key, greedy: bool = False):
        """Raw host obs (no T dim, [0,255] cnn stacks) + prev_actions [n_envs, A]:
        normalization, T=1 expansion, and the forward run as ONE jitted dispatch."""
        return self._act_raw(self.params, obs, prev_actions, prev_states, key, greedy)

    def act_packed(self, codec, packed, prev_actions, prev_states, key, greedy: bool = False):
        """Like act_raw but fed by ONE packed host->device transfer (see
        core/pipeline.PackedObsCodec): unpack + normalize + T=1 expansion run
        in-graph; prev actions/states stay device-resident between steps."""
        cache_key = (codec.signature, bool(greedy))
        fn = self._packed_act_fns.get(cache_key)
        if fn is None:

            def _packed(params, packed, prev_actions, prev_states, key):
                obs = {k: v[None] for k, v in codec.decode_obs(packed).items()}
                return self._act_impl(params, obs, prev_actions[None], prev_states, key, greedy)

            fn = jax_compile.guarded_jit(_packed, name="ppo_recurrent.act_packed")
            self._packed_act_fns[cache_key] = fn
        return fn(self.params, packed, prev_actions, prev_states, key)

    def get_values(self, obs, prev_actions, prev_states):
        return self._values(self.params, obs, prev_actions, prev_states)


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space: gymnasium.spaces.Dict,
    agent_state: Optional[Dict[str, Any]] = None,
) -> Tuple[RecurrentPPOAgent, Any, RecurrentPPOPlayer]:
    distribution = cfg.distribution.get("type", "auto").lower()
    if distribution == "auto":
        distribution = "normal" if is_continuous else "discrete"
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    in_channels = sum(prod(obs_space[k].shape[:-2]) for k in cnn_keys)
    mlp_input_dim = sum(obs_space[k].shape[0] for k in mlp_keys)
    agent = RecurrentPPOAgent(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=distribution,
        cnn_keys=tuple(cnn_keys),
        mlp_keys=tuple(mlp_keys),
        cnn_input_channels=in_channels,
        mlp_input_dim=mlp_input_dim,
        screen_size=cfg.env.screen_size,
        encoder_cfg=dict(cfg.algo.encoder),
        rnn_cfg=dict(cfg.algo.rnn),
        actor_cfg=dict(cfg.algo.actor),
        critic_cfg=dict(cfg.algo.critic),
        dtype=runtime.compute_dtype,
    )
    n_envs = cfg.env.num_envs * runtime.world_size
    sample_obs = {}
    for k in cnn_keys:
        shape = obs_space[k].shape
        sample_obs[k] = jnp.zeros((1, 1, prod(shape[:-2]), *shape[-2:]), dtype=jnp.float32)
    for k in mlp_keys:
        sample_obs[k] = jnp.zeros((1, 1, *obs_space[k].shape), dtype=jnp.float32)
    h = cfg.algo.rnn.lstm.hidden_size
    init_states = (jnp.zeros((1, h)), jnp.zeros((1, h)))
    prev_actions = jnp.zeros((1, 1, sum(actions_dim)), dtype=jnp.float32)
    params = agent.init(jax.random.PRNGKey(cfg.seed), sample_obs, prev_actions, init_states)
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
    params = runtime.place_params(params)
    # player copy lives on the player device (host CPU by default): no accelerator
    # round-trip per env step (see sheeprl_tpu.core.runtime.Runtime.player_device)
    player = RecurrentPPOPlayer(agent, runtime.to_player(params), actions_dim, n_envs)
    return agent, params, player
