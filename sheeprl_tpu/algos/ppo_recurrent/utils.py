"""Recurrent PPO utilities (reference sheeprl/algos/ppo_recurrent/utils.py)."""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.utils import normalize_obs, prepare_obs  # noqa: F401

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/entropy_loss",
    "Resilience/env_restarts",
    "Resilience/env_timeouts",
    "Resilience/nonfinite_skips",
}
# Compilation-management counters (core/compile.py), drained once per iteration.
AGGREGATOR_KEYS |= {
    "Compile/retraces",
    "Compile/cache_hits",
    "Compile/cache_misses",
    "Time/compile_seconds",
}
MODELS_TO_REGISTER = {"agent"}


def test(player, runtime, cfg, log_dir: str) -> None:
    """Greedy evaluation episode with carried recurrent state (reference utils.py:37)."""
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=cfg.seed)[0]
    key = jax.random.PRNGKey(cfg.seed)
    h = player.agent.rnn_hidden_size
    states = (jnp.zeros((1, h)), jnp.zeros((1, h)))
    prev_actions = jnp.zeros((1, 1, sum(player.actions_dim)), dtype=jnp.float32)
    while not done:
        jax_obs = prepare_obs(runtime, obs, cnn_keys=cfg.algo.cnn_keys.encoder)
        jax_obs = {k: v[None] for k, v in jax_obs.items()}
        cat_actions, env_actions, _, _, states, key = player(jax_obs, prev_actions, states, key, greedy=True)
        prev_actions = cat_actions
        real_actions = np.asarray(env_actions)[0]
        obs, reward, terminated, truncated, _ = env.step(
            np.asarray(real_actions).reshape(env.action_space.shape)
        )
        done = terminated or truncated
        cumulative_rew += reward
        if cfg.dry_run:
            done = True
    if cfg.metric.log_level > 0:
        runtime.print(f"Test - Reward: {cumulative_rew}")
        if getattr(runtime, "logger", None) is not None:
            runtime.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()

# Single-'agent' registration shared with the other model-free algos.
from sheeprl_tpu.utils.model_manager import log_agent_from_checkpoint as log_models_from_checkpoint  # noqa: E402, F401
