"""Recurrent PPO (reference sheeprl/algos/ppo_recurrent/ppo_recurrent.py:31-120 train,
:120 main).

BPTT over sequence chunks. Host side splits the rollout into per-env episodes, chunks
them to ``per_rank_sequence_length``, pads, and buckets the sequence count to a
power-of-two so the jitted train function (epochs x minibatches via ``lax.scan``,
masked losses) retraces only on bucket growth — not every iteration.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, List

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo.utils import normalize_obs, prepare_obs
from sheeprl_tpu.algos.ppo_recurrent.agent import build_agent, evaluate_actions
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.core import health as health_mod
from sheeprl_tpu.core import resilience
from sheeprl_tpu.core.pipeline import AsyncEnvStepper, PackedObsCodec, pipeline_enabled
from sheeprl_tpu.data.factory import make_rollout_buffer
from sheeprl_tpu.utils.env import finished_episodes, make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.optim import with_clipping
from sheeprl_tpu.utils.profiler import TraceProfiler
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import PlayerParamsSync, gae, polynomial_decay, save_configs


def _masked_mean(x: jax.Array, mask: jax.Array) -> jax.Array:
    return (x * mask).sum() / jnp.clip(mask.sum(), 1, None)


def make_train_fn(agent, tx, cfg, runtime, obs_keys, cnn_keys, params_sync=None):
    update_epochs = int(cfg.algo.update_epochs)
    n_batches = max(int(cfg.algo.per_rank_num_batches), 1)
    data_sharding = NamedSharding(runtime.mesh, P(None, "data"))
    nonfinite_guard = resilience.guard_enabled(resilience.resolve(cfg))

    def loss_fn(params, batch, clip_coef, ent_coef):
        norm_obs = normalize_obs(batch, cnn_keys, obs_keys)
        actions = (
            jnp.split(batch["actions"], np.cumsum(agent.actions_dim)[:-1].tolist(), axis=-1)
            if len(agent.actions_dim) > 1
            else [batch["actions"]]
        )
        mask = batch["mask"]
        actor_outs, values, _ = agent.apply(
            params, norm_obs, batch["prev_actions"], (batch["prev_hx"], batch["prev_cx"]), mask
        )
        new_logprobs, entropy = evaluate_actions(actor_outs, actions, agent.is_continuous, agent.distribution)
        advantages = batch["advantages"]
        if cfg.algo.normalize_advantages:
            # masked normalization (reference ppo_recurrent.py:77-81)
            n = jnp.clip(mask.sum(), 1, None)
            mean = (advantages * mask).sum() / n
            var = (((advantages - mean) * mask) ** 2).sum() / n
            advantages = (advantages - mean) / (jnp.sqrt(var) + 1e-8) * mask
        pg = policy_loss(new_logprobs, batch["logprobs"], advantages, clip_coef, "none")
        pg_loss = _masked_mean(pg, mask)
        if cfg.algo.clip_vloss:
            v_unclipped = (values - batch["returns"]) ** 2
            v_clipped_pred = batch["values"] + jnp.clip(values - batch["values"], -clip_coef, clip_coef)
            v_clipped = (v_clipped_pred - batch["returns"]) ** 2
            v_loss = 0.5 * _masked_mean(jnp.maximum(v_unclipped, v_clipped), mask)
        else:
            v_loss = _masked_mean((values - batch["returns"]) ** 2, mask)
        ent_loss = -_masked_mean(entropy, mask)
        total = pg_loss + cfg.algo.vf_coef * v_loss + cfg.algo.ent_coef * ent_loss
        return total, (pg_loss, v_loss, ent_loss)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def train(params, opt_state, data, key, clip_coef, ent_coef, lr_scale):
        n_seq = next(iter(data.values())).shape[1]
        batch_size = max(n_seq // n_batches, 1)
        n_mb = n_seq // batch_size

        epoch_keys = jax.random.split(key, update_epochs)
        perms = jnp.stack([jax.random.permutation(k, n_seq)[: n_mb * batch_size] for k in epoch_keys])
        perms = perms.reshape(update_epochs * n_mb, batch_size)

        def minibatch_step(carry, idx):
            params, opt_state = carry
            batch = jax.tree_util.tree_map(
                lambda v: jax.lax.with_sharding_constraint(jnp.take(v, idx, axis=1), data_sharding), data
            )
            # initial LSTM states of each sequence: [B, H]
            batch = dict(batch)
            batch["prev_hx"] = batch["prev_hx"][0]
            batch["prev_cx"] = batch["prev_cx"][0]
            (loss, (pg, vl, ent)), grads = grad_fn(params, batch, clip_coef, ent_coef)
            gnorm = optax.global_norm(grads)
            updates, new_opt_state = tx.update(grads, opt_state, params)
            # health-sentinel LR backoff: traced scalar operand; 1.0 is IEEE-exact
            updates = jax.tree_util.tree_map(lambda u: u * lr_scale, updates)
            new_params = optax.apply_updates(params, updates)
            if nonfinite_guard:
                (params, opt_state), skipped = resilience.finite_or_skip(
                    (loss, gnorm), (new_params, new_opt_state), (params, opt_state)
                )
            else:
                params, opt_state, skipped = new_params, new_opt_state, jnp.float32(0.0)
            return (params, opt_state), jnp.stack([pg, vl, ent, skipped, gnorm])

        (params, opt_state), losses = jax.lax.scan(minibatch_step, (params, opt_state), perms)
        metrics = losses.mean(axis=0)
        flat_params = params_sync.ravel(params) if params_sync is not None else jnp.zeros(())
        return params, opt_state, flat_params, {
            "Loss/policy_loss": metrics[0],
            "Loss/value_loss": metrics[1],
            "Loss/entropy_loss": metrics[2],
            "Resilience/nonfinite_skips": losses[:, 3].sum(),
            "Grads/global_norm": metrics[4],
        }

    return jax_compile.guarded_jit(train, name="ppo_recurrent.train", donate_argnums=(0, 1))


def _chunk_and_pad(local_data: Dict[str, np.ndarray], dones: np.ndarray, sl: int, n_envs: int):
    """Split the rollout into per-env episodes, chunk to length <= sl, pad + mask.

    Returns dict of arrays [sl, n_seq_padded, ...] with a `mask` key; n_seq is
    bucketed to the next power of two (zero-mask padding) for jit-shape stability.
    """
    sequences: Dict[str, List[np.ndarray]] = {k: [] for k in local_data.keys()}
    lengths: List[int] = []
    T = next(iter(local_data.values())).shape[0]
    for env_id in range(n_envs):
        ends = np.nonzero(dones[:, env_id, 0])[0].tolist()
        ends.append(T - 1)
        start = 0
        for stop in ends:
            if stop + 1 <= start:
                continue
            ep_slice = slice(start, stop + 1)
            ep_len = stop + 1 - start
            for s0 in range(0, ep_len, sl):
                s1 = min(s0 + sl, ep_len)
                for k, v in local_data.items():
                    sequences[k].append(v[ep_slice][s0:s1, env_id])
                lengths.append(s1 - s0)
            start = stop + 1
    return jax_compile.bucketed_pad(sequences, lengths, sl)


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    if "minedojo" in cfg.env.wrapper._target_.lower():
        raise ValueError("MineDojo is not currently supported by PPO-recurrent agent.")
    initial_ent_coef = float(cfg.algo.ent_coef)
    initial_clip_coef = float(cfg.algo.clip_coef)
    world_size = runtime.world_size

    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_state

        state = load_state(cfg.checkpoint.resume_from)

    logger = get_logger(runtime, cfg)
    if logger:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.logger = logger
    runtime.print(f"Log dir: {log_dir}")

    ft = resilience.resolve(cfg)
    sentinel = health_mod.HealthSentinel(
        cfg, log_dir=log_dir if runtime.is_global_zero else None, world_size=world_size
    )
    n_envs = cfg.env.num_envs * world_size
    envs = resilience.make_supervised_env(
        [
            make_env(cfg, cfg.seed + i, 0, log_dir if runtime.is_global_zero else None, "train", vector_env_idx=i)
            for i in range(n_envs)
        ],
        sync=cfg.env.sync_env,
        ft=ft,
    )
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    obs_keys = cfg.algo.cnn_keys.encoder + cfg.algo.mlp_keys.encoder
    cnn_keys = cfg.algo.cnn_keys.encoder
    if cfg.algo.rollout_steps % cfg.algo.per_rank_sequence_length != 0:
        raise ValueError(
            "The rollout steps must be a multiple of the per_rank_sequence_length, got "
            f"{cfg.algo.rollout_steps} and {cfg.algo.per_rank_sequence_length}"
        )

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (envs.single_action_space.nvec.tolist() if is_multidiscrete else [envs.single_action_space.n])
    )

    agent, params, player = build_agent(
        runtime, actions_dim, is_continuous, cfg, observation_space, state["agent"] if state else None
    )

    policy_steps_per_iter = int(n_envs * cfg.algo.rollout_steps)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    tx = with_clipping(instantiate(dict(cfg.algo.optimizer))(), cfg.algo.max_grad_norm)
    opt_state = tx.init(params)
    if state:
        opt_state = jax.tree_util.tree_map(jnp.asarray, state["optimizer"])
    opt_state = runtime.place_params(opt_state)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    rb = make_rollout_buffer(cfg, runtime, n_envs, obs_keys, log_dir)
    # device backend: policy outputs AND recurrent states stay in HBM per step;
    # the episode chunking below still runs on host, fed by ONE bulk pull per
    # iteration (rollout_host) instead of per-step np.asarray syncs
    device_rollout = getattr(rb, "backend", "host") == "device"

    last_train = 0
    train_step = 0
    start_iter = (state["iter_num"] // world_size) + 1 if state else 1
    policy_step = state["iter_num"] * cfg.env.num_envs * cfg.algo.rollout_steps if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0

    params_sync = PlayerParamsSync(player.params)
    train_fn = make_train_fn(agent, tx, cfg, runtime, obs_keys, cnn_keys, params_sync)
    profiler = TraceProfiler(cfg.metric.get("profiler"), log_dir if runtime.is_global_zero else None)
    rng = jax.random.PRNGKey(cfg.seed)
    player_rng = jax.device_put(jax.random.PRNGKey(cfg.seed + 1), runtime.player_device)
    if state and "rng" in state:
        rng = jnp.asarray(state["rng"])
        player_rng = jax.device_put(jnp.asarray(state["player_rng"]), runtime.player_device)
    h = cfg.algo.rnn.lstm.hidden_size

    step_data = {}
    reset_obs = envs.reset(seed=cfg.seed)[0]
    next_obs = {}
    for k in obs_keys:
        _obs = reset_obs[k]
        if k in cnn_keys:
            _obs = _obs.reshape(n_envs, -1, *_obs.shape[-2:])
        next_obs[k] = _obs
        step_data[k] = _obs[np.newaxis]
    prev_states = player.initial_states(h)
    prev_actions = np.zeros((n_envs, sum(actions_dim)), dtype=np.float32)

    # ----- software pipeline (core/pipeline.py): same structure as ppo.py; the
    # recurrent state feedback (prev_actions/prev_states) stays immediate after
    # step_wait because the NEXT act depends on it, everything else is deferred
    stepper = AsyncEnvStepper(envs, enabled=pipeline_enabled(cfg))
    codec = PackedObsCodec(cnn_keys=cnn_keys, device=runtime.player_device)
    zero_extra = {
        "rewards": np.zeros((n_envs, 1), np.float32),
        "dones": np.zeros((n_envs, 1), np.float32),
    }
    pending: Dict[str, Any] = {}

    def _process_pending(cur_packed):
        """Close out the previous step while the env workers run (see ppo.py)."""
        if not pending:
            return
        if device_rollout:
            if cur_packed is not None:
                extra_packed, extra_only = cur_packed, False
            else:
                extra_packed, extra_only = (
                    codec.encode_extra_only(
                        {"rewards": pending["rewards"], "dones": pending["dones"]}
                    ),
                    True,
                )
            rb.add_env_packed(codec, pending["packed"], extra_packed, extra_only=extra_only)
        else:
            step_data["dones"] = pending["dones"][np.newaxis]
            step_data["values"] = np.asarray(pending["values"])[np.newaxis].reshape(1, n_envs, 1)
            step_data["actions"] = np.asarray(pending["cat_actions"]).reshape(1, n_envs, -1)
            step_data["logprobs"] = np.asarray(pending["logprobs"]).reshape(1, n_envs, 1)
            step_data["rewards"] = pending["rewards"][np.newaxis]
            step_data["prev_hx"] = np.asarray(pending["prev_hx"]).reshape(1, n_envs, -1)
            step_data["prev_cx"] = np.asarray(pending["prev_cx"]).reshape(1, n_envs, -1)
            step_data["prev_actions"] = np.asarray(pending["prev_actions"]).reshape(1, n_envs, -1)
            rb.add(step_data, validate_args=cfg.buffer.validate_args)
            for k in obs_keys:
                step_data[k] = next_obs[k][np.newaxis]
        if cfg.metric.log_level > 0:
            for i, (ep_rew, ep_len) in enumerate(finished_episodes(pending["info"])):
                if aggregator and "Rewards/rew_avg" in aggregator:
                    aggregator.update("Rewards/rew_avg", ep_rew)
                if aggregator and "Game/ep_len_avg" in aggregator:
                    aggregator.update("Game/ep_len_avg", ep_len)
                runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")
        pending.clear()

    def _ckpt_state():
        # shared by the periodic checkpoint and the preemption emergency save so
        # both are resumable through the identical path; the rng chains make the
        # resumed run BIT-IDENTICAL to an uninterrupted one
        return {
            "agent": jax.device_get(params),
            "optimizer": jax.device_get(opt_state),
            "iter_num": iter_num * world_size,
            "batch_size": -1,
            "last_log": last_log,
            "last_checkpoint": last_checkpoint,
            "rng": jax.device_get(rng),
            "player_rng": jax.device_get(player_rng),
        }

    guard = resilience.PreemptionGuard(
        enabled=ft.preemption.enabled, stop_after_iters=ft.preemption.stop_after_iters
    )
    with guard:
        for iter_num in range(start_iter, total_iters + 1):
            profiler.step(policy_step)
            for _ in range(cfg.algo.rollout_steps):
                policy_step += n_envs

                with timer("Time/env_interaction_time", SumMetric()):
                    # ONE packed host->device transfer per step: obs plus the
                    # previous step's rewards/dones; prev actions/states already
                    # live on the device (see RecurrentPPOPlayer.act_packed)
                    packed = codec.encode(
                        next_obs,
                        extra={"rewards": pending["rewards"], "dones": pending["dones"]}
                        if pending
                        else zero_extra,
                    )
                    cat_actions, env_actions, logprobs, values, states, player_rng = player.act_packed(
                        codec,
                        packed,
                        prev_actions,
                        prev_states,
                        player_rng,
                    )
                    real_actions = np.asarray(env_actions)
                    stepper.step_async(real_actions.reshape(envs.action_space.shape))

                    # ---- overlap window: env workers are stepping; close out the
                    # previous step and scatter this one's policy row in-graph
                    _process_pending(packed)
                    if device_rollout:
                        # policy outputs + the recurrent state that PRODUCED this
                        # step: all scattered in-graph, no per-step host pull
                        rb.add_policy(
                            {
                                "values": jnp.reshape(values, (n_envs, 1)),
                                "actions": jnp.reshape(cat_actions, (n_envs, -1)),
                                "logprobs": jnp.reshape(logprobs, (n_envs, 1)),
                                "prev_hx": jnp.reshape(prev_states[0], (n_envs, -1)),
                                "prev_cx": jnp.reshape(prev_states[1], (n_envs, -1)),
                                "prev_actions": jnp.reshape(jnp.asarray(prev_actions), (n_envs, -1)),
                            }
                        )

                    obs, rewards, terminated, truncated, info = stepper.step_wait()
                    rewards = np.asarray(rewards, dtype=np.float32)
                    # bootstrap on truncation (reference ppo_recurrent.py:312-336)
                    truncated_envs = np.nonzero(truncated)[0]
                    if len(truncated_envs) > 0 and "final_obs" in info:
                        final_obs_arr = np.asarray(info["final_obs"], dtype=object)
                        for te in truncated_envs:
                            fo = final_obs_arr[te]
                            if fo is None:
                                continue
                            f_obs = {}
                            for k in obs_keys:
                                v = np.asarray(fo[k], dtype=np.float32)
                                if k in cnn_keys:
                                    v = v.reshape(-1, *v.shape[-2:]) / 255.0 - 0.5
                                f_obs[k] = jnp.asarray(v)[None, None]
                            te_states = tuple(s[te : te + 1] for s in states)
                            te_prev_act = jnp.asarray(cat_actions).reshape(n_envs, -1)[te : te + 1][None]
                            val, _ = player.get_values(f_obs, te_prev_act, te_states)
                            rewards[te] += cfg.algo.gamma * float(np.asarray(val).reshape(-1)[0])
                    dones = np.logical_or(terminated, truncated).reshape(n_envs, -1).astype(np.float32)
                    rewards = rewards.reshape(n_envs, -1)

                # env products become the next step's pending work (the row write
                # and episode accounting run in the NEXT overlap window); the
                # act-time recurrent state is captured before the feedback below
                pending.update(
                    packed=packed,
                    rewards=rewards,
                    dones=dones,
                    info=info,
                    values=values,
                    cat_actions=cat_actions,
                    logprobs=logprobs,
                    prev_hx=prev_states[0],
                    prev_cx=prev_states[1],
                    prev_actions=prev_actions,
                )

                if device_rollout:
                    # prev action feedback stays device-side (the dones put is
                    # small and async)
                    prev_actions = jnp.asarray(1.0 - dones, dtype=jnp.float32) * jnp.reshape(
                        cat_actions, (n_envs, -1)
                    )
                else:
                    prev_actions = (1 - dones) * np.asarray(cat_actions).reshape(n_envs, -1)

                # reset recurrent state on done (reference :356-371)
                if cfg.algo.reset_recurrent_state_on_done:
                    not_done = jnp.asarray(1.0 - dones, dtype=jnp.float32)
                    prev_states = tuple(not_done * s for s in states)
                else:
                    prev_states = states

                next_obs = {}
                for k in obs_keys:
                    _obs = obs[k]
                    if k in cnn_keys:
                        _obs = _obs.reshape(n_envs, -1, *_obs.shape[-2:])
                    next_obs[k] = _obs

            with timer("Time/env_interaction_time", SumMetric()):
                # flush: the rollout's last row has no next act transfer to ride
                _process_pending(None)

            # device path: ONE bulk de-layout pull feeds the host-side episode
            # chunking (variable-length episode splitting is inherently host work)
            local_data = rb.rollout_host() if device_rollout else rb.to_arrays(dtype=np.float32)
            with timer("Time/train_time", SumMetric()):
                jax_obs = prepare_obs(runtime, next_obs, cnn_keys=cnn_keys, num_envs=n_envs)
                jax_obs = {k: v[None] for k, v in jax_obs.items()}
                next_values = np.asarray(
                    player.get_values(
                        jax_obs,
                        jax.device_put(np.asarray(prev_actions)[None], runtime.player_device),
                        prev_states,
                    )[0]
                )
                returns, advantages = gae(
                    jnp.asarray(local_data["rewards"]),
                    jnp.asarray(local_data["values"]),
                    jnp.asarray(local_data["dones"]),
                    next_values,
                    cfg.algo.rollout_steps,
                    cfg.algo.gamma,
                    cfg.algo.gae_lambda,
                )
                local_data["returns"] = np.asarray(returns, dtype=np.float32)
                local_data["advantages"] = np.asarray(advantages, dtype=np.float32)
                padded = _chunk_and_pad(
                    local_data, local_data["dones"], cfg.algo.per_rank_sequence_length, n_envs
                )
                device_data = {k: jnp.asarray(v) for k, v in padded.items()}
                rng, train_key = jax.random.split(rng)
                params, opt_state, flat_params, train_metrics = train_fn(
                    params,
                    opt_state,
                    device_data,
                    train_key,
                    jnp.float32(cfg.algo.clip_coef),
                    jnp.float32(cfg.algo.ent_coef),
                    jnp.float32(sentinel.lr_scale),
                )
                player.params = params_sync.pull(flat_params, runtime.player_device)
                if not timer.disabled:  # sync only when the train phase is being timed
                    jax.block_until_ready(params)
            train_step += world_size

            if cfg.metric.log_level > 0:
                if aggregator:
                    aggregator.update_from_device(train_metrics)
                if policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters:
                    overlap_s, overlap_steps = stepper.drain_overlap()
                    if overlap_s > 0:
                        sps_overlap = overlap_steps * n_envs * cfg.env.action_repeat / overlap_s
                        if aggregator and "Time/sps_pipeline_overlap" in aggregator:
                            aggregator.update("Time/sps_pipeline_overlap", sps_overlap)
                        else:
                            logger.log_metrics({"Time/sps_pipeline_overlap": sps_overlap}, policy_step)
                    if aggregator and not aggregator.disabled:
                        logger.log_metrics(aggregator.compute(), policy_step)
                        aggregator.reset()
                    if not timer.disabled:
                        timer_metrics = timer.compute()
                        if timer_metrics.get("Time/train_time", 0) > 0:
                            logger.log_metrics(
                                {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                                policy_step,
                            )
                        if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                            logger.log_metrics(
                                {
                                    "Time/sps_env_interaction": (
                                        (policy_step - last_log) / world_size * cfg.env.action_repeat
                                    )
                                    / timer_metrics["Time/env_interaction_time"]
                                },
                                policy_step,
                            )
                        timer.reset()
                    last_log = policy_step
                    last_train = train_step

            if cfg.algo.anneal_clip_coef:
                cfg.algo.clip_coef = polynomial_decay(
                    iter_num, initial=initial_clip_coef, final=0.0, max_decay_steps=total_iters, power=1.0
                )
            if cfg.algo.anneal_ent_coef:
                cfg.algo.ent_coef = polynomial_decay(
                    iter_num, initial=initial_ent_coef, final=0.0, max_decay_steps=total_iters, power=1.0
                )

            resilience.enforce_nonfinite_policy(ft, train_metrics)
            env_deltas = resilience.drain_env_counters(envs, aggregator)
            jax_compile.drain_compile_counters(aggregator)
            if iter_num == start_iter:
                # first iteration compiled every reachable signature for the
                # CURRENT bucket set; later buckets are legitimate first
                # compiles per signature, drift shows up as Compile/retraces
                jax_compile.mark_steady()

            # ----- health sentinel: warn -> backoff (lr_scale) -> rollback
            action = sentinel.observe(policy_step, train_metrics=train_metrics, env_counters=env_deltas)
            if action.rollback:
                rb_state = sentinel.take_rollback_state(os.path.join(log_dir, "checkpoint"))
                if rb_state is not None:
                    params = runtime.place_params(
                        jax.tree_util.tree_map(jnp.asarray, rb_state["agent"])
                    )
                    opt_state = runtime.place_params(
                        jax.tree_util.tree_map(jnp.asarray, rb_state["optimizer"])
                    )
                    if "rng" in rb_state:
                        rng = jnp.asarray(rb_state["rng"])
                        player_rng = jax.device_put(
                            jnp.asarray(rb_state["player_rng"]), runtime.player_device
                        )
                    player.params = params_sync.pull(params_sync.ravel(params), runtime.player_device)
                    if sentinel.reseed_envs:
                        # fresh episode streams AND a clean recurrent state: the
                        # in-flight hidden state was produced by the poisoned policy
                        pending.clear()
                        reset_obs = envs.reset(seed=cfg.seed + iter_num)[0]
                        next_obs = {}
                        for k in obs_keys:
                            _obs = reset_obs[k]
                            if k in cnn_keys:
                                _obs = _obs.reshape(n_envs, -1, *_obs.shape[-2:])
                            next_obs[k] = _obs
                            step_data[k] = _obs[np.newaxis]
                        prev_states = player.initial_states(h)
                        prev_actions = np.zeros((n_envs, sum(actions_dim)), dtype=np.float32)
                    runtime.print(
                        f"Health rollback at policy_step={policy_step}: restored certified "
                        "checkpoint, training continues."
                    )
            sentinel.drain(aggregator)

            if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
                iter_num == total_iters and cfg.checkpoint.save_last
            ):
                last_checkpoint = policy_step
                ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{runtime.global_rank}.ckpt")
                runtime.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=_ckpt_state(),
                    healthy=sentinel.certifiable,
                    policy_step=policy_step,
                )

            guard.completed_iteration()
            if guard.should_stop:
                if last_checkpoint != policy_step:  # periodic save above already covered this step
                    last_checkpoint = policy_step
                    ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{runtime.global_rank}.ckpt")
                    runtime.call(
                        "on_checkpoint_coupled",
                        ckpt_path=ckpt_path,
                        state=_ckpt_state(),
                        healthy=sentinel.certifiable,
                        policy_step=policy_step,
                    )
                runtime.print(
                    f"Preemption ({guard.describe()}) at iteration {iter_num}: emergency "
                    "checkpoint saved, exiting cleanly for resume."
                )
                break

    profiler.close()
    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        from sheeprl_tpu.algos.ppo_recurrent.utils import test

        test(player, runtime, cfg, log_dir)
    if logger:
        logger.finalize()
