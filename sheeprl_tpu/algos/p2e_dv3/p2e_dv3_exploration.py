"""Plan2Explore (DV3) — exploration phase (reference
sheeprl/algos/p2e_dv3/p2e_dv3_exploration.py:41-1059).

One jitted train call per iteration `lax.scan`s over the G gradient steps; each step
fuses (1) the DV3 world-model update — with the reward/continue heads trained on
DETACHED latents so task-reward gradients cannot shape the exploration-phase world
model (reference :154-161) — (2) the ensemble update (next-stochastic-state MSE
log-likelihood, reference :205-227), (3) the exploration actor with a *weighted set*
of two-hot exploration critics (intrinsic = ensemble-disagreement reward, task =
learned reward model; advantages normalized per-critic by its own Moments state and
weight-averaged, reference :259-305), (4) one two-hot critic update per exploration
critic with its EMA target regularizer (:344-369), and (5) the zero-shot task
actor/critic exactly as in DreamerV3 (:375-487). All EMA target updates run in-graph
via `lax.cond` on the step counter (replacing the reference's host-side copies,
:917-930).
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, NamedTuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.algos.dreamer_v3.agent import ActorOutput
from sheeprl_tpu.algos.dreamer_v3.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v3.utils import (
    get_action_masks,
    MomentsState,
    compute_lambda_values,
    init_moments,
    prepare_obs,
    test,
    update_moments,
)
from sheeprl_tpu.algos.p2e_dv3.agent import P2EDV3Modules, build_agent
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.factory import make_sequential_replay
from sheeprl_tpu.envs.wrappers import RestartOnException
from sheeprl_tpu.ops.distributions import (
    BernoulliSafeMode,
    Independent,
    MSEDistribution,
    OneHotCategorical,
    SymlogDistribution,
    TwoHotEncodingDistribution,
)
from sheeprl_tpu.core import resilience
from sheeprl_tpu.utils.env import finished_episodes, final_observations, make_env, vectorized_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.optim import with_clipping
from sheeprl_tpu.utils.profiler import TraceProfiler
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import PLAYER_WM_KEYS
from sheeprl_tpu.utils.utils import DreamerPlayerSync, Ratio, save_configs

from functools import partial


class P2EDV3OptStates(NamedTuple):
    world: Any
    ensembles: Any
    actor_task: Any
    critic_task: Any
    actor_exploration: Any
    critics_exploration: Dict[str, Any]


def make_train_fn(modules: P2EDV3Modules, cfg, runtime, is_continuous: bool, actions_dim, psync=None):
    """Build (init_opt, train): jitted G-step scan over the five P2E-DV3 updates.

    The moments argument/return is a dict ``{"task": MomentsState, <critic_key>:
    MomentsState, ...}`` — the per-critic percentile normalizers of the reference's
    ``moments_exploration``/``moments_task`` (p2e_dv3_exploration.py:660-675).
    """
    rssm = modules.rssm
    ensembles = modules.ensembles
    critics_spec = modules.critics_exploration  # {key: {weight, reward_type}} — static
    critic_keys = list(critics_spec.keys())
    weights_sum = sum(c["weight"] for c in critics_spec.values())
    horizon = int(cfg.algo.horizon)
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    ent_coef = float(cfg.algo.actor.ent_coef)
    kl_dynamic = float(cfg.algo.world_model.kl_dynamic)
    kl_representation = float(cfg.algo.world_model.kl_representation)
    kl_free_nats = float(cfg.algo.world_model.kl_free_nats)
    kl_regularizer = float(cfg.algo.world_model.kl_regularizer)
    continue_scale_factor = float(cfg.algo.world_model.continue_scale_factor)
    intrinsic_reward_multiplier = float(cfg.algo.intrinsic_reward_multiplier)
    stoch_size = rssm.stoch_state_size
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_keys_dec = list(cfg.algo.cnn_keys.decoder)
    mlp_keys_dec = list(cfg.algo.mlp_keys.decoder)
    target_freq = int(cfg.algo.critic.per_rank_target_network_update_freq)
    tau = float(cfg.algo.critic.tau)
    moments_cfg = cfg.algo.actor.moments
    actor_objective_mode = str(cfg.algo.actor.get("objective", "auto"))
    if actor_objective_mode not in ("auto", "reinforce"):
        raise ValueError(
            f"algo.actor.objective must be 'auto' or 'reinforce', got {actor_objective_mode!r}"
        )
    data_sharding = NamedSharding(runtime.mesh, P(None, "data"))

    world_tx = with_clipping(
        instantiate(dict(cfg.algo.world_model.optimizer))(), cfg.algo.world_model.clip_gradients
    )
    ens_tx = with_clipping(instantiate(dict(cfg.algo.ensembles.optimizer))(), cfg.algo.ensembles.clip_gradients)
    actor_tx = with_clipping(instantiate(dict(cfg.algo.actor.optimizer))(), cfg.algo.actor.clip_gradients)
    critic_tx = with_clipping(instantiate(dict(cfg.algo.critic.optimizer))(), cfg.algo.critic.clip_gradients)

    def init_opt(params) -> P2EDV3OptStates:
        return P2EDV3OptStates(
            world=world_tx.init(params["world_model"]),
            ensembles=ens_tx.init(params["ensembles"]),
            actor_task=actor_tx.init(params["actor_task"]),
            critic_task=critic_tx.init(params["critic_task"]),
            actor_exploration=actor_tx.init(params["actor_exploration"]),
            critics_exploration={
                k: critic_tx.init(params["critics_exploration"][k]["module"]) for k in critic_keys
            },
        )

    def init_moments_dict() -> Dict[str, MomentsState]:
        return {"task": init_moments(), **{k: init_moments() for k in critic_keys}}

    def ema(new_p, old_p, tau_eff):
        return jax.tree_util.tree_map(lambda p, tp: tau_eff * p + (1.0 - tau_eff) * tp, new_p, old_p)

    def norm_moments(key_name, moments, lambda_values):
        return update_moments(
            moments[key_name],
            lambda_values,
            decay=float(moments_cfg.decay),
            max_=float(moments_cfg.max),
            percentile_low=float(moments_cfg.percentile.low),
            percentile_high=float(moments_cfg.percentile.high),
        )

    def imagine(actor_mod, actor_params, wm_params, start_prior, start_recurrent, key0, keys):
        """H+1-step differentiable imagination (reference :259-283): actions come
        from the actor on the (detached) latent, then one RSSM imagination step."""
        latent0 = jnp.concatenate([start_prior, start_recurrent], axis=-1)
        out0 = ActorOutput(actor_mod, actor_mod.apply(actor_params, jax.lax.stop_gradient(latent0)))
        acts0, raws0 = out0.sample_actions_with_raw(key0)
        actions0 = jnp.concatenate(acts0, axis=-1)
        raw0 = jnp.concatenate(raws0, axis=-1)

        def step(carry, k):
            prior_flat, rec_state, act = carry
            k_img_step, k_act_step = jax.random.split(k)
            prior, rec_state = rssm.imagination_step(wm_params, prior_flat, rec_state, act, k_img_step)
            prior_flat = prior.reshape(prior_flat.shape)
            latent = jnp.concatenate([prior_flat, rec_state], axis=-1)
            out = ActorOutput(actor_mod, actor_mod.apply(actor_params, jax.lax.stop_gradient(latent)))
            new_acts, new_raws = out.sample_actions_with_raw(k_act_step)
            new_act = jnp.concatenate(new_acts, axis=-1)
            new_raw = jnp.concatenate(new_raws, axis=-1)
            return (prior_flat, rec_state, new_act), (latent, new_act, new_raw)

        _, (latents, acts, raws) = jax.lax.scan(step, (start_prior, start_recurrent, actions0), keys)
        trajectories = jnp.concatenate([latent0[None], latents], axis=0)  # [H+1, TB, L]
        im_actions = jnp.concatenate([actions0[None], acts], axis=0)  # [H+1, TB, A]
        im_actions_raw = jnp.concatenate([raw0[None], raws], axis=0)  # [H+1, TB, A]
        return trajectories, im_actions, im_actions_raw

    def actor_objective(actor_mod, actor_params, trajectories, im_actions_raw, advantage):
        policies = ActorOutput(
            actor_mod, actor_mod.apply(actor_params, jax.lax.stop_gradient(trajectories))
        )
        if is_continuous and actor_objective_mode != "reinforce":
            objective = advantage
        else:
            # score-function estimator at the RAW (pre-clip) samples — see
            # dreamer_v3.py and benchmarks/WALKER_WALK_NOTES.md
            splits = np.cumsum(np.asarray(actions_dim))[:-1]
            action_parts = jnp.split(jax.lax.stop_gradient(im_actions_raw), splits, axis=-1)
            log_probs = sum(d.log_prob(a) for d, a in zip(policies.dists, action_parts))  # [H+1, TB]
            objective = log_probs[..., None][:-1] * jax.lax.stop_gradient(advantage)
        try:
            entropy = ent_coef * policies.entropy()
        except NotImplementedError:
            entropy = jnp.zeros(trajectories.shape[:-1], dtype=jnp.float32)
        return objective, entropy

    def twohot_critic_loss(critic_mod, critic_params, target_params, trajectories, lambda_values, discount):
        """Two-hot critic regression onto λ-targets + EMA-target regularizer
        (reference :344-369 per exploration critic, :460-476 task)."""
        qv = TwoHotEncodingDistribution(critic_mod.apply(critic_params, trajectories[:-1]), dims=1)
        predicted_target_values = TwoHotEncodingDistribution(
            critic_mod.apply(target_params, trajectories[:-1]), dims=1
        ).mean
        value_loss = -qv.log_prob(lambda_values) - qv.log_prob(jax.lax.stop_gradient(predicted_target_values))
        return jnp.mean(value_loss * discount[:-1][..., 0])

    def one_step(carry, inp):
        params, opt_states, moments, counter = carry
        data, key = inp
        data = jax.tree_util.tree_map(lambda v: jax.lax.with_sharding_constraint(v, data_sharding), data)
        k_wm, k_expl0, k_expl, k_task0, k_task = jax.random.split(key, 5)

        # ---- EMA target critics (reference :917-930): tau=1 on the first step
        def do_ema(targets):
            tau_eff = jnp.where(counter == 0, 1.0, tau)
            new_task = ema(params["critic_task"], targets[0], tau_eff)
            new_expl = {
                k: ema(params["critics_exploration"][k]["module"], targets[1][k], tau_eff)
                for k in critic_keys
            }
            return (new_task, new_expl)

        old_targets = (
            params["target_critic_task"],
            {k: params["critics_exploration"][k]["target_module"] for k in critic_keys},
        )
        target_critic_task, target_critics_expl = jax.lax.cond(
            counter % target_freq == 0, do_ema, lambda t: t, old_targets
        )

        batch_obs = {k: data[k].astype(jnp.float32) / 255.0 - 0.5 for k in cnn_keys}
        batch_obs.update({k: data[k].astype(jnp.float32) for k in mlp_keys})
        is_first = data["is_first"].astype(jnp.float32).at[0].set(1.0)
        actions = data["actions"].astype(jnp.float32)
        batch_actions = jnp.concatenate([jnp.zeros_like(actions[:1]), actions[:-1]], axis=0)
        rewards = data["rewards"].astype(jnp.float32)
        continues_targets = 1.0 - data["terminated"].astype(jnp.float32)

        # ---- (1) world-model update; reward/continue heads on DETACHED latents
        # (reference :154-161)
        def world_loss_fn(wm_params):
            embedded = modules.encoder.apply(wm_params["encoder"], batch_obs)
            recurrent_states, posteriors, priors_logits, posteriors_logits = rssm.dynamic_scan(
                wm_params, embedded, batch_actions, is_first, k_wm
            )
            latent_states = jnp.concatenate(
                [posteriors.reshape(*posteriors.shape[:-2], -1), recurrent_states], axis=-1
            )
            reconstructed = modules.observation_model.apply(wm_params["observation_model"], latent_states)
            po_log_probs = {
                k: MSEDistribution(reconstructed[k], dims=reconstructed[k].ndim - 2).log_prob(batch_obs[k])
                for k in cnn_keys_dec
            }
            po_log_probs.update(
                {
                    k: SymlogDistribution(reconstructed[k], dims=reconstructed[k].ndim - 2).log_prob(batch_obs[k])
                    for k in mlp_keys_dec
                }
            )
            detached_latents = jax.lax.stop_gradient(latent_states)
            pr = TwoHotEncodingDistribution(
                modules.reward_model.apply(wm_params["reward_model"], detached_latents), dims=1
            )
            pc = Independent(
                BernoulliSafeMode(
                    logits=modules.continue_model.apply(wm_params["continue_model"], detached_latents)
                ),
                1,
            )
            loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
                po_log_probs,
                pr.log_prob(rewards),
                priors_logits,
                posteriors_logits,
                kl_dynamic,
                kl_representation,
                kl_free_nats,
                kl_regularizer,
                pc.log_prob(continues_targets),
                continue_scale_factor,
            )
            aux = {
                "posteriors": posteriors,
                "recurrent_states": recurrent_states,
                "priors_logits": priors_logits,
                "posteriors_logits": posteriors_logits,
                "kl": kl,
                "state_loss": state_loss,
                "reward_loss": reward_loss,
                "observation_loss": observation_loss,
                "continue_loss": continue_loss,
            }
            return loss, aux

        (world_loss, aux), world_grads = jax.value_and_grad(world_loss_fn, has_aux=True)(params["world_model"])
        world_grad_norm = optax.global_norm(world_grads)
        world_updates, world_opt = world_tx.update(world_grads, opt_states.world, params["world_model"])
        new_wm = optax.apply_updates(params["world_model"], world_updates)

        posteriors = jax.lax.stop_gradient(aux["posteriors"])  # [T, B, S, D]
        recurrent_states = jax.lax.stop_gradient(aux["recurrent_states"])  # [T, B, R]
        posteriors_flat = posteriors.reshape(*posteriors.shape[:-2], -1)

        # ---- (2) ensemble update: predict posterior[t+1] from (post, h, action)[t]
        # with an MSE head (reference :205-227); raw (unshifted) actions as input.
        ens_input = jnp.concatenate([posteriors_flat, recurrent_states, actions], axis=-1)

        def ensemble_loss_fn(ens_params):
            out = ensembles.apply(ens_params, ens_input)  # [N, T, B, S*D]
            if out.shape[1] < 2:
                # T == 1: there is no next-state target, and a mean over the empty
                # [:, :-1] slice would be NaN and poison every downstream param.
                return 0.0 * jnp.sum(out)
            out = out[:, :-1]  # [N, T-1, B, S*D]
            target = jnp.broadcast_to(posteriors_flat[None, 1:], out.shape)
            log_prob = MSEDistribution(out, dims=1).log_prob(target)
            return -(log_prob.mean(axis=(1, 2)).sum())

        ens_loss, ens_grads = jax.value_and_grad(ensemble_loss_fn)(params["ensembles"])
        ens_grad_norm = optax.global_norm(ens_grads)
        ens_updates, ens_opt = ens_tx.update(ens_grads, opt_states.ensembles, params["ensembles"])
        new_ens = optax.apply_updates(params["ensembles"], ens_updates)

        start_prior = posteriors_flat.reshape(1, -1, stoch_size)[0]  # [T*B, S*D]
        start_recurrent = recurrent_states.reshape(1, -1, recurrent_states.shape[-1])[0]
        true_continue = continues_targets.reshape(-1, 1)  # [T*B, 1]
        expl_keys = jax.random.split(k_expl, horizon)
        task_keys = jax.random.split(k_task, horizon)

        # ---- (3) exploration actor on the weighted multi-critic advantage
        # (reference :259-333)
        def actor_expl_loss_fn(actor_params):
            trajectories, im_actions, im_actions_raw = imagine(
                modules.actor_exploration, actor_params, new_wm, start_prior, start_recurrent, k_expl0, expl_keys
            )
            continues = Independent(
                BernoulliSafeMode(logits=modules.continue_model.apply(new_wm["continue_model"], trajectories)), 1
            ).base.mode
            continues = jnp.concatenate([true_continue[None], continues[1:]], axis=0)
            discount = jax.lax.stop_gradient(jnp.cumprod(continues * gamma, axis=0) / gamma)

            # Intrinsic (disagreement) reward is shared by every intrinsic critic
            ens_in = jax.lax.stop_gradient(jnp.concatenate([trajectories, im_actions], axis=-1))
            ens_preds = ensembles.apply(new_ens, ens_in)  # [N, H+1, TB, S*D]
            intrinsic_reward = (
                ens_preds.var(axis=0).mean(axis=-1, keepdims=True) * intrinsic_reward_multiplier
            )
            extrinsic_reward = TwoHotEncodingDistribution(
                modules.reward_model.apply(new_wm["reward_model"], trajectories), dims=1
            ).mean

            advantage = 0.0
            new_moments = {}
            per_critic = {}
            for k in critic_keys:
                spec = critics_spec[k]
                predicted_values = TwoHotEncodingDistribution(
                    modules.critic_exploration.apply(params["critics_exploration"][k]["module"], trajectories),
                    dims=1,
                ).mean
                reward = intrinsic_reward if spec["reward_type"] == "intrinsic" else extrinsic_reward
                lambda_values = compute_lambda_values(
                    reward[1:], predicted_values[1:], continues[1:] * gamma, lmbda=lmbda
                )
                offset, invscale, new_moments[k] = norm_moments(k, moments, lambda_values)
                normed_lambda = (lambda_values - offset) / invscale
                normed_baseline = (predicted_values[:-1] - offset) / invscale
                advantage = advantage + (normed_lambda - normed_baseline) * (spec["weight"] / weights_sum)
                per_critic[k] = {
                    "lambda_values": lambda_values,
                    "predicted_values": predicted_values,
                }

            objective, entropy = actor_objective(
                modules.actor_exploration, actor_params, trajectories, im_actions_raw, advantage
            )
            p_loss = -jnp.mean(jax.lax.stop_gradient(discount[:-1]) * (objective + entropy[..., None][:-1]))
            aux_e = {
                "trajectories": trajectories,
                "discount": discount,
                "moments": new_moments,
                "per_critic": per_critic,
                "intrinsic_reward": intrinsic_reward,
            }
            return p_loss, aux_e

        (policy_loss_expl, aux_e), actor_expl_grads = jax.value_and_grad(actor_expl_loss_fn, has_aux=True)(
            params["actor_exploration"]
        )
        actor_expl_gn = optax.global_norm(actor_expl_grads)
        actor_expl_updates, actor_expl_opt = actor_tx.update(
            actor_expl_grads, opt_states.actor_exploration, params["actor_exploration"]
        )
        new_actor_expl = optax.apply_updates(params["actor_exploration"], actor_expl_updates)

        # ---- (4) per-key exploration critic updates on the detached trajectories
        expl_traj = jax.lax.stop_gradient(aux_e["trajectories"])
        expl_discount = aux_e["discount"]
        new_critics_expl: Dict[str, Dict[str, Any]] = {}
        new_critics_opt: Dict[str, Any] = {}
        value_losses_expl = {}
        critic_expl_gns = {}
        for k in critic_keys:
            lam_k = jax.lax.stop_gradient(aux_e["per_critic"][k]["lambda_values"])
            loss_fn = partial(
                twohot_critic_loss,
                modules.critic_exploration,
                target_params=target_critics_expl[k],
                trajectories=expl_traj,
                lambda_values=lam_k,
                discount=expl_discount,
            )
            v_loss, c_grads = jax.value_and_grad(lambda p: loss_fn(p))(params["critics_exploration"][k]["module"])
            critic_expl_gns[k] = optax.global_norm(c_grads)
            c_updates, c_opt = critic_tx.update(
                c_grads, opt_states.critics_exploration[k], params["critics_exploration"][k]["module"]
            )
            new_critics_expl[k] = {
                "module": optax.apply_updates(params["critics_exploration"][k]["module"], c_updates),
                "target_module": target_critics_expl[k],
            }
            new_critics_opt[k] = c_opt
            value_losses_expl[k] = v_loss

        # ---- (5) zero-shot task behaviour, exactly DreamerV3 (reference :375-487)
        def actor_task_loss_fn(actor_params):
            trajectories, im_actions, im_actions_raw = imagine(
                modules.actor_task, actor_params, new_wm, start_prior, start_recurrent, k_task0, task_keys
            )
            predicted_values = TwoHotEncodingDistribution(
                modules.critic_task.apply(params["critic_task"], trajectories), dims=1
            ).mean
            predicted_rewards = TwoHotEncodingDistribution(
                modules.reward_model.apply(new_wm["reward_model"], trajectories), dims=1
            ).mean
            continues = Independent(
                BernoulliSafeMode(logits=modules.continue_model.apply(new_wm["continue_model"], trajectories)), 1
            ).base.mode
            continues = jnp.concatenate([true_continue[None], continues[1:]], axis=0)
            lambda_values = compute_lambda_values(
                predicted_rewards[1:], predicted_values[1:], continues[1:] * gamma, lmbda=lmbda
            )
            discount = jax.lax.stop_gradient(jnp.cumprod(continues * gamma, axis=0) / gamma)
            offset, invscale, new_task_moments = norm_moments("task", moments, lambda_values)
            advantage = (lambda_values - offset) / invscale - (predicted_values[:-1] - offset) / invscale
            objective, entropy = actor_objective(
                modules.actor_task, actor_params, trajectories, im_actions_raw, advantage
            )
            p_loss = -jnp.mean(jax.lax.stop_gradient(discount[:-1]) * (objective + entropy[..., None][:-1]))
            aux_t = {
                "trajectories": trajectories,
                "lambda_values": lambda_values,
                "discount": discount,
                "moments": new_task_moments,
            }
            return p_loss, aux_t

        (policy_loss_task, aux_t), actor_task_grads = jax.value_and_grad(actor_task_loss_fn, has_aux=True)(
            params["actor_task"]
        )
        actor_task_gn = optax.global_norm(actor_task_grads)
        actor_task_updates, actor_task_opt = actor_tx.update(
            actor_task_grads, opt_states.actor_task, params["actor_task"]
        )
        new_actor_task = optax.apply_updates(params["actor_task"], actor_task_updates)

        task_traj = jax.lax.stop_gradient(aux_t["trajectories"])
        task_lambda = jax.lax.stop_gradient(aux_t["lambda_values"])
        value_loss_task, critic_task_grads = jax.value_and_grad(
            lambda p: twohot_critic_loss(
                modules.critic_task, p, target_critic_task, task_traj, task_lambda, aux_t["discount"]
            )
        )(params["critic_task"])
        critic_task_gn = optax.global_norm(critic_task_grads)
        critic_task_updates, critic_task_opt = critic_tx.update(
            critic_task_grads, opt_states.critic_task, params["critic_task"]
        )
        new_critic_task = optax.apply_updates(params["critic_task"], critic_task_updates)

        post_ent = Independent(OneHotCategorical(logits=aux["posteriors_logits"]), 1).entropy().mean()
        prior_ent = Independent(OneHotCategorical(logits=aux["priors_logits"]), 1).entropy().mean()

        new_params = {
            "world_model": new_wm,
            "ensembles": new_ens,
            "actor_task": new_actor_task,
            "critic_task": new_critic_task,
            "target_critic_task": target_critic_task,
            "actor_exploration": new_actor_expl,
            "critics_exploration": new_critics_expl,
        }
        new_opt = P2EDV3OptStates(
            world=world_opt,
            ensembles=ens_opt,
            actor_task=actor_task_opt,
            critic_task=critic_task_opt,
            actor_exploration=actor_expl_opt,
            critics_exploration=new_critics_opt,
        )
        new_moments = {"task": aux_t["moments"], **aux_e["moments"]}
        metrics = {
            "Loss/world_model_loss": world_loss,
            "Loss/observation_loss": aux["observation_loss"],
            "Loss/reward_loss": aux["reward_loss"],
            "Loss/state_loss": aux["state_loss"],
            "Loss/continue_loss": aux["continue_loss"],
            "State/kl": aux["kl"],
            "State/post_entropy": post_ent,
            "State/prior_entropy": prior_ent,
            "Loss/ensemble_loss": ens_loss,
            "Loss/policy_loss_exploration": policy_loss_expl,
            "Loss/policy_loss_task": policy_loss_task,
            "Loss/value_loss_task": value_loss_task,
            "Grads/world_model": world_grad_norm,
            "Grads/ensemble": ens_grad_norm,
            "Grads/actor_exploration": actor_expl_gn,
            "Grads/actor_task": actor_task_gn,
            "Grads/critic_task": critic_task_gn,
        }
        for k in critic_keys:
            metrics[f"Loss/value_loss_exploration_{k}"] = value_losses_expl[k]
            metrics[f"Values_exploration/predicted_values_{k}"] = aux_e["per_critic"][k][
                "predicted_values"
            ].mean()
            metrics[f"Values_exploration/lambda_values_{k}"] = aux_e["per_critic"][k]["lambda_values"].mean()
            metrics[f"Grads/critic_exploration_{k}"] = critic_expl_gns[k]
            if critics_spec[k]["reward_type"] == "intrinsic":
                metrics[f"Rewards/intrinsic_{k}"] = aux_e["intrinsic_reward"].mean()
        return (new_params, new_opt, new_moments, counter + 1), metrics

    def train(params, opt_states, moments, counter, batches, key):
        g = next(iter(batches.values())).shape[0]
        keys = jax.random.split(key, g)
        (params, opt_states, moments, counter), metrics = jax.lax.scan(
            one_step, (params, opt_states, moments, counter), (batches, keys)
        )
        named = {k: v.mean(axis=0) for k, v in metrics.items()}
        # raveled player subset computed in-graph (one flat host-player transfer)
        flat_player = psync.ravel(params) if psync is not None else None
        return params, opt_states, moments, counter, flat_player, named

    return init_opt, init_moments_dict, jax_compile.guarded_jit(train, name="p2e_dv3.train", donate_argnums=(0, 1, 2))


def expand_critic_metric_keys(cfg, critics_spec) -> None:
    """Clone the generic exploration-critic metric specs into per-key specs
    (reference p2e_dv3_exploration.py:679-708). ``Rewards/intrinsic`` is only
    cloned for intrinsic-reward critics — the train step never emits it for
    task-reward ones."""
    if "aggregator" not in cfg.metric or "metrics" not in cfg.metric.aggregator:
        return
    metrics_cfg = cfg.metric.aggregator.metrics
    generic = [
        "Loss/value_loss_exploration",
        "Values_exploration/predicted_values",
        "Values_exploration/lambda_values",
        "Grads/critic_exploration",
    ]
    for k, spec in critics_spec.items():
        for g in generic:
            if g in metrics_cfg:
                metrics_cfg[f"{g}_{k}"] = metrics_cfg[g]
        if spec["reward_type"] == "intrinsic" and "Rewards/intrinsic" in metrics_cfg:
            metrics_cfg[f"Rewards/intrinsic_{k}"] = metrics_cfg["Rewards/intrinsic"]
    for g in generic + ["Rewards/intrinsic"]:
        metrics_cfg.pop(g, None)


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    world_size = runtime.world_size
    rank = runtime.global_rank

    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_state

        state = load_state(cfg.checkpoint.resume_from)

    # These arguments cannot be changed (reference p2e_dv3_exploration.py:540-542)
    cfg.env.frame_stack = 1
    cfg.algo.player.actor_type = "exploration"
    if 2 ** int(np.log2(cfg.env.screen_size)) != cfg.env.screen_size:
        raise ValueError(f"The screen size must be a power of 2, got: {cfg.env.screen_size}")

    logger = get_logger(runtime, cfg)
    if logger:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.logger = logger
    runtime.print(f"Log dir: {log_dir}")

    ft = resilience.resolve(cfg)
    env_fns = [
        make_env(
            cfg,
            cfg.seed + rank * cfg.env.num_envs + i,
            rank * cfg.env.num_envs,
            log_dir if runtime.is_global_zero else None,
            "train",
            vector_env_idx=i,
        )
        for i in range(cfg.env.num_envs)
    ]
    if ft.env_supervision.enabled:
        # WorkerSupervisor supersedes RestartOnException (same restart-on-crash
        # semantics plus bounded backoff, hang detection, and restart counters)
        envs = resilience.make_supervised_env(env_fns, sync=cfg.env.sync_env, ft=ft)
    else:
        envs = vectorized_env(
            [partial(RestartOnException, fn) for fn in env_fns],
            sync=cfg.env.sync_env,
            step_timeout=ft.env_supervision.step_timeout_s,
        )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if (
        len(set(cfg.algo.cnn_keys.encoder).intersection(set(cfg.algo.cnn_keys.decoder))) == 0
        and len(set(cfg.algo.mlp_keys.encoder).intersection(set(cfg.algo.mlp_keys.decoder))) == 0
    ):
        raise RuntimeError("The CNN keys or the MLP keys of the encoder and decoder must not be disjointed")
    if len(set(cfg.algo.cnn_keys.decoder) - set(cfg.algo.cnn_keys.encoder)) > 0:
        raise RuntimeError(
            "The CNN keys of the decoder must be contained in the encoder ones. "
            f"Those keys are decoded without being encoded: {list(set(cfg.algo.cnn_keys.decoder))}"
        )
    if len(set(cfg.algo.mlp_keys.decoder) - set(cfg.algo.mlp_keys.encoder)) > 0:
        raise RuntimeError(
            "The MLP keys of the decoder must be contained in the encoder ones. "
            f"Those keys are decoded without being encoded: {list(set(cfg.algo.mlp_keys.decoder))}"
        )
    obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)

    modules, params, player = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["world_model"] if state else None,
        state["ensembles"] if state else None,
        state["actor_task"] if state else None,
        state["critic_task"] if state else None,
        state["target_critic_task"] if state else None,
        state["actor_exploration"] if state else None,
        state["critics_exploration"] if state else None,
    )
    critic_keys = list(modules.critics_exploration.keys())
    expand_critic_metric_keys(cfg, modules.critics_exploration)

    psync = DreamerPlayerSync(
        runtime,
        params,
        wm_keys=PLAYER_WM_KEYS,
        actor_name="actor_exploration",
        every=cfg.algo.get("player_sync_every", 1),
    )
    init_opt, init_moments_dict, train_fn = make_train_fn(
        modules, cfg, runtime, is_continuous, actions_dim, psync
    )
    opt_states = init_opt(params)
    if state:
        opt_states = jax.tree_util.tree_map(jnp.asarray, state["opt_states"])
    moments = init_moments_dict()
    if state and "moments_task" in state:
        moments["task"] = MomentsState(*[jnp.asarray(v) for v in state["moments_task"]])
        for k in critic_keys:
            if f"moments_exploration_{k}" in state:
                moments[k] = MomentsState(*[jnp.asarray(v) for v in state[f"moments_exploration_{k}"]])
    counter = jnp.int32(state["counter"]) if state and "counter" in state else jnp.int32(0)
    params = runtime.place_params(params)
    opt_states = runtime.place_params(opt_states)
    # the player must never hold mesh-resident params when it lives on the host
    # CPU backend: its per-step calls would pay per-leaf cross-backend pulls
    psync.push(player, params, force=True)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    rb, prefetcher = make_sequential_replay(cfg, runtime, log_dir, obs_keys)
    if state and cfg.buffer.checkpoint and "rb" in state:
        rb.load_state_dict(state["rb"])

    train_step = 0
    last_train = 0
    start_iter = (state["iter_num"] // world_size) + 1 if state else 1
    policy_step = state["iter_num"] * cfg.env.num_envs if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(cfg.env.num_envs * world_size)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state:
        ratio.load_state_dict(state["ratio"])

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    profiler = TraceProfiler(cfg.metric.get("profiler"), log_dir if runtime.is_global_zero else None)
    rng = jax.random.PRNGKey(cfg.seed)
    step_data: Dict[str, np.ndarray] = {}

    obs = envs.reset(seed=cfg.seed)[0]
    for k in obs_keys:
        step_data[k] = np.asarray(obs[k])[np.newaxis]
    step_data["rewards"] = np.zeros((1, cfg.env.num_envs, 1))
    step_data["truncated"] = np.zeros((1, cfg.env.num_envs, 1))
    step_data["terminated"] = np.zeros((1, cfg.env.num_envs, 1))
    step_data["is_first"] = np.ones_like(step_data["terminated"])
    player.init_states()

    cumulative_per_rank_gradient_steps = 0
    for iter_num in range(start_iter, total_iters + 1):
        profiler.step(policy_step)
        policy_step += policy_steps_per_iter

        with timer("Time/env_interaction_time", SumMetric()):
            if iter_num <= learning_starts and state is None and "minedojo" not in cfg.env.wrapper._target_.lower():
                real_actions = actions = np.array(envs.action_space.sample())
                if not is_continuous:
                    actions = np.concatenate(
                        [
                            np.eye(act_dim, dtype=np.float32)[act.reshape(-1)]
                            for act, act_dim in zip(actions.reshape(len(actions_dim), -1), actions_dim)
                        ],
                        axis=-1,
                    )
            else:
                jax_obs = prepare_obs(runtime, obs, cnn_keys=cfg.algo.cnn_keys.encoder, num_envs=cfg.env.num_envs)
                mask = get_action_masks(jax_obs)
                rng, act_key = jax.random.split(rng)
                actions_list = player.get_actions(jax_obs, act_key, mask=mask)
                actions = np.concatenate([np.asarray(a) for a in actions_list], axis=-1)
                if is_continuous:
                    real_actions = actions
                else:
                    real_actions = np.stack([np.asarray(a).argmax(axis=-1) for a in actions_list], axis=-1)

            step_data["actions"] = actions.reshape((1, cfg.env.num_envs, -1))
            with prefetcher.guard():  # no torn rows under the worker's sample
                rb.add(step_data, validate_args=cfg.buffer.validate_args)

            next_obs, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
            dones = np.logical_or(terminated, truncated).astype(np.uint8)

        step_data["is_first"] = np.zeros_like(step_data["terminated"])
        if "restart_on_exception" in infos:
            for i, agent_roe in enumerate(infos["restart_on_exception"]):
                if agent_roe and not dones[i]:
                    # crash-restart boundary: the last stored transition becomes a
                    # truncation (works on host and HBM buffers alike)
                    with prefetcher.guard():  # no torn flags under the worker's sample
                        rb.patch_last([i], {"terminated": 0.0, "truncated": 1.0, "is_first": 0.0})
                    step_data["is_first"][0, i] = np.ones_like(step_data["is_first"][0, i])

        if cfg.metric.log_level > 0:
            for i, (ep_rew, ep_len) in enumerate(finished_episodes(infos)):
                if aggregator:
                    if "Rewards/rew_avg" in aggregator:
                        aggregator.update("Rewards/rew_avg", ep_rew)
                    if "Game/ep_len_avg" in aggregator:
                        aggregator.update("Game/ep_len_avg", ep_len)
                runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")

        real_next_obs = {k: np.asarray(v).copy() for k, v in next_obs.items() if k in obs_keys}
        finals = final_observations(infos, obs_keys)
        if finals:
            for idx, final_obs in finals.items():
                for k, v in final_obs.items():
                    real_next_obs[k][idx] = v

        for k in obs_keys:
            step_data[k] = np.asarray(next_obs[k])[np.newaxis]
        obs = next_obs

        rewards = np.asarray(rewards, dtype=np.float32).reshape((1, cfg.env.num_envs, -1))
        step_data["terminated"] = np.asarray(terminated, dtype=np.float32).reshape((1, cfg.env.num_envs, -1))
        step_data["truncated"] = np.asarray(truncated, dtype=np.float32).reshape((1, cfg.env.num_envs, -1))
        step_data["rewards"] = clip_rewards_fn(rewards)

        dones_idxes = dones.nonzero()[0].tolist()
        reset_envs = len(dones_idxes)
        if reset_envs > 0:
            reset_data = {}
            for k in obs_keys:
                reset_data[k] = (real_next_obs[k][dones_idxes])[np.newaxis]
            reset_data["terminated"] = step_data["terminated"][:, dones_idxes]
            reset_data["truncated"] = step_data["truncated"][:, dones_idxes]
            reset_data["actions"] = np.zeros((1, reset_envs, int(np.sum(actions_dim))))
            reset_data["rewards"] = step_data["rewards"][:, dones_idxes]
            reset_data["is_first"] = np.zeros_like(reset_data["terminated"])
            with prefetcher.guard():  # no torn rows under the worker's sample
                rb.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)

            step_data["rewards"][:, dones_idxes] = np.zeros_like(reset_data["rewards"])
            step_data["terminated"][:, dones_idxes] = np.zeros_like(step_data["terminated"][:, dones_idxes])
            step_data["truncated"][:, dones_idxes] = np.zeros_like(step_data["truncated"][:, dones_idxes])
            step_data["is_first"][:, dones_idxes] = np.ones_like(step_data["is_first"][:, dones_idxes])
            player.init_states(dones_idxes)

        if iter_num >= learning_starts:
            ratio_steps = policy_step - prefill_steps * policy_steps_per_iter
            per_rank_gradient_steps = ratio(ratio_steps / world_size)
            if per_rank_gradient_steps > 0:
                # consumes the batch prefetched during the previous train step and
                # immediately speculates the next one
                batches = prefetcher.get(
                    batch_size=cfg.algo.per_rank_batch_size * world_size,
                    sequence_length=cfg.algo.per_rank_sequence_length,
                    n_samples=per_rank_gradient_steps,
                )
                with timer("Time/train_time", SumMetric()):
                    rng, train_key = jax.random.split(rng)
                    params, opt_states, moments, counter, flat_player, train_metrics = train_fn(
                        params, opt_states, moments, counter, batches, train_key
                    )
                    if not timer.disabled:
                        # fence ONLY when timing (Time/train_time honesty); an
                        # unconditional sync serializes on the dispatch round-trip
                        jax.block_until_ready(params)
                    psync.push(player, params, flat=flat_player)
                    cumulative_per_rank_gradient_steps += per_rank_gradient_steps
                    train_step += world_size * per_rank_gradient_steps
                if aggregator:
                    aggregator.update_from_device(train_metrics)

        if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters):
            if aggregator and not aggregator.disabled:
                logger.log_metrics(aggregator.compute(), policy_step)
                aggregator.reset()
            if logger and policy_step > 0:
                logger.log_metrics(
                    {"Params/replay_ratio": cumulative_per_rank_gradient_steps * world_size / policy_step},
                    policy_step,
                )
            if not timer.disabled:
                timer_metrics = timer.compute()
                if logger and timer_metrics.get("Time/train_time", 0) > 0:
                    logger.log_metrics(
                        {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                        policy_step,
                    )
                if logger and timer_metrics.get("Time/env_interaction_time", 0) > 0:
                    logger.log_metrics(
                        {
                            "Time/sps_env_interaction": (
                                (policy_step - last_log) / world_size * cfg.env.action_repeat
                            )
                            / timer_metrics["Time/env_interaction_time"]
                        },
                        policy_step,
                    )
                timer.reset()
            last_log = policy_step
            last_train = train_step

        resilience.drain_env_counters(envs, aggregator)
        jax_compile.drain_compile_counters(aggregator)
        if cumulative_per_rank_gradient_steps > 0 and not jax_compile.is_steady():
            # everything reachable has compiled once: later traces are drift
            jax_compile.mark_steady()

        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            iter_num == total_iters and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "world_model": jax.device_get(params["world_model"]),
                "ensembles": jax.device_get(params["ensembles"]),
                "actor_task": jax.device_get(params["actor_task"]),
                "critic_task": jax.device_get(params["critic_task"]),
                "target_critic_task": jax.device_get(params["target_critic_task"]),
                "actor_exploration": jax.device_get(params["actor_exploration"]),
                "critics_exploration": jax.device_get(params["critics_exploration"]),
                "opt_states": jax.device_get(opt_states),
                "moments_task": tuple(np.asarray(v) for v in moments["task"]),
                **{
                    f"moments_exploration_{k}": tuple(np.asarray(v) for v in moments[k])
                    for k in critic_keys
                },
                "counter": int(counter),
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{rank}.ckpt")
            runtime.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
                io_lock=prefetcher.guard(),
            )

    profiler.close()
    prefetcher.close()
    envs.close()
    # Zero-shot evaluation runs with the TASK policy (reference :1032-1036).
    if runtime.is_global_zero and cfg.algo.run_test:
        player.actor = modules.actor_task
        # zero-shot eval swaps in the TASK actor: ship a coherent (wm, actor)
        # pair to the player device rather than mixing backends
        psync_task = DreamerPlayerSync(runtime, params, wm_keys=PLAYER_WM_KEYS, actor_name="actor_task")
        psync_task.push(player, params, force=True)
        player.actor_type = "task"
        test(player, runtime, cfg, log_dir, "zero-shot", greedy=False)
    if logger:
        logger.finalize()
