"""P2E-DV3 utilities (reference sheeprl/algos/p2e_dv3/utils.py)."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.dreamer_v3.utils import AGGREGATOR_KEYS as AGGREGATOR_KEYS_DV3
from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs, test  # noqa: F401

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/policy_loss_task",
    "Loss/value_loss_task",
    "Loss/policy_loss_exploration",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "Loss/ensemble_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Grads/world_model",
    "Grads/actor_task",
    "Grads/critic_task",
    "Grads/actor_exploration",
    "Grads/ensemble",
    # General key names for the exploration critics; the exploration entrypoint
    # clones them into per-critic-key variants (reference utils.py:38-44).
    "Loss/value_loss_exploration",
    "Values_exploration/predicted_values",
    "Values_exploration/lambda_values",
    "Grads/critic_exploration",
    "Rewards/intrinsic",
}.union(AGGREGATOR_KEYS_DV3)
MODELS_TO_REGISTER = {
    "world_model",
    "ensembles",
    "actor_exploration",
    "critic_exploration_intrinsic",
    "target_critic_exploration_intrinsic",
    "moments_exploration_intrinsic",
    "critic_exploration_extrinsic",
    "target_critic_exploration_extrinsic",
    "moments_exploration_extrinsic",
    "actor_task",
    "critic_task",
    "target_critic_task",
    "moments_task",
}


def log_models_from_checkpoint(runtime, env, cfg, state) -> Dict[str, Any]:
    """Register P2E-DV3 models from a checkpoint (reference utils.py:62-148).

    Exploration checkpoints carry every model (incl. the per-key exploration
    critics and their Moments); finetuning checkpoints carry the task quadruple +
    world model + exploration actor.
    """
    import gymnasium as gym

    from sheeprl_tpu.algos.p2e_dv3.agent import build_agent
    from sheeprl_tpu.utils.model_manager import log_model

    is_continuous = isinstance(env.action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(env.action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        env.action_space.shape
        if is_continuous
        else (env.action_space.nvec.tolist() if is_multidiscrete else [env.action_space.n])
    )
    exploration = "exploration" in cfg.algo.name
    _, params, _ = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        env.observation_space,
        state["world_model"],
        state["ensembles"] if exploration else None,
        state["actor_task"],
        state["critic_task"],
        state["target_critic_task"],
        state["actor_exploration"] if "actor_exploration" in state else None,
        state["critics_exploration"] if exploration else None,
    )
    info = {}
    for name in ("world_model", "actor_task", "critic_task", "target_critic_task"):
        info[name] = log_model(runtime, cfg, name, params[name])
    info["moments_task"] = log_model(runtime, cfg, "moments_task", state.get("moments_task"))
    if exploration:
        info["ensembles"] = log_model(runtime, cfg, "ensembles", params["ensembles"])
        info["actor_exploration"] = log_model(runtime, cfg, "actor_exploration", params["actor_exploration"])
        for k, cp in params["critics_exploration"].items():
            info[f"critic_exploration_{k}"] = log_model(runtime, cfg, f"critic_exploration_{k}", cp["module"])
            info[f"target_critic_exploration_{k}"] = log_model(
                runtime, cfg, f"target_critic_exploration_{k}", cp["target_module"]
            )
            info[f"moments_exploration_{k}"] = log_model(
                runtime, cfg, f"moments_exploration_{k}", state.get(f"moments_exploration_{k}")
            )
    return info
