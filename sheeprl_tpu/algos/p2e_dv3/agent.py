"""Plan2Explore (DV3) agent: DV3 world model + task actor-critic pair (with EMA
target critic) + exploration actor with a config-declared *set* of weighted
exploration critics (each a two-hot head with its own EMA target), plus an
ensemble of next-stochastic-state predictors.

Parity target: reference sheeprl/algos/p2e_dv3/agent.py:27-223 (build_agent
returning world model, ensembles, actor_task, critic_task, target_critic_task,
actor_exploration, critics_exploration dict, player).

TPU-first design: the ensemble is ONE module with vmapped stacked params (see
p2e_dv1.agent.Ensembles) — all N members run as one batched matmul set on the MXU
instead of the reference's Python loop over an ``nn.ModuleList``. The exploration
critics are kept as parallel param dicts keyed like the reference's
``cfg.algo.critics_exploration`` mapping so checkpoints keep the same shape
(``critics_exploration -> {key: {module, target_module}}``).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import gymnasium
import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v3.agent import (
    Actor as DV3Actor,
    DV3Modules,
    MinedojoActor as DV3MinedojoActor,
    MLPWithHead,
    MultiDecoderDV3,
    MultiEncoderDV3,
    PlayerDV3,
    RSSM,
    build_agent as dv3_build_agent,
)
from sheeprl_tpu.algos.dreamer_v3.agent import _ln_enabled
from sheeprl_tpu.algos.p2e_dv1.agent import Ensembles
from sheeprl_tpu.utils.utils import resolve_actor_cls

# Exposed for config-driven class selection (reference p2e_dv3/agent.py:23-24).
Actor = DV3Actor
MinedojoActor = DV3MinedojoActor


class P2EDV3Modules(NamedTuple):
    encoder: MultiEncoderDV3
    rssm: RSSM
    observation_model: MultiDecoderDV3
    reward_model: MLPWithHead
    continue_model: MLPWithHead
    ensembles: Ensembles
    actor_task: DV3Actor
    critic_task: MLPWithHead
    actor_exploration: DV3Actor
    critic_exploration: MLPWithHead  # shared module definition for every exploration critic
    critics_exploration: Dict[str, Dict[str, Any]]  # {key: {weight, reward_type}}

    def as_dv3(self, task: bool) -> DV3Modules:
        """View as a DV3Modules using the task behaviour pair.

        Only ``task=True`` is representable: the exploration behaviour has a
        *set* of critics, which does not fit ``DV3Modules.critic``.
        """
        if not task:
            raise ValueError(
                "P2EDV3Modules.as_dv3(task=False) is unsupported: the exploration "
                "behaviour uses multiple weighted critics (cfg.algo.critics_exploration)"
            )
        return DV3Modules(
            encoder=self.encoder,
            rssm=self.rssm,
            observation_model=self.observation_model,
            reward_model=self.reward_model,
            continue_model=self.continue_model,
            actor=self.actor_task,
            critic=self.critic_task,
        )


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Dict[str, Any]] = None,
    ensembles_state: Optional[Any] = None,
    actor_task_state: Optional[Dict[str, Any]] = None,
    critic_task_state: Optional[Dict[str, Any]] = None,
    target_critic_task_state: Optional[Dict[str, Any]] = None,
    actor_exploration_state: Optional[Dict[str, Any]] = None,
    critics_exploration_state: Optional[Dict[str, Dict[str, Any]]] = None,
) -> Tuple[P2EDV3Modules, Dict[str, Any], PlayerDV3]:
    """Build P2E-DV3 modules + params (reference p2e_dv3/agent.py:27-223).

    ``params`` keys: world_model, ensembles, actor_task, critic_task,
    target_critic_task, actor_exploration, critics_exploration (a dict
    ``{key: {"module": params, "target_module": params}}`` mirroring the
    reference checkpoint layout).
    """
    world_model_cfg = cfg.algo.world_model
    actor_cfg = cfg.algo.actor
    critic_cfg = cfg.algo.critic
    stochastic_size = int(world_model_cfg.stochastic_size) * int(world_model_cfg.discrete_size)
    latent_state_size = stochastic_size + int(world_model_cfg.recurrent_model.recurrent_state_size)
    compute_dtype = runtime.compute_dtype

    # Task models are exactly DV3's (reference p2e_dv3/agent.py:95-105)
    dv3_modules, dv3_params, player = dv3_build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        world_model_state,
        actor_task_state,
        critic_task_state,
        target_critic_task_state,
    )
    player.actor_type = cfg.algo.player.actor_type

    actor_ln, actor_eps = _ln_enabled(actor_cfg.get("layer_norm"))
    expl_actor_cls = resolve_actor_cls(actor_cfg.get("cls"), Actor, MinedojoActor)
    actor_exploration = expl_actor_cls(
        latent_state_size=latent_state_size,
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=cfg.distribution.get("type", "auto"),
        init_std=float(actor_cfg.init_std),
        min_std=float(actor_cfg.min_std),
        max_std=float(actor_cfg.get("max_std", 1.0)),
        dense_units=int(actor_cfg.dense_units),
        mlp_layers=int(actor_cfg.mlp_layers),
        layer_norm=actor_ln,
        layer_norm_eps=actor_eps,
        activation=actor_cfg.dense_act,
        unimix=float(cfg.algo.unimix),
        action_clip=float(actor_cfg.get("action_clip", 1.0)),
        dtype=compute_dtype,
    )

    # Exploration critics: one two-hot head per enabled entry of
    # cfg.algo.critics_exploration (reference p2e_dv3/agent.py:119-154). All of
    # them share the same module *definition*; parameters are per-key.
    critic_ln, critic_eps = _ln_enabled(critic_cfg.get("layer_norm"))
    critic_exploration = MLPWithHead(
        input_dim=latent_state_size,
        hidden_sizes=[int(critic_cfg.dense_units)] * int(critic_cfg.mlp_layers),
        output_dim=int(critic_cfg.bins),
        activation=critic_cfg.dense_act,
        layer_norm=critic_ln,
        layer_norm_eps=critic_eps,
        head_init_scale=0.0 if cfg.algo.hafner_initialization else -1.0,
        dtype=compute_dtype,
    )
    critics_spec: Dict[str, Dict[str, Any]] = {}
    intrinsic_critics = 0
    for k, v in cfg.algo.critics_exploration.items():
        if float(v.weight) > 0:
            if v.reward_type == "intrinsic":
                intrinsic_critics += 1
            elif v.reward_type != "task":
                raise ValueError(
                    f"Unknown exploration-critic reward_type '{v.reward_type}' for '{k}': "
                    "must be 'intrinsic' or 'task'"
                )
            critics_spec[k] = {"weight": float(v.weight), "reward_type": str(v.reward_type)}
    if intrinsic_critics == 0:
        raise RuntimeError("You must specify at least one intrinsic critic (`reward_type='intrinsic'`)")

    # The ensembles predict the NEXT stochastic state from (posterior, recurrent,
    # action) with an MSE head (reference p2e_dv3/agent.py:175-205,
    # p2e_dv3_exploration.py:205-227).
    ens_ln, _ = _ln_enabled(cfg.algo.ensembles.get("layer_norm"))
    ensembles = Ensembles(
        n=int(cfg.algo.ensembles.n),
        input_dim=int(sum(actions_dim)) + latent_state_size,
        output_dim=stochastic_size,
        mlp_layers=int(cfg.algo.ensembles.mlp_layers),
        dense_units=int(cfg.algo.ensembles.dense_units),
        activation=cfg.algo.ensembles.dense_act,
        layer_norm=ens_ln,
        dtype=compute_dtype,
    )

    key = jax.random.PRNGKey(cfg.seed + 1)  # distinct stream from the DV3 init
    k_actor, k_ens, k_crit = jax.random.split(key, 3)
    dummy_latent = jnp.zeros((1, latent_state_size))
    actor_exploration_params = actor_exploration.init(k_actor, dummy_latent)
    ensembles_params = ensembles.init(k_ens, jnp.zeros((1, ensembles.input_dim)))
    critics_exploration_params: Dict[str, Dict[str, Any]] = {}
    for i, k in enumerate(critics_spec):
        ck = jax.random.fold_in(k_crit, i)
        cp = critic_exploration.init(ck, dummy_latent)
        critics_exploration_params[k] = {"module": cp, "target_module": copy.deepcopy(cp)}

    if actor_exploration_state:
        actor_exploration_params = jax.tree_util.tree_map(jnp.asarray, actor_exploration_state)
    if ensembles_state:
        ensembles_params = jax.tree_util.tree_map(jnp.asarray, ensembles_state)
    if critics_exploration_state:
        critics_exploration_params = jax.tree_util.tree_map(jnp.asarray, dict(critics_exploration_state))

    modules = P2EDV3Modules(
        encoder=dv3_modules.encoder,
        rssm=dv3_modules.rssm,
        observation_model=dv3_modules.observation_model,
        reward_model=dv3_modules.reward_model,
        continue_model=dv3_modules.continue_model,
        ensembles=ensembles,
        actor_task=dv3_modules.actor,
        critic_task=dv3_modules.critic,
        actor_exploration=actor_exploration,
        critic_exploration=critic_exploration,
        critics_exploration=critics_spec,
    )
    params = {
        "world_model": dv3_params["world_model"],
        "ensembles": ensembles_params,
        "actor_task": dv3_params["actor"],
        "critic_task": dv3_params["critic"],
        "target_critic_task": dv3_params["target_critic"],
        "actor_exploration": actor_exploration_params,
        "critics_exploration": critics_exploration_params,
    }

    # Point the player at the requested behaviour policy (reference agent.py:207-216).
    if cfg.algo.player.actor_type == "exploration":
        player.actor = actor_exploration
        player.actor_params = actor_exploration_params
    return modules, params, player
