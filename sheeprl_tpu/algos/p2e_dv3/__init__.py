from sheeprl_tpu.algos.p2e_dv3 import p2e_dv3_exploration, p2e_dv3_finetuning  # noqa: F401
from sheeprl_tpu.algos.p2e_dv3 import evaluate  # noqa: F401  (must import after the algorithms register)
