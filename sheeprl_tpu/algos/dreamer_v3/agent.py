"""DreamerV3 agent: encoders/decoders, RSSM, actor, critic, player (flax + lax.scan).

Parity targets (reference sheeprl/algos/dreamer_v3/agent.py): CNNEncoder (:42),
MLPEncoder (:100), CNNDecoder (:154), MLPDecoder (:229), RecurrentModel (:281),
RSSM (:344), DecoupledRSSM (:501), PlayerDV3 (:596), Actor (:694), build_agent (:935),
Hafner init (dreamer_v3/utils.py:init_weights/uniform_init_weights).

TPU-first design decisions:
- The RSSM is a set of small flax modules (recurrent cell, representation, transition)
  composed by *pure scan functions* (`rssm_dynamic_scan`, `rssm_imagination_scan`)
  instead of a stateful module with Python loops: the T=64 dynamic unroll and the H=15
  imagination unroll each compile to ONE fused `lax.scan` whose per-step compute is a
  few MXU matmuls (the reference loops in Python, dreamer_v3.py:138-151, 243-252).
- Params live in a plain dict pytree (`wm_params`), so the world model / actor /
  critic are optax-updatable leaves with no module-wrapper state.
- Hafner initialization maps exactly onto `variance_scaling`: trunc-normal
  fan-avg scale 1.0 for trunks; fan-avg uniform (scale 1.0 or 0.0) for output heads.
- The player's policy step is a single jitted pure function over explicit state
  (recurrent/stochastic/actions), so rollout latency is one host->device dispatch.
"""

from __future__ import annotations

import copy
from math import prod
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.models.models import MLP, CNN, DeCNN, LayerNorm, LayerNormGRUCell
from sheeprl_tpu.ops.distributions import (
    Independent,
    Normal,
    OneHotCategoricalStraightThrough,
    TanhNormal,
)
from sheeprl_tpu.utils.utils import host_float32, resolve_actor_cls, symlog

# Hafner initializers (reference dreamer_v3/utils.py:init_weights / uniform_init_weights):
# trunc-normal with std = sqrt(1/fan_avg)/0.8796...  == variance_scaling truncated_normal;
# heads use uniform with limit sqrt(3*scale/fan_avg) == variance_scaling uniform.
hafner_trunc_init = nn.initializers.variance_scaling(1.0, "fan_avg", "truncated_normal")


def hafner_uniform_init(scale: float):
    if scale == 0.0:
        return nn.initializers.zeros_init()
    return nn.initializers.variance_scaling(scale, "fan_avg", "uniform")


def uniform_mix(logits: jax.Array, discrete: int, unimix: float) -> jax.Array:
    """1% uniform mixture over each categorical (reference agent.py:437-449).

    Input/output logits shape ``[..., stoch*discrete]``.
    """
    shape = logits.shape
    logits = logits.reshape(*shape[:-1], -1, discrete)
    if unimix > 0.0:
        probs = jax.nn.softmax(logits, axis=-1)
        uniform = jnp.ones_like(probs) / discrete
        probs = (1 - unimix) * probs + unimix * uniform
        logits = jnp.log(jnp.clip(probs, 1e-12, None))
    return logits.reshape(shape)


def compute_stochastic_state(
    logits: jax.Array, discrete: int, key: Optional[jax.Array] = None, sample: bool = True
) -> jax.Array:
    """Sample (straight-through) or take the mode of the categorical stochastic state.

    Reference: sheeprl/algos/dreamer_v2/utils.py:44-63. Input ``[..., stoch*discrete]``,
    output ``[..., stoch, discrete]``.
    """
    logits = logits.reshape(*logits.shape[:-1], -1, discrete)
    dist = OneHotCategoricalStraightThrough(logits=logits)
    if sample:
        return dist.rsample(key)
    return dist.mode


class CNNEncoder(nn.Module):
    """4-stage stride-2 image encoder, 64x64 -> 4x4 (reference agent.py:42-99).

    Multiple image keys are concatenated on the channel dim. Output is flattened.
    """

    keys: Sequence[str]
    input_channels: Sequence[int]
    image_size: Tuple[int, int]
    channels_multiplier: int
    layer_norm: bool = True
    layer_norm_eps: float = 1e-3
    activation: str = "silu"
    stages: int = 4
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @property
    def output_dim(self) -> int:
        h = self.image_size[0] // (2**self.stages)
        w = self.image_size[1] // (2**self.stages)
        return (2 ** (self.stages - 1)) * self.channels_multiplier * h * w

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-3)
        batch_shape = x.shape[:-3]
        x = x.reshape(-1, *x.shape[-3:])
        x = CNN(
            input_channels=sum(self.input_channels),
            hidden_channels=[(2**i) * self.channels_multiplier for i in range(self.stages)],
            layer_args={"kernel_size": 4, "stride": 2, "padding": 1, "bias": not self.layer_norm},
            activation=self.activation,
            layer_norm=self.layer_norm,
            norm_args={"eps": self.layer_norm_eps},
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=hafner_trunc_init,
        )(x)
        x = x.reshape(x.shape[0], -1)
        return x.reshape(*batch_shape, x.shape[-1])


class MLPEncoder(nn.Module):
    """Vector encoder with symlog inputs (reference agent.py:100-151)."""

    keys: Sequence[str]
    input_dims: Sequence[int]
    mlp_layers: int = 4
    dense_units: int = 512
    layer_norm: bool = True
    layer_norm_eps: float = 1e-3
    activation: str = "silu"
    symlog_inputs: bool = True
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @property
    def output_dim(self) -> int:
        return self.dense_units

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([symlog(obs[k]) if self.symlog_inputs else obs[k] for k in self.keys], axis=-1)
        return MLP(
            input_dims=sum(self.input_dims),
            output_dim=None,
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            norm_args={"eps": self.layer_norm_eps},
            use_bias=not self.layer_norm,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=hafner_trunc_init,
        )(x)


class MultiEncoderDV3(nn.Module):
    """Concatenate CNN and MLP features (reference MultiEncoder, models.py:413-475)."""

    cnn_encoder: Optional[CNNEncoder]
    mlp_encoder: Optional[MLPEncoder]

    @property
    def output_dim(self) -> int:
        out = 0
        if self.cnn_encoder is not None:
            out += self.cnn_encoder.output_dim
        if self.mlp_encoder is not None:
            out += self.mlp_encoder.output_dim
        return out

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        outs = []
        if self.cnn_encoder is not None:
            outs.append(self.cnn_encoder(obs))
        if self.mlp_encoder is not None:
            outs.append(self.mlp_encoder(obs))
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=-1)


class CNNDecoder(nn.Module):
    """Inverse of CNNEncoder: latent -> 4x4 features -> image dict (reference agent.py:154-228)."""

    keys: Sequence[str]
    output_channels: Sequence[int]
    channels_multiplier: int
    cnn_encoder_output_dim: int
    image_size: Tuple[int, int]
    layer_norm: bool = True
    layer_norm_eps: float = 1e-3
    activation: str = "silu"
    stages: int = 4
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent_states: jax.Array) -> Dict[str, jax.Array]:
        batch_shape = latent_states.shape[:-1]
        x = latent_states.reshape(-1, latent_states.shape[-1])
        x = nn.Dense(
            self.cnn_encoder_output_dim,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=hafner_trunc_init,
        )(x)
        h0 = self.image_size[0] // (2**self.stages)
        w0 = self.image_size[1] // (2**self.stages)
        x = x.reshape(-1, (2 ** (self.stages - 1)) * self.channels_multiplier, h0, w0)
        out_ch = sum(self.output_channels)
        x = DeCNN(
            input_channels=(2 ** (self.stages - 1)) * self.channels_multiplier,
            hidden_channels=[(2**i) * self.channels_multiplier for i in reversed(range(self.stages - 1))]
            + [out_ch],
            layer_args=[
                {"kernel_size": 4, "stride": 2, "padding": 1, "bias": not self.layer_norm}
                for _ in range(self.stages - 1)
            ]
            + [{"kernel_size": 4, "stride": 2, "padding": 1}],
            activation=[self.activation] * (self.stages - 1) + [None],
            layer_norm=[self.layer_norm] * (self.stages - 1) + [False],
            norm_args={"eps": self.layer_norm_eps},
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=[hafner_trunc_init] * (self.stages - 1) + [hafner_uniform_init(1.0)],
        )(x)
        x = x.reshape(*batch_shape, out_ch, *self.image_size)
        out: Dict[str, jax.Array] = {}
        start = 0
        for k, ch in zip(self.keys, self.output_channels):
            out[k] = x[..., start : start + ch, :, :]
            start += ch
        return out


class MLPDecoder(nn.Module):
    """Inverse of MLPEncoder: latent -> vector dict (reference agent.py:229-280)."""

    keys: Sequence[str]
    output_dims: Sequence[int]
    mlp_layers: int = 4
    dense_units: int = 512
    layer_norm: bool = True
    layer_norm_eps: float = 1e-3
    activation: str = "silu"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent_states: jax.Array) -> Dict[str, jax.Array]:
        x = MLP(
            input_dims=latent_states.shape[-1],
            output_dim=None,
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            norm_args={"eps": self.layer_norm_eps},
            use_bias=not self.layer_norm,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=hafner_trunc_init,
        )(latent_states)
        return {
            k: nn.Dense(
                dim,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=hafner_uniform_init(1.0),
                name=f"head_{k}",
            )(x)
            for k, dim in zip(self.keys, self.output_dims)
        }


class MultiDecoderDV3(nn.Module):
    cnn_decoder: Optional[CNNDecoder]
    mlp_decoder: Optional[MLPDecoder]

    @nn.compact
    def __call__(self, latent_states: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_decoder is not None:
            out.update(self.cnn_decoder(latent_states))
        if self.mlp_decoder is not None:
            out.update(self.mlp_decoder(latent_states))
        return out


class RecurrentModel(nn.Module):
    """MLP projection + LayerNorm GRU (reference agent.py:281-343).

    One fused input matmul + one fused GRU matmul per step — both MXU-friendly.
    """

    input_size: int
    recurrent_state_size: int
    dense_units: int
    layer_norm: bool = True
    layer_norm_eps: float = 1e-3
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, recurrent_state: jax.Array) -> jax.Array:
        feat = MLP(
            input_dims=self.input_size,
            output_dim=None,
            hidden_sizes=[self.dense_units],
            activation=None,
            layer_norm=self.layer_norm,
            norm_args={"eps": self.layer_norm_eps},
            use_bias=not self.layer_norm,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=hafner_trunc_init,
        )(x)
        return LayerNormGRUCell(
            hidden_size=self.recurrent_state_size,
            bias=False,
            layer_norm=True,
            layer_norm_eps=self.layer_norm_eps,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=hafner_trunc_init,
        )(feat, recurrent_state)


class MLPWithHead(nn.Module):
    """MLP trunk + linear head with Hafner head init (representation/transition/
    reward/continue/critic share this shape; reference builds them as plain MLPs with
    per-layer init overrides, agent.py:1021-1180)."""

    input_dim: int
    hidden_sizes: Sequence[int]
    output_dim: int
    activation: str = "silu"
    layer_norm: bool = True
    layer_norm_eps: float = 1e-3
    head_init_scale: float = 1.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if len(self.hidden_sizes) > 0:
            x = MLP(
                input_dims=self.input_dim,
                output_dim=None,
                hidden_sizes=self.hidden_sizes,
                activation=self.activation,
                layer_norm=self.layer_norm,
                norm_args={"eps": self.layer_norm_eps},
                use_bias=not self.layer_norm,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=hafner_trunc_init,
            )(x)
        head_init = (
            hafner_uniform_init(self.head_init_scale)
            if self.head_init_scale >= 0
            else nn.initializers.lecun_normal()
        )
        return nn.Dense(
            self.output_dim,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=head_init,
            name="head",
        )(x)


class Actor(nn.Module):
    """DV3 actor (reference agent.py:694-847).

    Returns the raw pre-distribution outputs (one per discrete action head, or a
    single mean/std tensor for continuous); distribution math lives in `ActorOutput`.
    """

    latent_state_size: int
    actions_dim: Sequence[int]
    is_continuous: bool
    distribution: str = "auto"
    init_std: float = 2.0
    min_std: float = 0.1
    max_std: float = 1.0
    dense_units: int = 1024
    mlp_layers: int = 5
    layer_norm: bool = True
    layer_norm_eps: float = 1e-3
    activation: str = "silu"
    unimix: float = 0.01
    action_clip: float = 1.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    # rollout-time masked sampling is an actor property, not a player branch
    uses_action_mask: bool = False

    def resolved_distribution(self) -> str:
        dist = self.distribution.lower()
        if dist not in ("auto", "normal", "tanh_normal", "discrete", "scaled_normal"):
            raise ValueError(
                "The distribution must be on of: `auto`, `discrete`, `normal`, `tanh_normal` and `scaled_normal`. "
                f"Found: {dist}"
            )
        if dist == "discrete" and self.is_continuous:
            raise ValueError("You have choose a discrete distribution but `is_continuous` is true")
        if dist == "auto":
            dist = "scaled_normal" if self.is_continuous else "discrete"
        return dist

    def sample(self, pre_dist: List[jax.Array], key: jax.Array, greedy: bool = False, mask=None) -> List[jax.Array]:
        """Turn raw head outputs into env actions; subclasses may consume ``mask``."""
        return ActorOutput(self, pre_dist).sample_actions(key, greedy=greedy)

    @nn.compact
    def __call__(self, state: jax.Array) -> List[jax.Array]:
        x = MLP(
            input_dims=self.latent_state_size,
            output_dim=None,
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            activation=self.activation,
            layer_norm=self.layer_norm,
            norm_args={"eps": self.layer_norm_eps},
            use_bias=not self.layer_norm,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=hafner_trunc_init,
        )(state)
        if self.is_continuous:
            return [
                nn.Dense(
                    int(np.sum(self.actions_dim)) * 2,
                    dtype=self.dtype,
                    param_dtype=self.param_dtype,
                    kernel_init=hafner_uniform_init(1.0),
                    name="head_0",
                )(x)
            ]
        return [
            nn.Dense(
                dim,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=hafner_uniform_init(1.0),
                name=f"head_{i}",
            )(x)
            for i, dim in enumerate(self.actions_dim)
        ]


class MinedojoActor(Actor):
    """DV3 actor for MineDojo (reference agent.py:848-934): same parameters as
    `Actor`, but rollout-time sampling applies the env-provided action masks —
    see `sample_minedojo_actions`. Selected via ``cfg.algo.actor.cls``."""

    uses_action_mask: bool = True

    def sample(self, pre_dist: List[jax.Array], key: jax.Array, greedy: bool = False, mask=None) -> List[jax.Array]:
        return sample_minedojo_actions(self, pre_dist, mask, key, greedy=greedy)


def sample_minedojo_actions(
    actor,
    pre_dist: List[jax.Array],
    mask: Optional[Dict[str, jax.Array]],
    key: jax.Array,
    greedy: bool = False,
) -> List[jax.Array]:
    """Sequential masked sampling over MineDojo's three action heads
    (reference MinedojoActor.forward, agent.py:883-934).

    Head 0 (action type) is masked by ``mask_action_type``; head 1 (craft
    target) is masked by ``mask_craft_smelt`` only when the sampled macro is 15
    (craft); head 2 (equip/place/destroy target) is masked by
    ``mask_equip_place`` for macros 16/17 and ``mask_destroy`` for macro 18.
    The reference loops over every [t, b] element in Python; here the
    conditional masking is a batched `jnp.where` on the logits.
    """
    if mask is None:
        return ActorOutput(actor, pre_dist).sample_actions(key, greedy=greedy)

    keys = jax.random.split(key, len(pre_dist))
    actions: List[jax.Array] = []
    functional_action = None
    for i, logits in enumerate(pre_dist):
        logits = uniform_mix(logits, logits.shape[-1], getattr(actor, "unimix", 0.0))
        logits = minedojo_mask_logits(logits, i, mask, functional_action)
        dist = OneHotCategoricalStraightThrough(logits=logits)
        actions.append(dist.mode if greedy else dist.rsample(keys[i]))
        if functional_action is None:
            functional_action = actions[0].argmax(axis=-1)
    return actions


def minedojo_mask_logits(
    logits: jax.Array, head: int, mask: Dict[str, jax.Array], functional_action: Optional[jax.Array]
) -> jax.Array:
    """-inf-mask one MineDojo head's logits per the env constraints.

    Head 0: ``mask_action_type``. Head 1: ``mask_craft_smelt`` when the sampled
    macro is 15 (craft). Head 2: ``mask_equip_place`` for macros 16/17,
    ``mask_destroy`` for macro 18. Single source for the macro->mask mapping
    (used by DV3/DV2 sampling AND the DV2 masked exploration noise); batched
    `jnp.where` instead of the reference's per-[t,b] Python loops.
    """

    def masked(m):
        m = jnp.broadcast_to(jnp.asarray(m, dtype=bool), logits.shape)
        return jnp.where(m, logits, -jnp.inf)

    if head == 0:
        return masked(mask["mask_action_type"])
    if head == 1:
        return jnp.where((functional_action == 15)[..., None], masked(mask["mask_craft_smelt"]), logits)
    is_equip_place = ((functional_action == 16) | (functional_action == 17))[..., None]
    out = jnp.where(is_equip_place, masked(mask["mask_equip_place"]), logits)
    return jnp.where((functional_action == 18)[..., None], masked(mask["mask_destroy"]), out)


class ActorOutput:
    """Distribution wrapper over the actor's raw head outputs.

    Mirrors the (actions, dists) tuple the reference actor returns (agent.py:783-847)
    with explicit PRNG keys.
    """

    def __init__(self, actor: Actor, pre_dist: List[jax.Array]):
        self.actor = actor
        self.dist_type = actor.resolved_distribution()
        self.pre_dist = pre_dist
        if actor.is_continuous:
            mean, std = jnp.split(pre_dist[0], 2, axis=-1)
            if self.dist_type == "tanh_normal":
                mean = 5 * jnp.tanh(mean / 5)
                std = jax.nn.softplus(std + actor.init_std) + actor.min_std
                self.dists = [Independent(TanhNormal(mean, std), 1)]
            elif self.dist_type == "normal":
                self.dists = [Independent(Normal(mean, std), 1)]
            else:  # scaled_normal
                std = (actor.max_std - actor.min_std) * jax.nn.sigmoid(std + actor.init_std) + actor.min_std
                self.dists = [Independent(Normal(jnp.tanh(mean), std), 1)]
        else:
            self.dists = [
                OneHotCategoricalStraightThrough(logits=uniform_mix(logits, logits.shape[-1], getattr(actor, "unimix", 0.0)))
                for logits in pre_dist
            ]

    def sample_actions(self, key: jax.Array, greedy: bool = False) -> List[jax.Array]:
        return self.sample_actions_with_raw(key, greedy=greedy)[0]

    def sample_actions_with_raw(self, key: jax.Array, greedy: bool = False):
        """(clipped actions, raw pre-clip samples).

        The raw sample is the point at which a score-function (REINFORCE)
        estimator must evaluate log-prob: for a saturated continuous policy the
        clip rescaling moves ~half the samples onto the boundary, and log-prob
        at the CLIPPED point no longer estimates the sampled policy's score
        (walker_walk measures 40-46% saturation, benchmarks/WALKER_WALK_NOTES.md).
        The env/dynamics always consume the clipped actions.
        """
        if self.actor.is_continuous:
            if greedy:
                # Reference draws 100 samples and takes the max-log-prob one
                # (agent.py:809-812); the distribution mode is equivalent in the
                # scaled_normal case and deterministic, so we use it directly.
                actions = self.dists[0].mode
            else:
                actions = self.dists[0].rsample(key)
            raw = actions
            if self.actor.action_clip > 0.0:
                clip = jnp.full_like(actions, self.actor.action_clip)
                actions = actions * jax.lax.stop_gradient(clip / jnp.maximum(clip, jnp.abs(actions)))
            return [actions], [raw]
        keys = jax.random.split(key, len(self.dists))
        if greedy:
            modes = [d.mode for d in self.dists]
            return modes, modes
        samples = [d.rsample(k) for d, k in zip(self.dists, keys)]
        return samples, samples

    def log_prob(self, actions: List[jax.Array]) -> jax.Array:
        """Summed log-prob across heads; ``[...,]`` shaped."""
        return sum(d.log_prob(a) for d, a in zip(self.dists, actions))

    def entropy(self) -> jax.Array:
        return sum(d.entropy() for d in self.dists)


class RSSM:
    """Pure-functional RSSM composition (reference agent.py:344-500).

    Holds module definitions + static hyperparams; all state flows through args.
    `wm_params` is the world-model param dict with keys ``recurrent_model``,
    ``representation_model``, ``transition_model``, ``initial_recurrent_state``.
    """

    def __init__(
        self,
        recurrent_model: RecurrentModel,
        representation_model: MLPWithHead,
        transition_model: MLPWithHead,
        stochastic_size: int,
        discrete_size: int = 32,
        unimix: float = 0.01,
        learnable_initial_recurrent_state: bool = True,
        decoupled: bool = False,
        dynamic_scan_unroll: int = 1,
        kernels: str = "off",
    ):
        self.recurrent_model = recurrent_model
        self.representation_model = representation_model
        self.transition_model = transition_model
        self.stochastic_size = stochastic_size
        self.discrete_size = discrete_size
        self.unimix = unimix
        self.learnable_initial_recurrent_state = learnable_initial_recurrent_state
        self.decoupled = decoupled
        # lax.scan unroll factor for the T-step dynamic scan: the per-step matmuls
        # ([B,~1.5k]x[~1.5k,512] at the S preset) are small for the MXU, so unrolling
        # lets XLA overlap/pipeline consecutive steps' HBM reads and MXU work
        self.dynamic_scan_unroll = int(dynamic_scan_unroll)
        # world_model.kernels knob: off/auto/pallas/interpret/reference. Anything
        # but "off" routes the dynamic/imagination steps through the fused Pallas
        # subsystem (ops/pallas/rssm_step.py); "off" is the bitwise flax reference.
        self.kernels = str(kernels).lower()

    def _fused_spec(self, embed_size: int, action_size: int):
        """Build the static step spec, or raise KernelUnsupported when this RSSM
        falls outside the fused-step contract (dispatch then stays on flax)."""
        from sheeprl_tpu.ops.pallas import rssm_step as _fk

        if self.decoupled:
            raise _fk.KernelUnsupported("decoupled RSSM has no sequential posterior step")
        if not (self.recurrent_model.layer_norm and self.representation_model.layer_norm
                and self.transition_model.layer_norm):
            raise _fk.KernelUnsupported("fused step requires layer_norm on all RSSM trunks")
        for m in (self.representation_model, self.transition_model):
            if str(m.activation) != "silu":
                raise _fk.KernelUnsupported(f"fused step expects silu trunks, got {m.activation!r}")
            if len(m.hidden_sizes) != 1:
                raise _fk.KernelUnsupported("fused step expects single-hidden-layer trunks")
        return _fk.RSSMStepSpec(
            action_size=int(action_size),
            embed_size=int(embed_size),
            dense_units=int(self.recurrent_model.dense_units),
            recurrent_size=int(self.recurrent_model.recurrent_state_size),
            trans_hidden=int(self.transition_model.hidden_sizes[0]),
            repr_hidden=int(self.representation_model.hidden_sizes[0]),
            stochastic=self.stochastic_size,
            discrete=self.discrete_size,
            unimix=float(self.unimix),
            eps_in=float(self.recurrent_model.layer_norm_eps),
            eps_gru=float(self.recurrent_model.layer_norm_eps),
            eps_trans=float(self.transition_model.layer_norm_eps),
            eps_repr=float(self.representation_model.layer_norm_eps),
            dtype=jnp.dtype(self.recurrent_model.dtype).name,
        )

    @property
    def stoch_state_size(self) -> int:
        return self.stochastic_size * self.discrete_size

    def initial_states(self, wm_params: Dict[str, Any], batch_shape: Sequence[int]) -> Tuple[jax.Array, jax.Array]:
        """(initial recurrent state, initial posterior mode); reference agent.py:391-395."""
        raw = wm_params["initial_recurrent_state"]
        if not self.learnable_initial_recurrent_state:
            # fixed zeros buffer (reference registers a non-trainable buffer, agent.py:383-388)
            raw = jax.lax.stop_gradient(raw)
        init = jnp.tanh(raw)
        recurrent_state = jnp.broadcast_to(init, (*batch_shape, init.shape[-1]))
        logits, prior = self._transition(wm_params, recurrent_state, sample=False)
        return recurrent_state, prior.reshape(*batch_shape, -1)

    def _transition(
        self, wm_params, recurrent_out: jax.Array, key: Optional[jax.Array] = None, sample: bool = True
    ) -> Tuple[jax.Array, jax.Array]:
        logits = self.transition_model.apply(wm_params["transition_model"], recurrent_out)
        logits = uniform_mix(logits, self.discrete_size, self.unimix)
        return logits, compute_stochastic_state(logits, self.discrete_size, key, sample=sample)

    def _representation(
        self, wm_params, embedded_obs: jax.Array, key: jax.Array, recurrent_state: Optional[jax.Array] = None
    ) -> Tuple[jax.Array, jax.Array]:
        if self.decoupled:
            x = embedded_obs
        else:
            x = jnp.concatenate([recurrent_state, embedded_obs], axis=-1)
        logits = self.representation_model.apply(wm_params["representation_model"], x)
        logits = uniform_mix(logits, self.discrete_size, self.unimix)
        return logits, compute_stochastic_state(logits, self.discrete_size, key)

    def _recurrent(self, wm_params, posterior_flat: jax.Array, action: jax.Array, recurrent_state: jax.Array):
        x = jnp.concatenate([posterior_flat, action], axis=-1)
        return self.recurrent_model.apply(wm_params["recurrent_model"], x, recurrent_state)

    def dynamic_step(
        self,
        wm_params,
        posterior_flat: jax.Array,
        recurrent_state: jax.Array,
        action: jax.Array,
        embedded_obs: jax.Array,
        is_first: jax.Array,
        key: jax.Array,
    ):
        """One step of dynamic learning (reference agent.py:396-435)."""
        k_prior, k_post = jax.random.split(key)
        action = (1 - is_first) * action
        init_rec, init_post = self.initial_states(wm_params, recurrent_state.shape[:-1])
        recurrent_state = (1 - is_first) * recurrent_state + is_first * init_rec
        posterior_flat = (1 - is_first) * posterior_flat + is_first * init_post
        recurrent_state = self._recurrent(wm_params, posterior_flat, action, recurrent_state)
        prior_logits, prior = self._transition(wm_params, recurrent_state, k_prior)
        posterior_logits, posterior = self._representation(
            wm_params, embedded_obs, k_post, recurrent_state=recurrent_state
        )
        return recurrent_state, posterior, prior, posterior_logits, prior_logits

    def dynamic_scan(
        self,
        wm_params,
        embedded_obs: jax.Array,  # [T, B, E]
        actions: jax.Array,  # [T, B, A] (already shifted: a_{t-1} enters step t)
        is_first: jax.Array,  # [T, B, 1]
        key: jax.Array,
    ):
        """lax.scan over the sequence dim: the hot loop of world-model learning.

        With ``kernels != off`` the non-decoupled path dispatches to the fused
        Pallas step (ops/pallas/rssm_step.py): same return contract, logits in
        f32, sampling distribution-equivalent (not bitwise) to this path. Any
        structural mismatch or an active ``train.kernel_dispatch`` failpoint
        degrades back to the flax scan below.
        """
        if self.kernels != "off" and not self.decoupled:
            fused = self._fused_dynamic_scan(wm_params, embedded_obs, actions, is_first, key)
            if fused is not None:
                return fused
        T, B = embedded_obs.shape[0], embedded_obs.shape[1]
        keys = jax.random.split(key, T)
        init_rec = jnp.zeros((B, self.recurrent_model.recurrent_state_size), dtype=embedded_obs.dtype)
        init_post = jnp.zeros((B, self.stoch_state_size), dtype=embedded_obs.dtype)

        if self.decoupled:
            # representation is independent of the recurrent state: batch it over [T,B]
            post_keys = jax.random.split(jax.random.fold_in(key, 1), T)

            def rep(embedded, k):
                return self._representation(wm_params, embedded, k)

            posteriors_logits, posteriors = jax.vmap(rep)(embedded_obs, post_keys)
            posteriors_flat = posteriors.reshape(T, B, -1)
            prev_posts = jnp.concatenate([jnp.zeros_like(posteriors_flat[:1]), posteriors_flat[:-1]], axis=0)

            def step(carry, xs):
                recurrent_state = carry
                prev_post, action, is_f, k = xs
                action = (1 - is_f) * action
                init_r, init_p = self.initial_states(wm_params, recurrent_state.shape[:-1])
                recurrent_state = (1 - is_f) * recurrent_state + is_f * init_r
                prev_post = (1 - is_f) * prev_post + is_f * init_p
                recurrent_state = self._recurrent(wm_params, prev_post, action, recurrent_state)
                prior_logits, _ = self._transition(wm_params, recurrent_state, k)
                return recurrent_state, (recurrent_state, prior_logits)

            _, (recurrent_states, priors_logits) = jax.lax.scan(
                step, init_rec, (prev_posts, actions, is_first, keys), unroll=self.dynamic_scan_unroll
            )
            # logits leave flat [T,B,S*D]; expose factorized [T,B,S,D] (the shape the
            # KL-balance loss and entropy metrics expect, reference loss.py:45-70)
            priors_logits = priors_logits.reshape(T, B, self.stochastic_size, self.discrete_size)
            posteriors_logits = posteriors_logits.reshape(T, B, self.stochastic_size, self.discrete_size)
            return recurrent_states, posteriors, priors_logits, posteriors_logits

        def step(carry, xs):
            recurrent_state, posterior_flat = carry
            action, embedded, is_f, k = xs
            recurrent_state, posterior, prior, post_logits, prior_logits = self.dynamic_step(
                wm_params, posterior_flat, recurrent_state, action, embedded, is_f, k
            )
            new_carry = (recurrent_state, posterior.reshape(*posterior.shape[:-2], -1))
            return new_carry, (recurrent_state, posterior, post_logits, prior_logits)

        _, (recurrent_states, posteriors, posteriors_logits, priors_logits) = jax.lax.scan(
            step, (init_rec, init_post), (actions, embedded_obs, is_first, keys), unroll=self.dynamic_scan_unroll
        )
        # factorized logits [T,B,S,D]: categorical_kl and the entropy metrics softmax
        # per-categorical over D, not over the flat S*D vector
        priors_logits = priors_logits.reshape(T, B, self.stochastic_size, self.discrete_size)
        posteriors_logits = posteriors_logits.reshape(T, B, self.stochastic_size, self.discrete_size)
        return recurrent_states, posteriors, priors_logits, posteriors_logits

    def _fused_dynamic_scan(self, wm_params, embedded_obs, actions, is_first, key):
        """Fused-path dispatch; None means fall back to the flax scan."""
        from sheeprl_tpu.ops.pallas import rssm_step as _fk

        try:
            spec = self._fused_spec(embedded_obs.shape[-1], actions.shape[-1])
            impl = _fk.select_impl(self.kernels, spec, embedded_obs.shape[1])
            if impl is None:
                return None
            p = _fk.extract_step_params(wm_params, self.stoch_state_size)
        except _fk.KernelUnsupported:
            return None
        return _fk.fused_dynamic_scan(
            p,
            spec.with_impl(impl),
            wm_params["initial_recurrent_state"],
            embedded_obs,
            actions,
            is_first,
            key,
            learnable_init=self.learnable_initial_recurrent_state,
            unroll=self.dynamic_scan_unroll,
        )

    def imagination_step(self, wm_params, prior_flat: jax.Array, recurrent_state: jax.Array, actions: jax.Array, key):
        """One-step latent imagination (reference agent.py:482-498); dispatches
        to the fused step under the same ``kernels`` knob as dynamic_scan."""
        if self.kernels != "off" and not self.decoupled:
            from sheeprl_tpu.ops.pallas import rssm_step as _fk

            try:
                spec = self._fused_spec(0, actions.shape[-1])
                impl = _fk.select_impl(self.kernels, spec, recurrent_state.shape[0])
                if impl is not None:
                    p = _fk.extract_step_params(wm_params, self.stoch_state_size)
                    return _fk.fused_imagination_step(
                        p, spec.with_impl(impl), prior_flat, recurrent_state, actions, key
                    )
            except _fk.KernelUnsupported:
                pass
        recurrent_state = self._recurrent(wm_params, prior_flat, actions, recurrent_state)
        _, imagined_prior = self._transition(wm_params, recurrent_state, key)
        return imagined_prior.reshape(*prior_flat.shape), recurrent_state


class PlayerDV3:
    """Stateful host-side rollout policy over a single jitted step (reference agent.py:596-693).

    The per-step device work (encode -> recurrent -> representation -> actor) is one
    compiled XLA program; the recurrent/stochastic/action state lives on device.
    """

    def __init__(
        self,
        encoder: MultiEncoderDV3,
        rssm: RSSM,
        actor: Actor,
        actions_dim: Sequence[int],
        num_envs: int,
        stochastic_size: int,
        recurrent_state_size: int,
        discrete_size: int = 32,
        actor_type: Optional[str] = None,
    ):
        self.encoder = encoder
        self.rssm = rssm
        self.actor = actor
        self.actions_dim = tuple(actions_dim)
        self.num_envs = num_envs
        self.stochastic_size = stochastic_size
        self.recurrent_state_size = recurrent_state_size
        self.discrete_size = discrete_size
        self.actor_type = actor_type
        # filled by build_agent
        self.wm_params: Any = None
        self.actor_params: Any = None
        self._step = jax_compile.guarded_jit(
            self._raw_step, name="dv3.step", static_argnames=("greedy",)
        )
        self._packed_step_fns: Dict[Any, Any] = {}

    def _actor_step(self, actor_params, latent, key, greedy: bool = False, mask=None):
        """Sample actions from the latent; subclasses override to change how the
        actor is queried (e.g. PonderNet inference-mode halting in PlayerDAP);
        mask consumption is the actor's own concern (Actor.sample)."""
        pre_dist = self.actor.apply(actor_params, latent)
        return self.actor.sample(pre_dist, key, greedy=greedy, mask=mask)

    def _raw_step(self, wm_params, actor_params, state, obs, key, greedy: bool = False, mask=None):
        recurrent_state, stochastic_state, actions = state
        k_rep, k_act = jax.random.split(key)
        embedded = self.encoder.apply(wm_params["encoder"], obs)
        recurrent_state = self.rssm._recurrent(wm_params, stochastic_state, actions, recurrent_state)
        if self.rssm.decoupled:
            _, stoch = self.rssm._representation(wm_params, embedded, k_rep)
        else:
            _, stoch = self.rssm._representation(wm_params, embedded, k_rep, recurrent_state=recurrent_state)
        stochastic_state = stoch.reshape(*stoch.shape[:-2], self.stochastic_size * self.discrete_size)
        latent = jnp.concatenate([stochastic_state, recurrent_state], axis=-1)
        actions_list = host_float32(self._actor_step(actor_params, latent, k_act, greedy=greedy, mask=mask))
        actions = jnp.concatenate(actions_list, axis=-1)
        return tuple(actions_list), (recurrent_state, stochastic_state, actions)

    def init_states(self, reset_envs: Optional[Sequence[int]] = None) -> None:
        if reset_envs is None or len(reset_envs) == 0:
            actions = jnp.zeros((1, self.num_envs, int(np.sum(self.actions_dim))), dtype=jnp.float32)
            recurrent_state, stoch = self.rssm.initial_states(self.wm_params, (1, self.num_envs))
            self.state = (recurrent_state, stoch.reshape(1, self.num_envs, -1), actions)
        else:
            recurrent_state, stochastic_state, actions = self.state
            reset = np.zeros((self.num_envs,), dtype=bool)
            reset[np.asarray(reset_envs)] = True
            mask = jnp.asarray(reset)[None, :, None]
            init_rec, init_stoch = self.rssm.initial_states(self.wm_params, (1, self.num_envs))
            self.state = (
                jnp.where(mask, init_rec, recurrent_state),
                jnp.where(mask, init_stoch.reshape(1, self.num_envs, -1), stochastic_state),
                jnp.where(mask, 0.0, actions),
            )

    def get_actions(self, obs: Dict[str, jax.Array], key: jax.Array, greedy: bool = False, mask=None):
        # getattr: custom actors (e.g. PonderActor) aren't Actor subclasses
        if not getattr(self.actor, "uses_action_mask", False):
            mask = None  # avoids re-tracing _step on mask presence for mask-free actors
        actions_list, self.state = self._step(
            self.wm_params, self.actor_params, self.state, obs, key, greedy=greedy, mask=mask
        )
        return actions_list

    def get_actions_packed(self, codec, packed: jax.Array, key: jax.Array, greedy: bool = False):
        """Like get_actions but fed by ONE packed host->device transfer (see
        core/pipeline.PackedObsCodec): unpack + normalize + the ``mask_*``-key
        action-mask extraction all run in-graph."""
        fn = self.packed_step_fn(codec, greedy=greedy)
        actions_list, self.state = fn(self.wm_params, self.actor_params, self.state, packed, key)
        return actions_list

    def packed_step_fn(self, codec, greedy: bool = False):
        """The guarded jitted packed-step entry point for ``codec`` (exposed so
        the train loop can register its AOT warmup before the rollout starts).
        greedy/mask-usage close over the trace — no static args, AOT-friendly."""
        use_mask = bool(getattr(self.actor, "uses_action_mask", False))
        cache_key = (codec.signature, bool(greedy), use_mask)
        fn = self._packed_step_fns.get(cache_key)
        if fn is None:

            def _packed(wm_params, actor_params, state, packed, key):
                obs = codec.decode_obs(packed)
                mask = None
                if use_mask:
                    mask = {k: v for k, v in obs.items() if k.startswith("mask")} or None
                return self._raw_step(wm_params, actor_params, state, obs, key, greedy=greedy, mask=mask)

            fn = jax_compile.guarded_jit(_packed, name="dv3.step_packed")
            self._packed_step_fns[cache_key] = fn
        return fn


class DV3Modules(NamedTuple):
    """Static module definitions shared by the train step and the player."""

    encoder: MultiEncoderDV3
    rssm: RSSM
    observation_model: MultiDecoderDV3
    reward_model: MLPWithHead
    continue_model: MLPWithHead
    actor: Actor
    critic: MLPWithHead


def _ln_enabled(ln_cfg: Dict[str, Any]) -> Tuple[bool, float]:
    """Parse a reference-style layer_norm config {cls: ..., kw: {eps}} to (enabled, eps)."""
    if ln_cfg is None:
        return True, 1e-3
    cls = str(ln_cfg.get("cls", "LayerNorm"))
    enabled = not cls.rsplit(".", 1)[-1].lower().startswith("identity")
    eps = float(ln_cfg.get("kw", {}).get("eps", 1e-3))
    return enabled, eps


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Dict[str, Any]] = None,
    actor_state: Optional[Dict[str, Any]] = None,
    critic_state: Optional[Dict[str, Any]] = None,
    target_critic_state: Optional[Dict[str, Any]] = None,
    build_actor: bool = True,
) -> Tuple[DV3Modules, Dict[str, Any], PlayerDV3]:
    """Build module defs + init params (reference agent.py:935-1260).

    Returns (modules, params, player) where params is a dict with keys
    ``world_model``, ``actor``, ``critic``, ``target_critic``. With
    ``build_actor=False`` the actor and player are skipped (``None`` in the
    results) — for callers that supply their own actor (e.g. dream_and_ponder).
    """
    world_model_cfg = cfg.algo.world_model
    actor_cfg = cfg.algo.actor
    critic_cfg = cfg.algo.critic

    recurrent_state_size = int(world_model_cfg.recurrent_model.recurrent_state_size)
    stochastic_size = int(world_model_cfg.stochastic_size) * int(world_model_cfg.discrete_size)
    latent_state_size = stochastic_size + recurrent_state_size
    compute_dtype = runtime.compute_dtype
    param_dtype = jnp.float32

    cnn_stages = int(np.log2(cfg.env.screen_size) - np.log2(4))
    cnn_ln, cnn_eps = _ln_enabled(world_model_cfg.encoder.get("cnn_layer_norm"))
    mlp_ln, mlp_eps = _ln_enabled(world_model_cfg.encoder.get("mlp_layer_norm"))
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)

    cnn_encoder = (
        CNNEncoder(
            keys=cnn_keys,
            input_channels=[int(np.prod(obs_space[k].shape[:-2])) for k in cnn_keys],
            image_size=tuple(obs_space[cnn_keys[0]].shape[-2:]),
            channels_multiplier=int(world_model_cfg.encoder.cnn_channels_multiplier),
            layer_norm=cnn_ln,
            layer_norm_eps=cnn_eps,
            activation=world_model_cfg.encoder.cnn_act,
            stages=cnn_stages,
            dtype=compute_dtype,
            param_dtype=param_dtype,
        )
        if len(cnn_keys) > 0
        else None
    )
    mlp_encoder = (
        MLPEncoder(
            keys=mlp_keys,
            input_dims=[int(obs_space[k].shape[0]) for k in mlp_keys],
            mlp_layers=int(world_model_cfg.encoder.mlp_layers),
            dense_units=int(world_model_cfg.encoder.dense_units),
            layer_norm=mlp_ln,
            layer_norm_eps=mlp_eps,
            activation=world_model_cfg.encoder.dense_act,
            dtype=compute_dtype,
            param_dtype=param_dtype,
        )
        if len(mlp_keys) > 0
        else None
    )
    encoder = MultiEncoderDV3(cnn_encoder, mlp_encoder)

    rec_ln, rec_eps = _ln_enabled(world_model_cfg.recurrent_model.get("layer_norm"))
    recurrent_model = RecurrentModel(
        input_size=int(sum(actions_dim) + stochastic_size),
        recurrent_state_size=recurrent_state_size,
        dense_units=int(world_model_cfg.recurrent_model.dense_units),
        layer_norm=rec_ln,
        layer_norm_eps=rec_eps,
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )
    decoupled = bool(world_model_cfg.get("decoupled_rssm", False))
    repr_input = encoder.output_dim + (0 if decoupled else recurrent_state_size)
    repr_ln, repr_eps = _ln_enabled(world_model_cfg.representation_model.get("layer_norm"))
    representation_model = MLPWithHead(
        input_dim=repr_input,
        hidden_sizes=[int(world_model_cfg.representation_model.hidden_size)],
        output_dim=stochastic_size,
        activation=world_model_cfg.representation_model.dense_act,
        layer_norm=repr_ln,
        layer_norm_eps=repr_eps,
        head_init_scale=1.0 if cfg.algo.hafner_initialization else -1.0,
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )
    trans_ln, trans_eps = _ln_enabled(world_model_cfg.transition_model.get("layer_norm"))
    transition_model = MLPWithHead(
        input_dim=recurrent_state_size,
        hidden_sizes=[int(world_model_cfg.transition_model.hidden_size)],
        output_dim=stochastic_size,
        activation=world_model_cfg.transition_model.dense_act,
        layer_norm=trans_ln,
        layer_norm_eps=trans_eps,
        head_init_scale=1.0 if cfg.algo.hafner_initialization else -1.0,
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )
    rssm = RSSM(
        recurrent_model=recurrent_model,
        representation_model=representation_model,
        transition_model=transition_model,
        stochastic_size=int(world_model_cfg.stochastic_size),
        discrete_size=int(world_model_cfg.discrete_size),
        unimix=float(cfg.algo.unimix),
        learnable_initial_recurrent_state=bool(world_model_cfg.get("learnable_initial_recurrent_state", True)),
        decoupled=decoupled,
        dynamic_scan_unroll=int(world_model_cfg.get("dynamic_scan_unroll", 1)),
        kernels=str(world_model_cfg.get("kernels", "off")),
    )

    cnn_keys_dec = list(cfg.algo.cnn_keys.decoder)
    mlp_keys_dec = list(cfg.algo.mlp_keys.decoder)
    obs_cnn_ln, obs_cnn_eps = _ln_enabled(world_model_cfg.observation_model.get("cnn_layer_norm"))
    obs_mlp_ln, obs_mlp_eps = _ln_enabled(world_model_cfg.observation_model.get("mlp_layer_norm"))
    cnn_decoder = (
        CNNDecoder(
            keys=cnn_keys_dec,
            output_channels=[int(np.prod(obs_space[k].shape[:-2])) for k in cnn_keys_dec],
            channels_multiplier=int(world_model_cfg.observation_model.cnn_channels_multiplier),
            cnn_encoder_output_dim=cnn_encoder.output_dim,
            image_size=tuple(obs_space[cnn_keys_dec[0]].shape[-2:]),
            layer_norm=obs_cnn_ln,
            layer_norm_eps=obs_cnn_eps,
            activation=world_model_cfg.observation_model.cnn_act,
            stages=cnn_stages,
            dtype=compute_dtype,
            param_dtype=param_dtype,
        )
        if len(cnn_keys_dec) > 0
        else None
    )
    mlp_decoder = (
        MLPDecoder(
            keys=mlp_keys_dec,
            output_dims=[int(obs_space[k].shape[0]) for k in mlp_keys_dec],
            mlp_layers=int(world_model_cfg.observation_model.mlp_layers),
            dense_units=int(world_model_cfg.observation_model.dense_units),
            layer_norm=obs_mlp_ln,
            layer_norm_eps=obs_mlp_eps,
            activation=world_model_cfg.observation_model.dense_act,
            dtype=compute_dtype,
            param_dtype=param_dtype,
        )
        if len(mlp_keys_dec) > 0
        else None
    )
    observation_model = MultiDecoderDV3(cnn_decoder, mlp_decoder)

    rew_ln, rew_eps = _ln_enabled(world_model_cfg.reward_model.get("layer_norm"))
    reward_model = MLPWithHead(
        input_dim=latent_state_size,
        hidden_sizes=[int(world_model_cfg.reward_model.dense_units)] * int(world_model_cfg.reward_model.mlp_layers),
        output_dim=int(world_model_cfg.reward_model.bins),
        activation=world_model_cfg.reward_model.dense_act,
        layer_norm=rew_ln,
        layer_norm_eps=rew_eps,
        head_init_scale=0.0 if cfg.algo.hafner_initialization else -1.0,
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )
    cont_ln, cont_eps = _ln_enabled(world_model_cfg.discount_model.get("layer_norm"))
    continue_model = MLPWithHead(
        input_dim=latent_state_size,
        hidden_sizes=[int(world_model_cfg.discount_model.dense_units)]
        * int(world_model_cfg.discount_model.mlp_layers),
        output_dim=1,
        activation=world_model_cfg.discount_model.dense_act,
        layer_norm=cont_ln,
        layer_norm_eps=cont_eps,
        head_init_scale=1.0 if cfg.algo.hafner_initialization else -1.0,
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )

    actor_ln, actor_eps = _ln_enabled(actor_cfg.get("layer_norm"))
    # Config-selected actor class (reference uses hydra.utils.get_class on
    # cfg.algo.actor.cls, agent.py:1184): MinedojoActor adds rollout-time masking
    actor_cls = resolve_actor_cls(actor_cfg.get("cls"), Actor, MinedojoActor)
    actor = None if not build_actor else actor_cls(
        latent_state_size=latent_state_size,
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=cfg.distribution.get("type", "auto"),
        init_std=float(actor_cfg.init_std),
        min_std=float(actor_cfg.min_std),
        max_std=float(actor_cfg.get("max_std", 1.0)),
        dense_units=int(actor_cfg.dense_units),
        mlp_layers=int(actor_cfg.mlp_layers),
        layer_norm=actor_ln,
        layer_norm_eps=actor_eps,
        activation=actor_cfg.dense_act,
        unimix=float(cfg.algo.unimix),
        action_clip=float(actor_cfg.get("action_clip", 1.0)),
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )
    critic_ln, critic_eps = _ln_enabled(critic_cfg.get("layer_norm"))
    critic = MLPWithHead(
        input_dim=latent_state_size,
        hidden_sizes=[int(critic_cfg.dense_units)] * int(critic_cfg.mlp_layers),
        output_dim=int(critic_cfg.bins),
        activation=critic_cfg.dense_act,
        layer_norm=critic_ln,
        layer_norm_eps=critic_eps,
        head_init_scale=0.0 if cfg.algo.hafner_initialization else -1.0,
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )

    # ---- init params
    key = jax.random.PRNGKey(cfg.seed)
    keys = jax.random.split(key, 10)
    dummy_obs: Dict[str, jax.Array] = {}
    for k in cnn_keys:
        dummy_obs[k] = jnp.zeros((1, int(np.prod(obs_space[k].shape[:-2])), *obs_space[k].shape[-2:]))
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((1, int(obs_space[k].shape[0])))
    wm_params: Dict[str, Any] = {}
    wm_params["encoder"] = encoder.init(keys[0], dummy_obs)
    wm_params["recurrent_model"] = recurrent_model.init(
        keys[1], jnp.zeros((1, int(sum(actions_dim)) + stochastic_size)), jnp.zeros((1, recurrent_state_size))
    )
    wm_params["representation_model"] = representation_model.init(keys[2], jnp.zeros((1, repr_input)))
    wm_params["transition_model"] = transition_model.init(keys[3], jnp.zeros((1, recurrent_state_size)))
    wm_params["observation_model"] = observation_model.init(keys[4], jnp.zeros((1, latent_state_size)))
    wm_params["reward_model"] = reward_model.init(keys[5], jnp.zeros((1, latent_state_size)))
    wm_params["continue_model"] = continue_model.init(keys[6], jnp.zeros((1, latent_state_size)))
    wm_params["initial_recurrent_state"] = jnp.zeros((recurrent_state_size,), dtype=jnp.float32)
    actor_params = actor.init(keys[7], jnp.zeros((1, latent_state_size))) if build_actor else None
    critic_params = critic.init(keys[8], jnp.zeros((1, latent_state_size)))

    if world_model_state:
        wm_params = jax.tree_util.tree_map(jnp.asarray, world_model_state)
    if actor_state and build_actor:
        actor_params = jax.tree_util.tree_map(jnp.asarray, actor_state)
    if critic_state:
        critic_params = jax.tree_util.tree_map(jnp.asarray, critic_state)
    target_critic_params = (
        jax.tree_util.tree_map(jnp.asarray, target_critic_state)
        if target_critic_state
        else copy.deepcopy(critic_params)
    )

    modules = DV3Modules(
        encoder=encoder,
        rssm=rssm,
        observation_model=observation_model,
        reward_model=reward_model,
        continue_model=continue_model,
        actor=actor,
        critic=critic,
    )
    params = {
        "world_model": wm_params,
        "actor": actor_params,
        "critic": critic_params,
        "target_critic": target_critic_params,
    }

    player = None
    if build_actor:
        player = PlayerDV3(
            encoder=encoder,
            rssm=rssm,
            actor=actor,
            actions_dim=actions_dim,
            num_envs=cfg.env.num_envs,
            stochastic_size=int(world_model_cfg.stochastic_size),
            recurrent_state_size=recurrent_state_size,
            discrete_size=int(world_model_cfg.discrete_size),
        )
        player.wm_params = wm_params
        player.actor_params = actor_params
    return modules, params, player
