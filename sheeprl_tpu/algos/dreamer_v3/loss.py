"""DreamerV3 world-model loss (reference sheeprl/algos/dreamer_v3/loss.py:9-91).

Pure JAX: KL-balanced dynamics/representation losses with free nats, plus
observation / reward / continue log-likelihood terms. All terms are per-element
``[T, B]`` and averaged once at the end (Eq. 4/5 of the paper).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def categorical_kl(p_logits: jax.Array, q_logits: jax.Array) -> jax.Array:
    """KL(p || q) for factorized categoricals; inputs ``[..., stoch, discrete]``,
    output summed over the stoch dim -> ``[...]``.

    f32 island (precision audit, ROADMAP 3a): under bf16-mixed the RSSM hands
    over bf16 logits and the KL is a difference of near-equal log-sum-exps
    accumulated over stoch*discrete terms — bf16's 8 mantissa bits lose the
    free-nats comparison long before the loss does. Pinning the logits here is
    a no-op for f32 runs (health-off bit-parity preserved) and the fused-kernel
    path already emits f32 logits.
    """
    p_log = jax.nn.log_softmax(p_logits.astype(jnp.float32), axis=-1)
    q_log = jax.nn.log_softmax(q_logits.astype(jnp.float32), axis=-1)
    p = jnp.exp(p_log)
    return jnp.sum(p * (p_log - q_log), axis=(-2, -1))


def reconstruction_loss(
    po_log_probs: Dict[str, jax.Array],
    pr_log_prob: jax.Array,
    priors_logits: jax.Array,
    posteriors_logits: jax.Array,
    kl_dynamic: float = 0.5,
    kl_representation: float = 0.1,
    kl_free_nats: float = 1.0,
    kl_regularizer: float = 1.0,
    pc_log_prob: Optional[jax.Array] = None,
    continue_scale_factor: float = 1.0,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Compute the total world-model loss.

    Args mirror the reference but take precomputed per-element log-probs (the
    distribution objects are constructed at the call site so this stays a pure
    array->array function):
      po_log_probs: decoder log-probs per key, each ``[T, B]``.
      pr_log_prob: reward log-prob ``[T, B]``.
      priors_logits/posteriors_logits: ``[T, B, stoch, discrete]``.
      pc_log_prob: continue log-prob ``[T, B]`` or None.

    Returns (loss, kl, state_loss, reward_loss, observation_loss, continue_loss).
    """
    observation_loss = -sum(po_log_probs.values())
    reward_loss = -pr_log_prob
    kl = categorical_kl(jax.lax.stop_gradient(posteriors_logits), priors_logits)
    dyn_loss = kl_dynamic * jnp.maximum(kl, kl_free_nats)
    repr_kl = categorical_kl(posteriors_logits, jax.lax.stop_gradient(priors_logits))
    repr_loss = kl_representation * jnp.maximum(repr_kl, kl_free_nats)
    kl_loss = dyn_loss + repr_loss
    if pc_log_prob is not None:
        continue_loss = continue_scale_factor * -pc_log_prob
    else:
        continue_loss = jnp.zeros_like(reward_loss)
    loss = jnp.mean(kl_regularizer * kl_loss + observation_loss + reward_loss + continue_loss)
    return (
        loss,
        kl.mean(),
        kl_loss.mean(),
        reward_loss.mean(),
        observation_loss.mean(),
        continue_loss.mean(),
    )
