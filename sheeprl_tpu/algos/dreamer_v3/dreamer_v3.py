"""DreamerV3, coupled training (reference sheeprl/algos/dreamer_v3/dreamer_v3.py:48-393).

TPU-first train step: per iteration the buffer is sampled once for all G gradient
steps ([G, T, B, *] batch) and ONE jitted call `lax.scan`s over G. Each gradient step
fuses (a) the world-model update — encoder forward batched over [T,B], RSSM dynamic
unrolled by `lax.scan` over T (the reference loops in Python, dreamer_v3.py:138-151) —
(b) the actor update with the H-step imagination `lax.scan` differentiated end-to-end,
and (c) the two-hot critic update with an in-graph conditional target-critic EMA.
The batch axis is sharded over the `data` mesh axis; XLA inserts the gradient
all-reduce over ICI (replacing Fabric DDP), and the Moments quantile runs on the
global batch (replacing the reference's fabric.all_gather, utils.py:57).
"""

from __future__ import annotations

import os
import time
import warnings
from functools import partial
from typing import Any, Dict, NamedTuple, Sequence

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.algos.dreamer_v3.agent import ActorOutput, DV3Modules, build_agent
from sheeprl_tpu.algos.dreamer_v3.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v3.utils import (
    MomentsState,
    compute_lambda_values,
    init_moments,
    test,
    update_moments,
)
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.core import health as health_mod
from sheeprl_tpu.core import resilience
from sheeprl_tpu.core.pipeline import AsyncEnvStepper, PackedObsCodec, pipeline_enabled
from sheeprl_tpu.data.factory import make_sequential_replay
from sheeprl_tpu.envs.wrappers import RestartOnException
from sheeprl_tpu.telemetry import device as tel_device
from sheeprl_tpu.ops.distributions import (
    BernoulliSafeMode,
    Independent,
    MSEDistribution,
    OneHotCategorical,
    SymlogDistribution,
    TwoHotEncodingDistribution,
)
from sheeprl_tpu.utils.env import finished_episodes, final_observations, make_env, vectorized_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.optim import with_clipping
from sheeprl_tpu.utils.profiler import TraceProfiler
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import (
    NUMPY_TO_JAX_DTYPE,
    DreamerPlayerSync,
    Ratio,
    polyak_update,
    save_configs,
)

# Obs->latent->action world-model subset the rollout player needs (see
# PlayerDV3._raw_step / RSSM.initial_states); shipped to the player device by
# DreamerPlayerSync instead of the full world model.
PLAYER_WM_KEYS = (
    "encoder",
    "recurrent_model",
    "representation_model",
    "transition_model",
    "initial_recurrent_state",
)


class DV3OptStates(NamedTuple):
    world: Any
    actor: Any
    critic: Any


def make_train_fn(modules: DV3Modules, cfg, runtime, is_continuous: bool, actions_dim: Sequence[int], psync=None):
    """Build (init_opt, train) where train is a single jitted scan over G gradient steps."""
    if int(cfg.algo.get("grad_microbatches", 1) or 1) > 1:
        # DV3's world-model/actor/critic updates chain through the latent
        # rollout — chunking the [B, T] batch would change the sequence model's
        # statistics, not just the reduction order
        warnings.warn(
            "algo.grad_microbatches > 1 is not supported by DreamerV3; falling back to 1"
        )
    rssm = modules.rssm
    horizon = int(cfg.algo.horizon)
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    ent_coef = float(cfg.algo.actor.ent_coef)
    kl_dynamic = float(cfg.algo.world_model.kl_dynamic)
    kl_representation = float(cfg.algo.world_model.kl_representation)
    kl_free_nats = float(cfg.algo.world_model.kl_free_nats)
    kl_regularizer = float(cfg.algo.world_model.kl_regularizer)
    continue_scale_factor = float(cfg.algo.world_model.continue_scale_factor)
    stoch_size = rssm.stoch_state_size
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_keys_dec = list(cfg.algo.cnn_keys.decoder)
    mlp_keys_dec = list(cfg.algo.mlp_keys.decoder)
    target_freq = int(cfg.algo.critic.per_rank_target_network_update_freq)
    tau = float(cfg.algo.critic.tau)
    moments_cfg = cfg.algo.actor.moments
    actor_objective = str(cfg.algo.actor.get("objective", "auto"))
    if actor_objective not in ("auto", "reinforce"):
        raise ValueError(
            f"algo.actor.objective must be 'auto' or 'reinforce', got {actor_objective!r}"
        )
    imagination_unroll = int(cfg.algo.get("imagination_scan_unroll", 1))
    data_sharding = NamedSharding(runtime.mesh, P(None, "data"))
    nonfinite_guard = resilience.guard_enabled(resilience.resolve(cfg))

    world_tx = with_clipping(
        instantiate(dict(cfg.algo.world_model.optimizer))(), cfg.algo.world_model.clip_gradients
    )
    actor_tx = with_clipping(instantiate(dict(cfg.algo.actor.optimizer))(), cfg.algo.actor.clip_gradients)
    critic_tx = with_clipping(instantiate(dict(cfg.algo.critic.optimizer))(), cfg.algo.critic.clip_gradients)

    def init_opt(params) -> DV3OptStates:
        return DV3OptStates(
            world=world_tx.init(params["world_model"]),
            actor=actor_tx.init(params["actor"]),
            critic=critic_tx.init(params["critic"]),
        )

    def one_step(carry, inp):
        params, opt_states, moments_state, counter = carry
        data, key = inp
        data = jax.tree_util.tree_map(lambda v: jax.lax.with_sharding_constraint(v, data_sharding), data)
        k_wm, k_img0, k_img, k_actor = jax.random.split(key, 4)

        # ---- target critic EMA (reference dreamer_v3.py:740-753): tau=1 on first step
        def do_ema(tc):
            tau_eff = jnp.where(counter == 0, 1.0, tau)
            return jax.tree_util.tree_map(
                lambda p, tp: tau_eff * p + (1.0 - tau_eff) * tp, params["critic"], tc
            )

        target_critic = jax.lax.cond(
            counter % target_freq == 0, do_ema, lambda tc: tc, params["target_critic"]
        )

        # ---- batch prep (in-graph: uint8 pixels stay uint8 until HBM)
        # batch_obs stays f32: these are the reconstruction-loss TARGETS (an f32
        # island of the precision audit). The encoder gets a compute-dtype view
        # below — its first layer casts anyway, so the values reaching the first
        # matmul are bitwise identical, but casting at the batch boundary stops
        # XLA from materializing the [T,B,C,H,W] normalization in f32 under
        # bf16-mixed (pure HBM-traffic win, audited in howto/performance.md).
        batch_obs = {k: data[k].astype(jnp.float32) / 255.0 - 0.5 for k in cnn_keys}
        batch_obs.update({k: data[k].astype(jnp.float32) for k in mlp_keys})
        encoder_obs = {k: v.astype(runtime.compute_dtype) for k, v in batch_obs.items()}
        is_first = data["is_first"].astype(jnp.float32).at[0].set(1.0)
        actions = data["actions"].astype(jnp.float32)
        batch_actions = jnp.concatenate([jnp.zeros_like(actions[:1]), actions[:-1]], axis=0)
        rewards = data["rewards"].astype(jnp.float32)
        continues_targets = 1.0 - data["terminated"].astype(jnp.float32)

        # ---- world-model update (Eq. 4)
        def world_loss_fn(wm_params):
            embedded = modules.encoder.apply(wm_params["encoder"], encoder_obs)
            recurrent_states, posteriors, priors_logits, posteriors_logits = rssm.dynamic_scan(
                wm_params, embedded, batch_actions, is_first, k_wm
            )
            latent_states = jnp.concatenate(
                [posteriors.reshape(*posteriors.shape[:-2], -1), recurrent_states], axis=-1
            )
            reconstructed = modules.observation_model.apply(wm_params["observation_model"], latent_states)
            po_log_probs = {
                k: MSEDistribution(reconstructed[k], dims=reconstructed[k].ndim - 2).log_prob(batch_obs[k])
                for k in cnn_keys_dec
            }
            po_log_probs.update(
                {
                    k: SymlogDistribution(reconstructed[k], dims=reconstructed[k].ndim - 2).log_prob(batch_obs[k])
                    for k in mlp_keys_dec
                }
            )
            pr = TwoHotEncodingDistribution(
                modules.reward_model.apply(wm_params["reward_model"], latent_states), dims=1
            )
            pc = Independent(
                BernoulliSafeMode(logits=modules.continue_model.apply(wm_params["continue_model"], latent_states)),
                1,
            )
            loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
                po_log_probs,
                pr.log_prob(rewards),
                priors_logits,
                posteriors_logits,
                kl_dynamic,
                kl_representation,
                kl_free_nats,
                kl_regularizer,
                pc.log_prob(continues_targets),
                continue_scale_factor,
            )
            aux = {
                "posteriors": posteriors,
                "recurrent_states": recurrent_states,
                "priors_logits": priors_logits,
                "posteriors_logits": posteriors_logits,
                "kl": kl,
                "state_loss": state_loss,
                "reward_loss": reward_loss,
                "observation_loss": observation_loss,
                "continue_loss": continue_loss,
            }
            return loss, aux

        (world_loss, aux), world_grads = jax.value_and_grad(world_loss_fn, has_aux=True)(params["world_model"])
        world_grad_norm = optax_global_norm(world_grads)
        world_updates, world_opt = world_tx.update(world_grads, opt_states.world, params["world_model"])
        new_wm = apply_updates(params["world_model"], world_updates)
        if nonfinite_guard:
            # a skipped world update also feeds the OLD world model to imagination below
            (new_wm, world_opt), wm_skipped = resilience.finite_or_skip(
                (world_loss, world_grad_norm), (new_wm, world_opt), (params["world_model"], opt_states.world)
            )
        else:
            wm_skipped = jnp.float32(0.0)

        # ---- behaviour learning: imagination with the freshly-updated world model
        posteriors = jax.lax.stop_gradient(aux["posteriors"])  # [T, B, S, D]
        recurrent_states = jax.lax.stop_gradient(aux["recurrent_states"])  # [T, B, R]
        start_prior = posteriors.reshape(1, -1, stoch_size)[0]  # [T*B, S*D]
        start_recurrent = recurrent_states.reshape(1, -1, recurrent_states.shape[-1])[0]
        true_continue = continues_targets.reshape(-1, 1)  # [T*B, 1]

        def imagine(actor_params, key0, keys):
            """H+1-step differentiable imagination -> (trajectories, clipped actions,
            raw pre-clip samples — the score-function evaluation points)."""
            latent0 = jnp.concatenate([start_prior, start_recurrent], axis=-1)
            out0 = ActorOutput(modules.actor, modules.actor.apply(actor_params, jax.lax.stop_gradient(latent0)))
            acts0, raws0 = out0.sample_actions_with_raw(key0)
            actions0 = jnp.concatenate(acts0, axis=-1)
            raw0 = jnp.concatenate(raws0, axis=-1)

            def step(carry, k):
                prior_flat, rec_state, act = carry
                k_img_step, k_act_step = jax.random.split(k)
                prior, rec_state = rssm.imagination_step(new_wm, prior_flat, rec_state, act, k_img_step)
                prior_flat = prior.reshape(prior_flat.shape)
                latent = jnp.concatenate([prior_flat, rec_state], axis=-1)
                out = ActorOutput(
                    modules.actor, modules.actor.apply(actor_params, jax.lax.stop_gradient(latent))
                )
                new_acts, new_raws = out.sample_actions_with_raw(k_act_step)
                new_act = jnp.concatenate(new_acts, axis=-1)
                new_raw = jnp.concatenate(new_raws, axis=-1)
                return (prior_flat, rec_state, new_act), (latent, new_act, new_raw)

            _, (latents, acts, raws) = jax.lax.scan(
                step, (start_prior, start_recurrent, actions0), keys, unroll=imagination_unroll
            )
            trajectories = jnp.concatenate([latent0[None], latents], axis=0)  # [H+1, TB, L]
            im_actions = jnp.concatenate([actions0[None], acts], axis=0)  # [H+1, TB, A]
            im_actions_raw = jnp.concatenate([raw0[None], raws], axis=0)  # [H+1, TB, A]
            return trajectories, im_actions, im_actions_raw

        img_keys = jax.random.split(k_img, horizon)

        def actor_loss_fn(actor_params):
            trajectories, im_actions, im_actions_raw = imagine(actor_params, k_img0, img_keys)
            predicted_values = TwoHotEncodingDistribution(
                modules.critic.apply(params["critic"], trajectories), dims=1
            ).mean
            predicted_rewards = TwoHotEncodingDistribution(
                modules.reward_model.apply(new_wm["reward_model"], trajectories), dims=1
            ).mean
            continues = Independent(
                BernoulliSafeMode(logits=modules.continue_model.apply(new_wm["continue_model"], trajectories)), 1
            ).base.mode
            continues = jnp.concatenate([true_continue[None], continues[1:]], axis=0)
            lambda_values = compute_lambda_values(
                predicted_rewards[1:], predicted_values[1:], continues[1:] * gamma, lmbda=lmbda
            )
            discount = jax.lax.stop_gradient(jnp.cumprod(continues * gamma, axis=0) / gamma)

            offset, invscale, new_moments = update_moments(
                moments_state,
                lambda_values,
                decay=float(moments_cfg.decay),
                max_=float(moments_cfg.max),
                percentile_low=float(moments_cfg.percentile.low),
                percentile_high=float(moments_cfg.percentile.high),
            )
            baseline = predicted_values[:-1]
            normed_lambda = (lambda_values - offset) / invscale
            normed_baseline = (baseline - offset) / invscale
            advantage = normed_lambda - normed_baseline
            policies = ActorOutput(
                modules.actor, modules.actor.apply(actor_params, jax.lax.stop_gradient(trajectories))
            )
            if is_continuous and actor_objective != "reinforce":
                # reference parity: direct advantage (dynamics backprop) for
                # continuous actions. The walker_walk forensics measured this
                # gradient as noise-dominated at the trained-policy state
                # (key-to-key update cosine ~0, benchmarks/WALKER_WALK_NOTES.md);
                # algo.actor.objective=reinforce opts continuous actors into the
                # low-variance score-function estimator the discrete branch uses
                # (the DreamerV3 paper's own default for all action spaces).
                objective = advantage
            else:
                # score-function estimator: log-prob evaluated at the RAW samples
                # (clipping rescales saturated continuous actions onto the
                # boundary, where the clipped point's log-prob is not the
                # sampled policy's score; discrete raw == clipped)
                splits = np.cumsum(np.asarray(actions_dim))[:-1]
                action_parts = jnp.split(jax.lax.stop_gradient(im_actions_raw), splits, axis=-1)
                log_probs = sum(
                    d.log_prob(a) for d, a in zip(policies.dists, action_parts)
                )  # [H+1, TB]
                objective = log_probs[..., None][:-1] * jax.lax.stop_gradient(advantage)
            try:
                entropy = ent_coef * policies.entropy()
            except NotImplementedError:
                entropy = jnp.zeros(trajectories.shape[:-1], dtype=jnp.float32)
            policy_loss = -jnp.mean(
                jax.lax.stop_gradient(discount[:-1]) * (objective + entropy[..., None][:-1])
            )
            aux_a = {
                "trajectories": trajectories,
                "lambda_values": lambda_values,
                "discount": discount,
                "moments": new_moments,
            }
            return policy_loss, aux_a

        (policy_loss, aux_a), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(params["actor"])
        actor_grad_norm = optax_global_norm(actor_grads)
        actor_updates, actor_opt = actor_tx.update(actor_grads, opt_states.actor, params["actor"])
        new_actor = apply_updates(params["actor"], actor_updates)
        if nonfinite_guard:
            (new_actor, actor_opt), actor_skipped = resilience.finite_or_skip(
                (policy_loss, actor_grad_norm), (new_actor, actor_opt), (params["actor"], opt_states.actor)
            )
        else:
            actor_skipped = jnp.float32(0.0)

        # ---- critic update (Eq. 10) on the pre-update-actor trajectories
        trajectories = jax.lax.stop_gradient(aux_a["trajectories"])
        lambda_values = jax.lax.stop_gradient(aux_a["lambda_values"])
        discount = aux_a["discount"]

        def critic_loss_fn(critic_params):
            qv = TwoHotEncodingDistribution(modules.critic.apply(critic_params, trajectories[:-1]), dims=1)
            predicted_target_values = TwoHotEncodingDistribution(
                modules.critic.apply(target_critic, trajectories[:-1]), dims=1
            ).mean
            value_loss = -qv.log_prob(lambda_values) - qv.log_prob(
                jax.lax.stop_gradient(predicted_target_values)
            )
            return jnp.mean(value_loss * discount[:-1][..., 0])

        value_loss, critic_grads = jax.value_and_grad(critic_loss_fn)(params["critic"])
        critic_grad_norm = optax_global_norm(critic_grads)
        critic_updates, critic_opt = critic_tx.update(critic_grads, opt_states.critic, params["critic"])
        new_critic = apply_updates(params["critic"], critic_updates)
        if nonfinite_guard:
            (new_critic, critic_opt), critic_skipped = resilience.finite_or_skip(
                (value_loss, critic_grad_norm), (new_critic, critic_opt), (params["critic"], opt_states.critic)
            )
        else:
            critic_skipped = jnp.float32(0.0)

        # f32 island: entropy is a sum of p*log p terms over discrete*stoch
        # categories — accumulate in f32 even when the RSSM emits bf16 logits
        # (no-op for f32 runs; the fused kernel path already returns f32 logits)
        post_ent = (
            Independent(OneHotCategorical(logits=aux["posteriors_logits"].astype(jnp.float32)), 1)
            .entropy()
            .mean()
        )
        prior_ent = (
            Independent(OneHotCategorical(logits=aux["priors_logits"].astype(jnp.float32)), 1)
            .entropy()
            .mean()
        )
        new_params = {
            "world_model": new_wm,
            "actor": new_actor,
            "critic": new_critic,
            "target_critic": target_critic,
        }
        metrics = jnp.stack(
            [
                world_loss,
                value_loss,
                policy_loss,
                aux["observation_loss"],
                aux["reward_loss"],
                aux["state_loss"],
                aux["continue_loss"],
                aux["kl"],
                post_ent,
                prior_ent,
                world_grad_norm,
                actor_grad_norm,
                critic_grad_norm,
                # return-normalizer state: the advantage scale divisor is
                # max(1e-8, high-low); its drift is the first thing to check
                # when a policy degrades under a healthy world model+critic
                aux_a["moments"].low,
                aux_a["moments"].high,
                wm_skipped + actor_skipped + critic_skipped,
            ]
        )
        return (new_params, DV3OptStates(world_opt, actor_opt, critic_opt), aux_a["moments"], counter + 1), metrics

    def train(params, opt_states, moments_state, counter, batches, key):
        g = next(iter(batches.values())).shape[0]
        keys = jax.random.split(key, g)
        (params, opt_states, moments_state, counter), metrics = jax.lax.scan(
            one_step, (params, opt_states, moments_state, counter), (batches, keys)
        )
        m = metrics.mean(axis=0)
        named = {
            "Loss/world_model_loss": m[0],
            "Loss/value_loss": m[1],
            "Loss/policy_loss": m[2],
            "Loss/observation_loss": m[3],
            "Loss/reward_loss": m[4],
            "Loss/state_loss": m[5],
            "Loss/continue_loss": m[6],
            "State/kl": m[7],
            "State/post_entropy": m[8],
            "State/prior_entropy": m[9],
            "Grads/world_model": m[10],
            "Grads/actor": m[11],
            "Grads/critic": m[12],
            "State/moments_low": m[13],
            "State/moments_high": m[14],
            "Resilience/nonfinite_skips": metrics[:, 15].sum(),
        }
        # raveled player subset computed in-graph: the host-player refresh is one
        # flat transfer, not a per-leaf pull (see DreamerPlayerSync)
        flat_player = psync.ravel(params) if psync is not None else None
        return params, opt_states, moments_state, counter, flat_player, named

    return init_opt, jax_compile.guarded_jit(train, name="dv3.train", donate_argnums=(0, 1, 2))


def optax_global_norm(tree) -> jax.Array:
    import optax

    return optax.global_norm(tree)


def apply_updates(params, updates):
    import optax

    return optax.apply_updates(params, updates)


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    world_size = runtime.world_size
    rank = runtime.global_rank

    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_state

        state = load_state(cfg.checkpoint.resume_from)

    # These arguments cannot be changed (reference dreamer_v3.py:400-403)
    cfg.env.frame_stack = -1
    if 2 ** int(np.log2(cfg.env.screen_size)) != cfg.env.screen_size:
        raise ValueError(f"The screen size must be a power of 2, got: {cfg.env.screen_size}")

    logger = get_logger(runtime, cfg)
    if logger:
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name, logger=logger)
    runtime.logger = logger
    runtime.print(f"Log dir: {log_dir}")

    ft = resilience.resolve(cfg)
    sentinel = health_mod.HealthSentinel(
        cfg, log_dir=log_dir if runtime.is_global_zero else None, world_size=world_size
    )
    env_fns = [
        make_env(
            cfg,
            cfg.seed + rank * cfg.env.num_envs + i,
            rank * cfg.env.num_envs,
            log_dir if runtime.is_global_zero else None,
            "train",
            vector_env_idx=i,
        )
        for i in range(cfg.env.num_envs)
    ]
    if ft.env_supervision.enabled:
        # WorkerSupervisor supersedes RestartOnException: same restart-on-crash
        # semantics (it emits the same `restart_on_exception` info key the buffer
        # patching below consumes) plus bounded backoff, hang detection via the
        # per-step deadline, and exported restart counters
        envs = resilience.make_supervised_env(env_fns, sync=cfg.env.sync_env, ft=ft)
    else:
        envs = vectorized_env(
            [partial(RestartOnException, fn) for fn in env_fns],
            sync=cfg.env.sync_env,
            step_timeout=ft.env_supervision.step_timeout_s,
        )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if (
        len(set(cfg.algo.cnn_keys.encoder).intersection(set(cfg.algo.cnn_keys.decoder))) == 0
        and len(set(cfg.algo.mlp_keys.encoder).intersection(set(cfg.algo.mlp_keys.decoder))) == 0
    ):
        raise RuntimeError("The CNN keys or the MLP keys of the encoder and decoder must not be disjointed")
    if len(set(cfg.algo.cnn_keys.decoder) - set(cfg.algo.cnn_keys.encoder)) > 0:
        raise RuntimeError(
            "The CNN keys of the decoder must be contained in the encoder ones. "
            f"Those keys are decoded without being encoded: {list(set(cfg.algo.cnn_keys.decoder))}"
        )
    if len(set(cfg.algo.mlp_keys.decoder) - set(cfg.algo.mlp_keys.encoder)) > 0:
        raise RuntimeError(
            "The MLP keys of the decoder must be contained in the encoder ones. "
            f"Those keys are decoded without being encoded: {list(set(cfg.algo.mlp_keys.decoder))}"
        )
    if cfg.metric.log_level > 0:
        runtime.print("Encoder CNN keys:", cfg.algo.cnn_keys.encoder)
        runtime.print("Encoder MLP keys:", cfg.algo.mlp_keys.encoder)
        runtime.print("Decoder CNN keys:", cfg.algo.cnn_keys.decoder)
        runtime.print("Decoder MLP keys:", cfg.algo.mlp_keys.decoder)
    obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)

    modules, params, player = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["world_model"] if state else None,
        state["actor"] if state else None,
        state["critic"] if state else None,
        state["target_critic"] if state else None,
    )

    psync = DreamerPlayerSync(
        runtime, params, wm_keys=PLAYER_WM_KEYS, every=cfg.algo.get("player_sync_every", 1)
    )
    init_opt, train_fn = make_train_fn(modules, cfg, runtime, is_continuous, actions_dim, psync)
    opt_states = init_opt(params)
    if state:
        opt_states = jax.tree_util.tree_map(jnp.asarray, state["opt_states"])
    moments_state = init_moments()
    if state and "moments" in state:
        moments_state = MomentsState(*[jnp.asarray(v) for v in state["moments"]])
    counter = jnp.int32(state["counter"]) if state and "counter" in state else jnp.int32(0)
    params = runtime.place_params(params)
    opt_states = runtime.place_params(opt_states)
    # the player must never hold mesh-resident params when it lives on the host
    # CPU backend: its per-step calls would pay per-leaf cross-backend pulls
    psync.push(player, params, force=True)

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    rb, prefetcher = make_sequential_replay(cfg, runtime, log_dir, obs_keys)
    if state and cfg.buffer.checkpoint and "rb" in state:
        rb.load_state_dict(state["rb"])

    train_step = 0
    last_train = 0
    train_calls = 0
    last_train_calls = 0
    start_iter = (state["iter_num"] // world_size) + 1 if state else 1
    policy_step = state["iter_num"] * cfg.env.num_envs if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(cfg.env.num_envs * world_size)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state:
        ratio.load_state_dict(state["ratio"])


    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    profiler = TraceProfiler(cfg.metric.get("profiler"), log_dir if runtime.is_global_zero else None)
    rng = jax.random.PRNGKey(cfg.seed)
    if state and "rng" in state:
        rng = jnp.asarray(state["rng"])
    step_data: Dict[str, np.ndarray] = {}
    obs = envs.reset(seed=cfg.seed)[0]
    for k in obs_keys:
        step_data[k] = np.asarray(obs[k])[np.newaxis]
    step_data["rewards"] = np.zeros((1, cfg.env.num_envs, 1))
    step_data["truncated"] = np.zeros((1, cfg.env.num_envs, 1))
    step_data["terminated"] = np.zeros((1, cfg.env.num_envs, 1))
    step_data["is_first"] = np.ones_like(step_data["terminated"])
    player.init_states()

    # ----- software pipeline (core/pipeline.py): env workers step while the host
    # writes the pre-step buffer row (the prefetcher lock wait hides behind the
    # env step); obs reach the device as ONE packed put per step
    stepper = AsyncEnvStepper(envs, enabled=pipeline_enabled(cfg))
    codec = PackedObsCodec(
        cnn_keys=cfg.algo.cnn_keys.encoder,
        device=runtime.player_device,
        leading_dims=(1, cfg.env.num_envs),
    )

    # ----- AOT warmup (core/compile.py): compile the packed policy step, the
    # fused world-model/actor/critic train step (for every gradient-step count
    # the Ratio schedule will request) and the metric-drain kernels on a
    # background thread while the prefill rollout collects; the first train
    # call then executes a pre-built executable (trace count 0 at call time).
    warmup = jax_compile.AOTWarmup(enabled=jax_compile.aot_enabled(cfg))
    if warmup.enabled:
        packed0 = codec.encode(obs)
        act_fn = player.packed_step_fn(codec)
        act_specs = (
            jax_compile.specs_of(player.wm_params),
            jax_compile.specs_of(player.actor_params),
            jax_compile.specs_of(player.state),
            jax_compile.spec_like(packed0),
            jax_compile.spec_like(rng),
        )
        warmup.add(act_fn, *act_specs)
        # The recurrent/stochastic state's dtype differs between the reset
        # state (f32 zeros from init_states) and the step's own output (the
        # model's compute dtype, e.g. bf16), and episode resets flip it back:
        # warm the steady-state signature too or step #2 retraces every run.
        _acts_out, state_out = jax.eval_shape(act_fn.fun, *act_specs)
        steady_specs = (
            act_specs[0],
            act_specs[1],
            jax.tree_util.tree_map(lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), state_out),
            act_specs[3],
            act_specs[4],
        )
        if jax_compile.abstract_signature(steady_specs, {}) != jax_compile.abstract_signature(
            act_specs, {}
        ):
            warmup.add(act_fn, *steady_specs)
        # The train step's leading batch dim is the per-iteration gradient-step
        # count: predict the counts the Ratio schedule will yield by replaying
        # the loop's exact arithmetic on a clone (the schedule is periodic
        # after the first few train iterations, so 1024 iterations and 4
        # distinct counts bound the sweep).
        clone = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
        clone.load_state_dict(ratio.state_dict())
        unique_g = []
        sim_policy_step = policy_step
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for sim_iter in range(start_iter, min(total_iters, start_iter + 1024) + 1):
                sim_policy_step += policy_steps_per_iter
                if sim_iter >= learning_starts:
                    g = clone((sim_policy_step - prefill_steps * policy_steps_per_iter) / world_size)
                    if g > 0 and g not in unique_g:
                        unique_g.append(g)
                        if len(unique_g) >= 4:
                            break
        # batch specs mirror the prefetcher's output: [G, L, B, *feat] on the
        # data axis, storage dtypes narrowed exactly like get_array's transfer
        seq_len = int(cfg.algo.per_rank_sequence_length)
        bsz = int(cfg.algo.per_rank_batch_size) * world_size
        batch_sharding = NamedSharding(runtime.mesh, P(None, None, "data"))
        feat = {k: tuple(step_data[k].shape[2:]) for k in obs_keys}
        store_dtype = {k: step_data[k].dtype for k in obs_keys}
        for k in ("rewards", "truncated", "terminated", "is_first"):
            feat[k] = (1,)
            store_dtype[k] = step_data[k].dtype
        feat["actions"] = (int(np.sum(actions_dim)),)
        store_dtype["actions"] = np.dtype(np.float32)
        for g in unique_g:
            batches_spec = {
                k: jax.ShapeDtypeStruct(
                    (g, seq_len, bsz, *feat[k]),
                    NUMPY_TO_JAX_DTYPE.get(np.dtype(store_dtype[k]), jnp.float32),
                    sharding=batch_sharding,
                )
                for k in feat
            }
            warmup.add(
                train_fn,
                jax_compile.specs_of(params),
                jax_compile.specs_of(opt_states),
                jax_compile.specs_of(moments_state),
                jax_compile.spec_like(counter),
                batches_spec,
                jax_compile.spec_like(rng),
            )
        if aggregator is not None:
            warmup.add_task(
                lambda: aggregator.precompile_drain(
                    (
                        "Loss/world_model_loss",
                        "Loss/value_loss",
                        "Loss/policy_loss",
                        "Loss/observation_loss",
                        "Loss/reward_loss",
                        "Loss/state_loss",
                        "Loss/continue_loss",
                        "State/kl",
                        "State/post_entropy",
                        "State/prior_entropy",
                        "Grads/world_model",
                        "Grads/actor",
                        "Grads/critic",
                        "State/moments_low",
                        "State/moments_high",
                        "Resilience/nonfinite_skips",
                    )
                ),
                name="metric.drain",
            )
        warmup.start()

    cumulative_per_rank_gradient_steps = 0
    heartbeat_t0, heartbeat_iter = time.perf_counter(), start_iter

    def _save_checkpoint():
        # shared by the periodic checkpoint and the preemption emergency save so
        # both are resumable through the identical path; the rng chain makes the
        # resumed action/train key sequence identical to an uninterrupted run
        ckpt_state = {
            "world_model": jax.device_get(params["world_model"]),
            "actor": jax.device_get(params["actor"]),
            "critic": jax.device_get(params["critic"]),
            "target_critic": jax.device_get(params["target_critic"]),
            "opt_states": jax.device_get(opt_states),
            "moments": tuple(np.asarray(v) for v in moments_state),
            "counter": int(counter),
            "ratio": ratio.state_dict(),
            "iter_num": iter_num * world_size,
            "batch_size": cfg.algo.per_rank_batch_size * world_size,
            "last_log": last_log,
            "last_checkpoint": last_checkpoint,
            "rng": jax.device_get(rng),
        }
        ckpt_path = os.path.join(log_dir, f"checkpoint/ckpt_{policy_step}_{rank}.ckpt")
        runtime.call(
            "on_checkpoint_coupled",
            ckpt_path=ckpt_path,
            state=ckpt_state,
            replay_buffer=rb if cfg.buffer.checkpoint else None,
            io_lock=prefetcher.guard(),
            healthy=sentinel.certifiable,
            policy_step=policy_step,
        )

    guard = resilience.PreemptionGuard(
        enabled=ft.preemption.enabled, stop_after_iters=ft.preemption.stop_after_iters
    )
    with guard:
        for iter_num in range(start_iter, total_iters + 1):
            profiler.step(policy_step)
            policy_step += policy_steps_per_iter
            if iter_num % 100 == 0 and iter_num > heartbeat_iter:
                now = time.perf_counter()
                runtime.print(
                    f"[hb] iter={iter_num}/{total_iters} policy_step={policy_step} "
                    f"({(iter_num - heartbeat_iter) / (now - heartbeat_t0):.2f} it/s)",
                    flush=True,
                )
                heartbeat_t0, heartbeat_iter = now, iter_num

            with timer("Time/env_interaction_time", SumMetric()):
                if iter_num <= learning_starts and state is None and "minedojo" not in cfg.env.wrapper._target_.lower():
                    real_actions = actions = np.array(envs.action_space.sample())
                    if not is_continuous:
                        actions = np.concatenate(
                            [
                                np.eye(act_dim, dtype=np.float32)[act.reshape(-1)]
                                for act, act_dim in zip(actions.reshape(len(actions_dim), -1), actions_dim)
                            ],
                            axis=-1,
                        )
                else:
                    # ONE packed host->device transfer per step: unpack, normalize
                    # and action-mask extraction run in-graph (PlayerDV3.get_actions_packed)
                    packed = codec.encode(obs)
                    rng, act_key = jax.random.split(rng)
                    actions_list = player.get_actions_packed(codec, packed, act_key)
                    actions = np.concatenate([np.asarray(a) for a in actions_list], axis=-1)
                    if is_continuous:
                        real_actions = actions
                    else:
                        real_actions = np.stack(
                            [np.asarray(a).argmax(axis=-1) for a in actions_list], axis=-1
                        )

                stepper.step_async(real_actions.reshape(envs.action_space.shape))

                # ---- overlap window: env workers are stepping; the pre-step row
                # write (and any wait on the prefetcher's sample lock) hides here
                step_data["actions"] = actions.reshape((1, cfg.env.num_envs, -1))
                with prefetcher.guard():  # no torn rows under the worker's concurrent sample
                    rb.add(step_data, validate_args=cfg.buffer.validate_args)

                next_obs, rewards, terminated, truncated, infos = stepper.step_wait()
                dones = np.logical_or(terminated, truncated).astype(np.uint8)

            step_data["is_first"] = np.zeros_like(step_data["terminated"])
            if "restart_on_exception" in infos:
                for i, agent_roe in enumerate(infos["restart_on_exception"]):
                    if agent_roe and not dones[i]:
                        # crash-restart boundary: the last stored transition becomes a
                        # truncation (works on host and HBM buffers alike)
                        with prefetcher.guard():  # no torn flags under the worker's sample
                            rb.patch_last([i], {"terminated": 0.0, "truncated": 1.0, "is_first": 0.0})
                        step_data["is_first"][0, i] = np.ones_like(step_data["is_first"][0, i])

            if cfg.metric.log_level > 0:
                for i, (ep_rew, ep_len) in enumerate(finished_episodes(infos)):
                    if aggregator:
                        if "Rewards/rew_avg" in aggregator:
                            aggregator.update("Rewards/rew_avg", ep_rew)
                        if "Game/ep_len_avg" in aggregator:
                            aggregator.update("Game/ep_len_avg", ep_len)
                    runtime.print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep_rew}")

            # Save the real next observation (terminal obs for autoreset envs)
            real_next_obs = {k: np.asarray(v).copy() for k, v in next_obs.items() if k in obs_keys}
            finals = final_observations(infos, obs_keys)
            if finals:
                for idx, final_obs in finals.items():
                    for k, v in final_obs.items():
                        real_next_obs[k][idx] = v

            for k in obs_keys:
                step_data[k] = np.asarray(next_obs[k])[np.newaxis]
            obs = next_obs

            rewards = np.asarray(rewards, dtype=np.float32).reshape((1, cfg.env.num_envs, -1))
            step_data["terminated"] = np.asarray(terminated, dtype=np.float32).reshape((1, cfg.env.num_envs, -1))
            step_data["truncated"] = np.asarray(truncated, dtype=np.float32).reshape((1, cfg.env.num_envs, -1))
            step_data["rewards"] = clip_rewards_fn(rewards)

            dones_idxes = dones.nonzero()[0].tolist()
            reset_envs = len(dones_idxes)
            if reset_envs > 0:
                reset_data = {}
                for k in obs_keys:
                    reset_data[k] = (real_next_obs[k][dones_idxes])[np.newaxis]
                reset_data["terminated"] = step_data["terminated"][:, dones_idxes]
                reset_data["truncated"] = step_data["truncated"][:, dones_idxes]
                reset_data["actions"] = np.zeros((1, reset_envs, int(np.sum(actions_dim))))
                reset_data["rewards"] = step_data["rewards"][:, dones_idxes]
                reset_data["is_first"] = np.zeros_like(reset_data["terminated"])
                with prefetcher.guard():
                    rb.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)

                step_data["rewards"][:, dones_idxes] = np.zeros_like(reset_data["rewards"])
                step_data["terminated"][:, dones_idxes] = np.zeros_like(step_data["terminated"][:, dones_idxes])
                step_data["truncated"][:, dones_idxes] = np.zeros_like(step_data["truncated"][:, dones_idxes])
                step_data["is_first"][:, dones_idxes] = np.ones_like(step_data["is_first"][:, dones_idxes])
                player.init_states(dones_idxes)

            # ---- training phase
            if iter_num >= learning_starts:
                ratio_steps = policy_step - prefill_steps * policy_steps_per_iter
                per_rank_gradient_steps = ratio(ratio_steps / world_size)
                if per_rank_gradient_steps > 0 and sentinel.ratio_scale < 1.0:
                    # health-sentinel backoff: shrink this round's gradient grant
                    per_rank_gradient_steps = max(1, int(per_rank_gradient_steps * sentinel.ratio_scale))
                if per_rank_gradient_steps > 0:
                    # steady-state: this consumes the batch prefetched during the previous
                    # train step and immediately starts speculating the next one
                    batches = prefetcher.get(
                        batch_size=cfg.algo.per_rank_batch_size * world_size,
                        sequence_length=cfg.algo.per_rank_sequence_length,
                        n_samples=per_rank_gradient_steps,
                    )
                    with timer("Time/train_time", SumMetric()):
                        # no-op once the warmup thread finished (first train
                        # call at the latest; usually hidden behind prefill)
                        warmup.wait()
                        rng, train_key = jax.random.split(rng)
                        params, opt_states, moments_state, counter, flat_player, train_metrics = train_fn(
                            params, opt_states, moments_state, counter, batches, train_key
                        )
                        if not timer.disabled:
                            # fence ONLY when timing: Time/train_time must include the
                            # device work, but an unconditional sync would serialize the
                            # loop on the dispatch round-trip
                            jax.block_until_ready(params)
                        psync.push(player, params, flat=flat_player)
                        cumulative_per_rank_gradient_steps += per_rank_gradient_steps
                        train_step += world_size * per_rank_gradient_steps
                        train_calls += 1
                    if aggregator:
                        aggregator.update_from_device(train_metrics)
                    resilience.enforce_nonfinite_policy(ft, train_metrics)
            env_deltas = resilience.drain_env_counters(envs, aggregator)
            jax_compile.drain_compile_counters(aggregator)
            if cumulative_per_rank_gradient_steps > 0 and not jax_compile.is_steady():
                # steady-state watermark: the first real train iteration has
                # compiled everything; any retrace from here is a perf cliff
                jax_compile.mark_steady()

            # ----- health sentinel: warn -> backoff (ratio grant above) -> rollback
            action = sentinel.observe(
                policy_step,
                train_metrics=train_metrics if "train_metrics" in dir() else None,
                env_counters=env_deltas,
            )
            if action.rollback:
                rb_state = sentinel.take_rollback_state(os.path.join(log_dir, "checkpoint"))
                if rb_state is not None:
                    params = runtime.place_params(
                        {
                            **params,
                            "world_model": jax.tree_util.tree_map(jnp.asarray, rb_state["world_model"]),
                            "actor": jax.tree_util.tree_map(jnp.asarray, rb_state["actor"]),
                            "critic": jax.tree_util.tree_map(jnp.asarray, rb_state["critic"]),
                            "target_critic": jax.tree_util.tree_map(jnp.asarray, rb_state["target_critic"]),
                        }
                    )
                    opt_states = runtime.place_params(
                        jax.tree_util.tree_map(jnp.asarray, rb_state["opt_states"])
                    )
                    moments_state = MomentsState(*[jnp.asarray(v) for v in rb_state["moments"]])
                    counter = jnp.int32(rb_state["counter"])
                    ratio.load_state_dict(rb_state["ratio"])
                    if "rng" in rb_state:
                        rng = jnp.asarray(rb_state["rng"])
                    # replay rows stay valid off-policy data; only the learner
                    # (and the player's copy of it) rewinds to the snapshot
                    psync.push(player, params, force=True)
                    runtime.print(
                        f"Health rollback at policy_step={policy_step}: restored certified "
                        "checkpoint, training continues."
                    )
            sentinel.drain(aggregator)

            # ---- logging
            if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters):
                overlap_s, overlap_steps = stepper.drain_overlap()
                if overlap_s > 0:
                    sps_overlap = overlap_steps * cfg.env.num_envs * cfg.env.action_repeat / overlap_s
                    if aggregator and "Time/sps_pipeline_overlap" in aggregator:
                        aggregator.update("Time/sps_pipeline_overlap", sps_overlap)
                    elif logger:
                        logger.log_metrics({"Time/sps_pipeline_overlap": sps_overlap}, policy_step)
                if aggregator and not aggregator.disabled:
                    logger.log_metrics(aggregator.compute(), policy_step)
                    aggregator.reset()
                if logger and policy_step > 0:
                    logger.log_metrics(
                        {"Params/replay_ratio": cumulative_per_rank_gradient_steps * world_size / policy_step},
                        policy_step,
                    )
                if not timer.disabled:
                    timer_metrics = timer.compute()
                    if logger and timer_metrics.get("Time/train_time", 0) > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                            policy_step,
                        )
                        # model FLOPs utilization from the AOT cost analysis of the
                        # G-step train program (same contract as ppo/a2c/sac)
                        _mfu = tel_device.mfu(
                            getattr(train_fn, "last_step_flops", None),
                            timer_metrics["Time/train_time"]
                            / max(train_calls - last_train_calls, 1),
                            runtime.device,
                        )
                        if _mfu is not None:
                            logger.log_metrics({"Time/mfu": _mfu}, policy_step)
                    if logger and timer_metrics.get("Time/env_interaction_time", 0) > 0:
                        logger.log_metrics(
                            {
                                "Time/sps_env_interaction": (
                                    (policy_step - last_log) / world_size * cfg.env.action_repeat
                                )
                                / timer_metrics["Time/env_interaction_time"]
                            },
                            policy_step,
                        )
                    timer.reset()
                last_log = policy_step
                last_train = train_step
                last_train_calls = train_calls

            # ---- checkpoint
            if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
                iter_num == total_iters and cfg.checkpoint.save_last
            ):
                last_checkpoint = policy_step
                _save_checkpoint()

            guard.completed_iteration()
            if guard.should_stop:
                if last_checkpoint != policy_step:  # periodic save above already covered this step
                    last_checkpoint = policy_step
                    _save_checkpoint()
                runtime.print(
                    f"Preemption ({guard.describe()}) at iteration {iter_num}: emergency "
                    "checkpoint saved, exiting cleanly for resume."
                )
                break

    prefetcher.close()
    profiler.close()
    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        psync.push(player, params, force=True)  # the cadence may have left the player stale
        test(player, runtime, cfg, log_dir, greedy=False)
    if logger:
        logger.finalize()
