"""DreamerV1 agent: gaussian-stochastic RSSM + DV2-shared encoders/actor.

Parity targets (reference sheeprl/algos/dreamer_v1/agent.py): RecurrentModel (:31,
Linear+ELU -> plain GRU), RSSM (:64, gaussian stochastic state), WorldModel (:192),
PlayerDV1 (:219), build_agent (:329). The encoders/decoders and the actor are the
DV2 classes (reference imports them, agent.py:16-19), with layer_norm disabled.

TPU-first: the T-step dynamic unroll and the H-step imagination both compile to
single `lax.scan`s; the stochastic state is a reparameterized Normal sample
(softplus std + min_std, reference dreamer_v1/utils.py:80-107).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.algos.dreamer_v2.agent import (
    ActorDV2,
    ActorOutputDV2,
    CNNDecoderDV2,
    CNNEncoderDV2,
    MLPDecoderDV2,
    MLPEncoderDV2,
    MLPWithHeadDV2,
    MultiDecoderDV2,
    MultiEncoderDV2,
    add_exploration_noise,
    xavier_normal_init,
)
from sheeprl_tpu.models.models import MLP
from sheeprl_tpu.utils.utils import host_float32


def compute_stochastic_state(
    state_information: jax.Array, key: Optional[jax.Array] = None, min_std: float = 0.1, sample: bool = True
) -> Tuple[Tuple[jax.Array, jax.Array], jax.Array]:
    """Split (mean, raw_std), apply softplus + min_std, and rsample a Normal.

    Reference: sheeprl/algos/dreamer_v1/utils.py:80-107. Returns ((mean, std), state).
    """
    mean, std = jnp.split(state_information, 2, axis=-1)
    std = jax.nn.softplus(std) + min_std
    if sample:
        state = mean + std * jax.random.normal(key, mean.shape, dtype=mean.dtype)
    else:
        state = mean
    return (mean, std), state


class RecurrentModelDV1(nn.Module):
    """Linear + activation projection feeding a *standard* GRU cell
    (reference agent.py:31-61; torch nn.GRU semantics, not the Hafner LayerNorm GRU)."""

    input_size: int
    recurrent_state_size: int
    activation: str = "elu"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, recurrent_state: jax.Array) -> jax.Array:
        feat = MLP(
            input_dims=self.input_size,
            output_dim=None,
            hidden_sizes=[self.recurrent_state_size],
            activation=self.activation,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=xavier_normal_init,
        )(x)
        new_state, _ = nn.GRUCell(
            features=self.recurrent_state_size,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=xavier_normal_init,
        )(recurrent_state.astype(self.dtype), feat)
        return new_state


class RSSMDV1:
    """Pure-functional gaussian RSSM (reference agent.py:64-190).

    representation/transition output ``2*stochastic_size`` (mean, raw_std); no
    is_first resets (DV1 predates them).
    """

    def __init__(
        self,
        recurrent_model: RecurrentModelDV1,
        representation_model: MLPWithHeadDV2,
        transition_model: MLPWithHeadDV2,
        stochastic_size: int,
        min_std: float = 0.1,
    ):
        self.recurrent_model = recurrent_model
        self.representation_model = representation_model
        self.transition_model = transition_model
        self.stochastic_size = stochastic_size
        self.min_std = min_std

    @property
    def stoch_state_size(self) -> int:
        return self.stochastic_size

    def _transition(self, wm_params, recurrent_out, key=None, sample=True):
        info = self.transition_model.apply(wm_params["transition_model"], recurrent_out)
        return compute_stochastic_state(info, key, self.min_std, sample=sample)

    def _representation(self, wm_params, recurrent_state, embedded_obs, key=None, sample=True):
        info = self.representation_model.apply(
            wm_params["representation_model"], jnp.concatenate([recurrent_state, embedded_obs], axis=-1)
        )
        return compute_stochastic_state(info, key, self.min_std, sample=sample)

    def _recurrent(self, wm_params, stoch, action, recurrent_state):
        x = jnp.concatenate([stoch, action], axis=-1)
        return self.recurrent_model.apply(wm_params["recurrent_model"], x, recurrent_state)

    def dynamic_step(self, wm_params, posterior, recurrent_state, action, embedded_obs, key):
        """One step of dynamic learning (reference agent.py:97-134)."""
        k_prior, k_post = jax.random.split(key)
        recurrent_state = self._recurrent(wm_params, posterior, action, recurrent_state)
        prior_mean_std, prior = self._transition(wm_params, recurrent_state, k_prior)
        posterior_mean_std, posterior = self._representation(wm_params, recurrent_state, embedded_obs, k_post)
        return recurrent_state, posterior, prior, posterior_mean_std, prior_mean_std

    def dynamic_scan(self, wm_params, embedded_obs, actions, key):
        """lax.scan over T (the reference loops in Python, dreamer_v1.py:144-158)."""
        T, B = embedded_obs.shape[0], embedded_obs.shape[1]
        keys = jax.random.split(key, T)
        init_rec = jnp.zeros((B, self.recurrent_model.recurrent_state_size), dtype=embedded_obs.dtype)
        init_post = jnp.zeros((B, self.stochastic_size), dtype=embedded_obs.dtype)

        def step(carry, xs):
            recurrent_state, posterior = carry
            action, embedded, k = xs
            recurrent_state, posterior, _, post_ms, prior_ms = self.dynamic_step(
                wm_params, posterior, recurrent_state, action, embedded, k
            )
            return (recurrent_state, posterior), (recurrent_state, posterior, post_ms, prior_ms)

        _, (recurrent_states, posteriors, post_ms, prior_ms) = jax.lax.scan(
            step, (init_rec, init_post), (actions, embedded_obs, keys)
        )
        return recurrent_states, posteriors, post_ms, prior_ms

    def imagination_step(self, wm_params, stochastic_state, recurrent_state, actions, key):
        """One-step latent imagination (reference agent.py:170-190)."""
        recurrent_state = self._recurrent(wm_params, stochastic_state, actions, recurrent_state)
        _, imagined_prior = self._transition(wm_params, recurrent_state, key)
        return imagined_prior, recurrent_state


class PlayerDV1:
    """Stateful host-side rollout policy (reference agent.py:219-327); exploration
    noise is applied in-graph via a traced expl_amount scalar."""

    def __init__(
        self,
        encoder: MultiEncoderDV2,
        rssm: RSSMDV1,
        actor: ActorDV2,
        actions_dim: Sequence[int],
        num_envs: int,
        stochastic_size: int,
        recurrent_state_size: int,
        actor_type: Optional[str] = None,
    ):
        self.encoder = encoder
        self.rssm = rssm
        self.actor = actor
        self.actions_dim = tuple(actions_dim)
        self.num_envs = num_envs
        self.stochastic_size = stochastic_size
        self.recurrent_state_size = recurrent_state_size
        self.actor_type = actor_type
        self.expl_amount = 0.0
        self.wm_params: Any = None
        self.actor_params: Any = None
        self._step = jax_compile.guarded_jit(self._raw_step, name="dv1.step", static_argnames=("greedy",))
        self._packed_step_fns: Dict[Any, Any] = {}

    def _raw_step(self, wm_params, actor_params, state, obs, key, expl_amount, greedy: bool = False):
        recurrent_state, stochastic_state, actions = state
        k_rep, k_act, k_expl = jax.random.split(key, 3)
        embedded = self.encoder.apply(wm_params["encoder"], obs)
        recurrent_state = self.rssm._recurrent(wm_params, stochastic_state, actions, recurrent_state)
        _, stochastic_state = self.rssm._representation(wm_params, recurrent_state, embedded, k_rep)
        latent = jnp.concatenate([stochastic_state, recurrent_state], axis=-1)
        out = ActorOutputDV2(self.actor, self.actor.apply(actor_params, latent))
        actions_list = out.sample_actions(k_act, greedy=greedy)
        if not greedy:  # exploration noise is a training-only behavior (reference get_actions adds none)
            actions_list = add_exploration_noise(
                actions_list, expl_amount, self.actor.is_continuous, self.actions_dim, k_expl
            )
        actions_list = host_float32(actions_list)
        actions = jnp.concatenate(actions_list, axis=-1)
        return tuple(actions_list), (recurrent_state, stochastic_state, actions)

    def init_states(self, reset_envs: Optional[Sequence[int]] = None) -> None:
        if reset_envs is None or len(reset_envs) == 0:
            self.state = (
                jnp.zeros((1, self.num_envs, self.recurrent_state_size), dtype=jnp.float32),
                jnp.zeros((1, self.num_envs, self.stochastic_size), dtype=jnp.float32),
                jnp.zeros((1, self.num_envs, int(np.sum(self.actions_dim))), dtype=jnp.float32),
            )
        else:
            recurrent_state, stochastic_state, actions = self.state
            reset = np.zeros((self.num_envs,), dtype=bool)
            reset[np.asarray(reset_envs)] = True
            mask = jnp.asarray(reset)[None, :, None]
            self.state = (
                jnp.where(mask, 0.0, recurrent_state),
                jnp.where(mask, 0.0, stochastic_state),
                jnp.where(mask, 0.0, actions),
            )

    def get_actions(self, obs: Dict[str, jax.Array], key: jax.Array, greedy: bool = False, mask=None):
        del mask
        actions_list, self.state = self._step(
            self.wm_params,
            self.actor_params,
            self.state,
            obs,
            key,
            jnp.float32(self.expl_amount),
            greedy=greedy,
        )
        return actions_list

    def get_actions_packed(self, codec, packed: jax.Array, key: jax.Array, greedy: bool = False):
        """Act from a packed obs buffer: unpack + normalize in-graph (one H2D transfer per step)."""
        cache_key = (codec.signature, bool(greedy))
        fn = self._packed_step_fns.get(cache_key)
        if fn is None:

            def _packed(wm_params, actor_params, state, packed, key, expl_amount):
                obs = codec.decode_obs(packed)
                return self._raw_step(wm_params, actor_params, state, obs, key, expl_amount, greedy=greedy)

            fn = jax_compile.guarded_jit(_packed, name="dv1.step_packed")
            self._packed_step_fns[cache_key] = fn
        actions_list, self.state = fn(
            self.wm_params, self.actor_params, self.state, packed, key, jnp.float32(self.expl_amount)
        )
        return actions_list

    # expl noise is folded into get_actions via self.expl_amount; kept for API parity
    get_exploration_actions = get_actions


class DV1Modules(NamedTuple):
    encoder: MultiEncoderDV2
    rssm: RSSMDV1
    observation_model: MultiDecoderDV2
    reward_model: MLPWithHeadDV2
    continue_model: Optional[MLPWithHeadDV2]
    actor: ActorDV2
    critic: MLPWithHeadDV2


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Dict[str, Any]] = None,
    actor_state: Optional[Dict[str, Any]] = None,
    critic_state: Optional[Dict[str, Any]] = None,
) -> Tuple[DV1Modules, Dict[str, Any], PlayerDV1]:
    """Build module defs + init params (reference agent.py:329-559)."""
    world_model_cfg = cfg.algo.world_model
    actor_cfg = cfg.algo.actor
    critic_cfg = cfg.algo.critic

    recurrent_state_size = int(world_model_cfg.recurrent_model.recurrent_state_size)
    stochastic_size = int(world_model_cfg.stochastic_size)
    latent_state_size = stochastic_size + recurrent_state_size
    compute_dtype = runtime.compute_dtype
    param_dtype = jnp.float32

    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_encoder = (
        CNNEncoderDV2(
            keys=cnn_keys,
            input_channels=[int(np.prod(obs_space[k].shape[:-2])) for k in cnn_keys],
            image_size=tuple(obs_space[cnn_keys[0]].shape[-2:]),
            channels_multiplier=int(world_model_cfg.encoder.cnn_channels_multiplier),
            layer_norm=False,
            activation=world_model_cfg.encoder.cnn_act,
            dtype=compute_dtype,
            param_dtype=param_dtype,
        )
        if len(cnn_keys) > 0
        else None
    )
    mlp_encoder = (
        MLPEncoderDV2(
            keys=mlp_keys,
            input_dims=[int(obs_space[k].shape[0]) for k in mlp_keys],
            mlp_layers=int(world_model_cfg.encoder.mlp_layers),
            dense_units=int(world_model_cfg.encoder.dense_units),
            layer_norm=False,
            activation=world_model_cfg.encoder.dense_act,
            dtype=compute_dtype,
            param_dtype=param_dtype,
        )
        if len(mlp_keys) > 0
        else None
    )
    encoder = MultiEncoderDV2(cnn_encoder, mlp_encoder)

    recurrent_model = RecurrentModelDV1(
        input_size=int(sum(actions_dim) + stochastic_size),
        recurrent_state_size=recurrent_state_size,
        activation=world_model_cfg.recurrent_model.dense_act,
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )
    repr_input = recurrent_state_size + encoder.output_dim
    representation_model = MLPWithHeadDV2(
        input_dim=repr_input,
        hidden_sizes=[int(world_model_cfg.representation_model.hidden_size)],
        output_dim=stochastic_size * 2,
        activation=world_model_cfg.representation_model.dense_act,
        layer_norm=False,
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )
    transition_model = MLPWithHeadDV2(
        input_dim=recurrent_state_size,
        hidden_sizes=[int(world_model_cfg.transition_model.hidden_size)],
        output_dim=stochastic_size * 2,
        activation=world_model_cfg.transition_model.dense_act,
        layer_norm=False,
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )
    rssm = RSSMDV1(
        recurrent_model=recurrent_model,
        representation_model=representation_model,
        transition_model=transition_model,
        stochastic_size=stochastic_size,
        min_std=float(world_model_cfg.min_std),
    )

    cnn_keys_dec = list(cfg.algo.cnn_keys.decoder)
    mlp_keys_dec = list(cfg.algo.mlp_keys.decoder)
    cnn_decoder = (
        CNNDecoderDV2(
            keys=cnn_keys_dec,
            output_channels=[int(np.prod(obs_space[k].shape[:-2])) for k in cnn_keys_dec],
            channels_multiplier=int(world_model_cfg.observation_model.cnn_channels_multiplier),
            cnn_encoder_output_dim=cnn_encoder.output_dim,
            image_size=tuple(obs_space[cnn_keys_dec[0]].shape[-2:]),
            layer_norm=False,
            activation=world_model_cfg.observation_model.cnn_act,
            dtype=compute_dtype,
            param_dtype=param_dtype,
        )
        if len(cnn_keys_dec) > 0
        else None
    )
    mlp_decoder = (
        MLPDecoderDV2(
            keys=mlp_keys_dec,
            output_dims=[int(obs_space[k].shape[0]) for k in mlp_keys_dec],
            mlp_layers=int(world_model_cfg.observation_model.mlp_layers),
            dense_units=int(world_model_cfg.observation_model.dense_units),
            layer_norm=False,
            activation=world_model_cfg.observation_model.dense_act,
            dtype=compute_dtype,
            param_dtype=param_dtype,
        )
        if len(mlp_keys_dec) > 0
        else None
    )
    observation_model = MultiDecoderDV2(cnn_decoder, mlp_decoder)

    reward_model = MLPWithHeadDV2(
        input_dim=latent_state_size,
        hidden_sizes=[int(world_model_cfg.reward_model.dense_units)] * int(world_model_cfg.reward_model.mlp_layers),
        output_dim=1,
        activation=world_model_cfg.reward_model.dense_act,
        layer_norm=False,
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )
    continue_model = (
        MLPWithHeadDV2(
            input_dim=latent_state_size,
            hidden_sizes=[int(world_model_cfg.discount_model.dense_units)]
            * int(world_model_cfg.discount_model.mlp_layers),
            output_dim=1,
            activation=world_model_cfg.discount_model.dense_act,
            layer_norm=False,
            dtype=compute_dtype,
            param_dtype=param_dtype,
        )
        if world_model_cfg.use_continues
        else None
    )

    actor = ActorDV2(
        latent_state_size=latent_state_size,
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        distribution=cfg.distribution.get("type", "auto"),
        init_std=float(actor_cfg.init_std),
        min_std=float(actor_cfg.min_std),
        dense_units=int(actor_cfg.dense_units),
        mlp_layers=int(actor_cfg.mlp_layers),
        layer_norm=False,
        activation=actor_cfg.dense_act,
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )
    critic = MLPWithHeadDV2(
        input_dim=latent_state_size,
        hidden_sizes=[int(critic_cfg.dense_units)] * int(critic_cfg.mlp_layers),
        output_dim=1,
        activation=critic_cfg.dense_act,
        layer_norm=False,
        dtype=compute_dtype,
        param_dtype=param_dtype,
    )

    key = jax.random.PRNGKey(cfg.seed)
    keys = jax.random.split(key, 10)
    dummy_obs: Dict[str, jax.Array] = {}
    for k in cnn_keys:
        dummy_obs[k] = jnp.zeros((1, int(np.prod(obs_space[k].shape[:-2])), *obs_space[k].shape[-2:]))
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((1, int(obs_space[k].shape[0])))
    wm_params: Dict[str, Any] = {}
    wm_params["encoder"] = encoder.init(keys[0], dummy_obs)
    wm_params["recurrent_model"] = recurrent_model.init(
        keys[1], jnp.zeros((1, int(sum(actions_dim)) + stochastic_size)), jnp.zeros((1, recurrent_state_size))
    )
    wm_params["representation_model"] = representation_model.init(keys[2], jnp.zeros((1, repr_input)))
    wm_params["transition_model"] = transition_model.init(keys[3], jnp.zeros((1, recurrent_state_size)))
    wm_params["observation_model"] = observation_model.init(keys[4], jnp.zeros((1, latent_state_size)))
    wm_params["reward_model"] = reward_model.init(keys[5], jnp.zeros((1, latent_state_size)))
    if continue_model is not None:
        wm_params["continue_model"] = continue_model.init(keys[6], jnp.zeros((1, latent_state_size)))
    actor_params = actor.init(keys[7], jnp.zeros((1, latent_state_size)))
    critic_params = critic.init(keys[8], jnp.zeros((1, latent_state_size)))

    if world_model_state:
        wm_params = jax.tree_util.tree_map(jnp.asarray, world_model_state)
    if actor_state:
        actor_params = jax.tree_util.tree_map(jnp.asarray, actor_state)
    if critic_state:
        critic_params = jax.tree_util.tree_map(jnp.asarray, critic_state)

    modules = DV1Modules(
        encoder=encoder,
        rssm=rssm,
        observation_model=observation_model,
        reward_model=reward_model,
        continue_model=continue_model,
        actor=actor,
        critic=critic,
    )
    params = {"world_model": wm_params, "actor": actor_params, "critic": critic_params}

    player = PlayerDV1(
        encoder=encoder,
        rssm=rssm,
        actor=actor,
        actions_dim=actions_dim,
        num_envs=cfg.env.num_envs,
        stochastic_size=stochastic_size,
        recurrent_state_size=recurrent_state_size,
    )
    player.expl_amount = float(actor_cfg.get("expl_amount", 0.0))
    player.wm_params = wm_params
    player.actor_params = actor_params
    return modules, params, player
