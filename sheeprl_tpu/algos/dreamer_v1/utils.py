"""DreamerV1 utilities (reference sheeprl/algos/dreamer_v1/utils.py).

`compute_lambda_values` follows the DV1 recursion (:42-77): horizon-1 targets with
the mixed (1-lambda) value bootstrap, as a reverse `lax.scan`.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Params/exploration_amount",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}
# Compilation-management counters (core/compile.py), drained once per iteration.
AGGREGATOR_KEYS |= {
    "Compile/retraces",
    "Compile/cache_hits",
    "Compile/cache_misses",
    "Time/compile_seconds",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic"}


def compute_lambda_values(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    last_values: jax.Array,
    horizon: int = 15,
    lmbda: float = 0.95,
) -> jax.Array:
    """DV1 lambda targets (reference utils.py:42-77).

    Inputs ``[H, B, 1]``; output ``[H-1, B, 1]``. For step < H-2 the next value is
    ``values[step+1] * (1 - lmbda)``; the last step bootstraps with ``last_values``.
    """
    next_values = jnp.concatenate([values[1 : horizon - 1] * (1 - lmbda), last_values[None]], axis=0)
    deltas = rewards[: horizon - 1] + next_values * continues[: horizon - 1]

    def body(carry, xs):
        delta_t, cont_t = xs
        val = delta_t + cont_t * lmbda * carry
        return val, val

    _, out = jax.lax.scan(
        body, jnp.zeros_like(last_values), (deltas[::-1], continues[: horizon - 1][::-1])
    )
    return out[::-1]


# The rollout/test helpers are identical to DV2's (the reference likewise reuses
# DV2's test from DV1); import instead of duplicating.
from sheeprl_tpu.algos.dreamer_v2.utils import prepare_obs, test  # noqa: E402, F401


def log_models_from_checkpoint(runtime, env, cfg, state) -> Dict[str, Any]:
    """Register DV1 models from a checkpoint (reference utils.py:110-160)."""
    import gymnasium as gym

    from sheeprl_tpu.algos.dreamer_v1.agent import build_agent
    from sheeprl_tpu.utils.model_manager import log_model

    is_continuous = isinstance(env.action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(env.action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        env.action_space.shape
        if is_continuous
        else (env.action_space.nvec.tolist() if is_multidiscrete else [env.action_space.n])
    )
    _, params, _ = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        env.observation_space,
        state["world_model"],
        state["actor"],
        state["critic"],
    )
    info = {}
    for name in ("world_model", "actor", "critic"):
        info[name] = log_model(runtime, cfg, name, params[name])
    return info
