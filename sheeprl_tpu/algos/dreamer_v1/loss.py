"""DreamerV1 losses (reference sheeprl/algos/dreamer_v1/loss.py).

Gaussian KL with free nats, gaussian observation/reward heads, optional Bernoulli
continue head, plus the actor/critic objectives (Eq. 7/8/10 of the paper).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def gaussian_kl(p_mean, p_std, q_mean, q_std) -> jax.Array:
    """KL(N(p) || N(q)) summed over the last (event) dim."""
    var_ratio = (p_std / q_std) ** 2
    t1 = ((p_mean - q_mean) / q_std) ** 2
    return 0.5 * jnp.sum(var_ratio + t1 - 1.0 - jnp.log(var_ratio), axis=-1)


def actor_loss(discounted_lambda_values: jax.Array) -> jax.Array:
    """Eq. 7 (reference loss.py:27-39): maximize the discounted lambda returns."""
    return -jnp.mean(discounted_lambda_values)


def critic_loss(qv_log_prob: jax.Array, discount: jax.Array) -> jax.Array:
    """Eq. 8 (reference loss.py:9-24): discounted value log-likelihood."""
    return -jnp.mean(discount * qv_log_prob)


def reconstruction_loss(
    qo_log_probs: Dict[str, jax.Array],
    qr_log_prob: jax.Array,
    posteriors_mean: jax.Array,
    posteriors_std: jax.Array,
    priors_mean: jax.Array,
    priors_std: jax.Array,
    kl_free_nats: float = 3.0,
    kl_regularizer: float = 1.0,
    qc_log_prob: Optional[jax.Array] = None,
    continue_scale_factor: float = 10.0,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Eq. 10 world-model loss (reference loss.py:42-99).

    Returns (loss, kl, state_loss, reward_loss, observation_loss, continue_loss).
    The continue term is the *negative* log-likelihood (the reference has a sign
    slip at loss.py:94, `continue_scale_factor * qc.log_prob(...)`, which would
    reward mispredicting terminals; the intended objective is implemented here).
    """
    observation_loss = -sum(lp.mean() for lp in qo_log_probs.values())
    reward_loss = -qr_log_prob.mean()
    kl = gaussian_kl(posteriors_mean, posteriors_std, priors_mean, priors_std).mean()
    state_loss = jnp.maximum(kl, kl_free_nats)
    if qc_log_prob is not None:
        continue_loss = continue_scale_factor * -qc_log_prob.mean()
    else:
        continue_loss = jnp.zeros_like(reward_loss)
    loss = kl_regularizer * state_loss + observation_loss + reward_loss + continue_loss
    return loss, kl, state_loss, reward_loss, observation_loss, continue_loss
