"""Exploit/explore policy: pick a parent, perturb its hyperparameters.

The transfer medium between population members is the certified checkpoint
sidecar (``utils/checkpoint.py``): a peer is a legitimate parent only if its
newest checkpoint was written while its own HealthSentinel reported healthy —
resowing a diverged trial from an *uncertified* peer checkpoint risks copying
the same poisoned state the resow exists to escape.

Fitness is the certified ``policy_step`` recorded in the sidecar: among
still-healthy peers, the one whose certified training state is furthest along.
That is deliberately cheap (no eval rollouts) — the controller runs on the
fleet's coordinator host and must never need an accelerator to make a
scheduling decision.
"""

from __future__ import annotations

import json
import os
import random
from typing import Any, Dict, List, Optional, Tuple

from sheeprl_tpu.utils.checkpoint import certified_sidecar, certified_under


def certified_fitness(trial_dir: str) -> Optional[Tuple[str, int]]:
    """``(ckpt_path, policy_step)`` of the newest certified checkpoint anywhere
    under ``trial_dir`` (a trial's incarnations each get their own run dir), or
    None when the trial has produced no certified checkpoint yet. A sidecar
    without ``policy_step`` (older writer) counts as step 0 — certified at all
    still beats nothing."""
    ckpt = certified_under(trial_dir)
    if ckpt is None:
        return None
    step = 0
    try:
        with open(certified_sidecar(ckpt)) as f:
            payload = json.load(f)
        step = int(payload.get("policy_step") or 0)
    except (OSError, ValueError, TypeError):
        step = 0
    return ckpt, step


def select_parent(
    trial_dirs: Dict[str, str],
    exclude: Optional[List[str]] = None,
) -> Optional[Tuple[str, str, int]]:
    """Best resow parent among ``{trial_key: trial_dir}``.

    Returns ``(parent_key, ckpt_path, policy_step)`` for the eligible peer with
    the highest certified fitness (ties broken by key for determinism), or None
    when no peer has certified anything yet — the caller then either waits
    (``resow.parent_wait_s``) or falls back to a from-scratch requeue.
    ``exclude`` lists keys that must not parent (the diverged trial itself, and
    any peer currently diverged)."""
    banned = set(exclude or ())
    best: Optional[Tuple[str, str, int]] = None
    for key in sorted(trial_dirs):
        if key in banned:
            continue
        fit = certified_fitness(trial_dirs[key])
        if fit is None:
            continue
        ckpt, step = fit
        if best is None or step > best[2]:
            best = (key, ckpt, step)
    return best


def perturb(
    hyperparams: Dict[str, Any],
    keys: List[str],
    factors: List[float],
    rng: Optional[random.Random] = None,
) -> Dict[str, Any]:
    """PBT-style explore step: multiply each listed numeric hyperparameter by a
    factor chosen uniformly from ``factors`` (classic PBT uses {0.8, 1.2}).

    Non-numeric or absent keys pass through untouched — perturbation must never
    invent a hyperparameter the trial did not declare, or a resown run would
    silently train under a config its lineage cannot explain."""
    rng = rng or random
    out = dict(hyperparams)
    if not factors:
        return out
    for key in keys:
        val = out.get(key)
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            continue
        out[key] = val * rng.choice(list(factors))
    return out


def bottom_quantile(
    fitness_by_key: Dict[str, int],
    quantile: float,
) -> List[str]:
    """Trial keys in the bottom ``quantile`` of the population by fitness —
    candidates for the periodic exploit step (``orchestrate.exploit``). At
    least one key is returned when the population is non-empty and the
    quantile is positive; ties at the cut keep population order stable by
    sorting (fitness, key)."""
    if not fitness_by_key or quantile <= 0:
        return []
    ranked = sorted(fitness_by_key.items(), key=lambda kv: (kv[1], kv[0]))
    n = max(int(len(ranked) * float(quantile)), 1)
    return [k for k, _ in ranked[:n]]
