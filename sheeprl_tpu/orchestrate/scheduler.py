"""Slot scheduler: bounded-backoff requeue over a preemptible slot pool.

Pure bookkeeping (no subprocess knowledge) so every policy decision — who gets
the next free slot, when a preempted trial becomes eligible again, when a
budget is exhausted — is unit-testable without spawning anything.

Preemption is *routine* here: a preempted trial consumes one unit of its
preemption budget and re-enters the queue after a jittered exponential backoff
(:func:`sheeprl_tpu.core.resilience.jittered_backoff` — the same anti-herd
policy the env-worker supervisor uses, because a fleet-wide preemption batch
would otherwise slam every slot back at the same instant). Failures have their
own smaller budget; past either budget the trial is FAILED, because a trial
that keeps dying is a bug, not weather.
"""

from __future__ import annotations

import random
import time
from typing import Any, List, Optional

from sheeprl_tpu.core.resilience import jittered_backoff
from sheeprl_tpu.orchestrate import trial as T
from sheeprl_tpu.orchestrate.trial import Trial


class SlotScheduler:
    def __init__(
        self,
        slots: int,
        max_preemptions: int = 8,
        max_failures: int = 2,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 30.0,
        rng: Optional[random.Random] = None,
    ):
        self.slots = max(int(slots), 1)
        self.max_preemptions = int(max_preemptions)
        self.max_failures = int(max_failures)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self._rng = rng or random.Random()

    # -- slot accounting ------------------------------------------------------ #

    def free_slots(self, trials: List[Trial]) -> int:
        return self.slots - sum(1 for t in trials if t.state == T.RUNNING)

    def next_to_run(self, trials: List[Trial], now: Optional[float] = None) -> List[Trial]:
        """Queued trials eligible NOW, oldest-eligibility first, capped at the
        free slot count. The caller spawns them and flips them to RUNNING."""
        now = time.time() if now is None else now
        free = self.free_slots(trials)
        if free <= 0:
            return []
        eligible = [t for t in trials if t.queued and t.next_eligible <= now]
        eligible.sort(key=lambda t: (t.next_eligible, t.key))
        return eligible[:free]

    # -- requeue policies ----------------------------------------------------- #

    def requeue_preempted(self, trial: Trial, resume_ckpt: Optional[str], now: Optional[float] = None) -> str:
        """PREEMPTED -> RESUMED (jittered backoff, budgeted) or FAILED.

        Returns the resulting state. ``resume_ckpt`` None means no checkpoint
        survived (preempted before the first save): the trial requeues from
        scratch — the generation keeps its identity, nothing is lost but the
        steps since the last save."""
        now = time.time() if now is None else now
        trial.preemptions += 1
        if trial.preemptions > self.max_preemptions:
            trial.to(T.FAILED, reason=f"preemption budget exhausted ({trial.preemptions - 1})")
            return trial.state
        delay = jittered_backoff(self.backoff_base_s, trial.preemptions, self.backoff_max_s, self._rng)
        trial.resume_ckpt = resume_ckpt
        trial.next_eligible = now + delay
        trial.to(T.RESUMED, resume_ckpt=resume_ckpt, backoff_s=round(delay, 3))
        return trial.state

    def requeue_failed(self, trial: Trial, reason: str, now: Optional[float] = None) -> str:
        """RUNNING -> FAILED (terminal) or back into the queue with backoff.

        A non-zero exit is retried like a preemption (the slot may simply have
        been bad — OOM neighbor, dirty /tmp) but against the smaller failure
        budget."""
        now = time.time() if now is None else now
        trial.failures += 1
        if trial.failures > self.max_failures:
            trial.to(T.FAILED, reason=f"failure budget exhausted: {reason}")
            return trial.state
        delay = jittered_backoff(self.backoff_base_s, trial.failures, self.backoff_max_s, self._rng)
        trial.next_eligible = now + delay
        # a crashed incarnation resumes from its newest save when one exists;
        # the caller passes that through trial.resume_ckpt before spawning
        trial.to(T.PREEMPTED, reason=reason, exit_kind="failure")
        trial.to(T.RESUMED, backoff_s=round(delay, 3))
        return trial.state
