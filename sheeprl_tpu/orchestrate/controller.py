"""Population controller: N trials on a pool of preemptible slots.

The control loop composes the single-run guarantees from PRs 2 and 5 into a
fleet. Each trial incarnation is a supervised ``sheeprl.py`` subprocess whose
own ``PreemptionGuard`` turns SIGTERM into checkpoint-and-exit-0; the
controller classifies every exit (completed / preempted / diverged / failed)
and feeds the scheduler. Divergence verdicts come from tailing the trial's
``health/events.jsonl`` — the trial's HealthSentinel is the fitness oracle,
the controller never inspects losses itself.

Exit classification uses three signals, in precedence order:

1. the controller's own *kill intent* (it sent the SIGTERM — for an injected
   preemption drill, a divergence kill, or an exploit kill);
2. the preemption **flag file** (``SHEEPRL_PREEMPTION_FLAG_FILE``) the child's
   guard touches when a REAL signal lands — distinguishing "exited 0 because
   preempted" from "exited 0 because finished", which are byte-identical at
   the returncode level;
3. the returncode.

The controller is itself preemptible: it runs under
``PreemptionGuard(forward_to_children=True)``, so SIGTERM fans out to every
trial, everyone checkpoints, the journal records the fleet as
preempted-and-requeued, and a restart with the same ``--state-dir`` resumes
with no duplicated or lost trials (reconciliation kills/requeues any trial the
journal thought was running).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from sheeprl_tpu.core import failpoints
from sheeprl_tpu.core.health import DIVERGENCE_EVENT_KINDS, EVENTS_FILENAME, read_events
from sheeprl_tpu.core.resilience import FLAG_FILE_ENV_VAR, READY_FILE_ENV_VAR, PreemptionGuard
from sheeprl_tpu.orchestrate import resolve
from sheeprl_tpu.orchestrate import trial as T
from sheeprl_tpu.orchestrate.journal import Journal
from sheeprl_tpu.orchestrate.lineage import LineageLog
from sheeprl_tpu.orchestrate.resow import certified_fitness, perturb, select_parent
from sheeprl_tpu.orchestrate.scheduler import SlotScheduler
from sheeprl_tpu.orchestrate.trial import Trial, TrialSpec
from sheeprl_tpu.telemetry import trace
from sheeprl_tpu.utils.checkpoint import ckpt_sort_key

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Env var overriding the trainee entry point (default: <repo>/sheeprl.py). The
# orchestrate unit tests point this at a stub trainee so the full
# spawn/preempt/diverge/resow loop runs in milliseconds without importing jax.
ENTRY_ENV_VAR = "SHEEPRL_TPU_ORCH_ENTRY"

READY_FILENAME = ".guard_ready"
FLAG_FILENAME = ".preempt_flag"


def _entry_point() -> str:
    return os.environ.get(ENTRY_ENV_VAR) or os.path.join(REPO_ROOT, "sheeprl.py")


def _newest_ckpt(root: str) -> Optional[str]:
    """Newest ``*.ckpt`` under ``root``, certified or not — preemption resume
    prefers the trial's very last save (often the guard's emergency checkpoint,
    uncertified by design: the sentinel only certifies healthy saves)."""
    best, best_key = None, None
    for base, _, files in os.walk(root):
        for name in files:
            if not name.endswith(".ckpt"):
                continue
            cand = os.path.join(base, name)
            key = ckpt_sort_key(cand)
            if best_key is None or key > best_key:
                best, best_key = cand, key
    return best


def _pid_alive(pid: Optional[int]) -> bool:
    if not pid:
        return False
    try:
        os.kill(int(pid), 0)
    except (ProcessLookupError, PermissionError, OSError):
        return False
    return True


class PopulationController:
    def __init__(
        self,
        specs: List[TrialSpec],
        state_dir: str,
        cfg: Any = None,
        inject_preempt: int = 0,
        inject_spacing_s: float = 2.0,
    ):
        self.cfg = resolve(cfg)
        self.state_dir = os.path.abspath(state_dir)
        os.makedirs(self.state_dir, exist_ok=True)
        self.journal = Journal(os.path.join(self.state_dir, "journal.json"))
        self.lineage = LineageLog(os.path.join(self.state_dir, "lineage.jsonl"))
        self.scheduler = SlotScheduler(
            slots=self.cfg.slots,
            max_preemptions=self.cfg.trial.max_preemptions,
            max_failures=self.cfg.trial.max_failures,
            backoff_base_s=self.cfg.trial.requeue_backoff_base_s,
            backoff_max_s=self.cfg.trial.requeue_backoff_max_s,
        )
        # The journal is the source of truth across controller incarnations:
        # specs only seed it the FIRST time this state_dir is used. A restart
        # with a different spec list does not add/drop trials silently.
        self.trials = self.journal.load_trials()
        if not self.trials:
            self.trials = [Trial(s) for s in specs]
        self.counters: Dict[str, Any] = (self.journal.load() or {}).get("counters") or {}
        self.counters.setdefault("spawn_seq", 0)
        self.counters.setdefault("preempt_recoveries", [])
        self.counters.setdefault("resow_walls", [])
        self.counters.setdefault("injections", 0)
        self.counters.setdefault("controller_incarnations", 0)
        self.counters["controller_incarnations"] += 1

        self._procs: Dict[str, subprocess.Popen] = {}
        self._logs: Dict[str, Any] = {}  # open log file handles
        self._run_names: Dict[str, str] = {}  # current incarnation's run_name
        self._intents: Dict[str, str] = {}  # key -> why WE killed it
        self._event_offsets: Dict[str, int] = {}  # events-file path -> byte offset
        self._preempted_at: Dict[str, float] = {}
        self._diverged_at: Dict[str, float] = {}
        self._resow_deadline: Dict[str, float] = {}
        self._inject_remaining = int(inject_preempt)
        self._inject_spacing_s = float(inject_spacing_s)
        self._injected: Dict[str, int] = {}
        self._last_inject = 0.0
        self._last_exploit = 0.0
        self.guard: Optional[PreemptionGuard] = None

    # -- paths ----------------------------------------------------------------- #

    def trial_dir(self, key: str) -> str:
        return os.path.join(self.state_dir, "trials", key)

    def _ready_file(self, key: str) -> str:
        return os.path.join(self.trial_dir(key), READY_FILENAME)

    def _flag_file(self, key: str) -> str:
        return os.path.join(self.trial_dir(key), FLAG_FILENAME)

    def _trial(self, key: str) -> Trial:
        return next(t for t in self.trials if t.key == key)

    def _save(self) -> None:
        # Drill site: journal durability — a kill here must leave either the
        # old or the new journal under the final name (Journal writes via
        # tmp+rename), never a torn file.
        failpoints.failpoint("orchestrate.journal", path=self.journal.path)
        self.journal.save(self.trials, self.counters)

    def _log(self, msg: str) -> None:
        print(f"[orchestrate] {msg}", flush=True)

    # -- spawning --------------------------------------------------------------- #

    def _spawn(self, trial: Trial, now: float) -> None:
        # Drill site: `orchestrate.spawn:kill:9:hit=N` dies between the journal
        # state change and the Popen — the restart-reconciliation path must
        # requeue the trial the journal thought was starting.
        failpoints.failpoint("orchestrate.spawn", key=trial.key)
        seq = self.counters["spawn_seq"]
        self.counters["spawn_seq"] = seq + 1
        run_name = f"inc{seq:04d}_{trial.key}"
        tdir = self.trial_dir(trial.key)
        os.makedirs(tdir, exist_ok=True)
        for path in (self._ready_file(trial.key), self._flag_file(trial.key)):
            try:
                os.remove(path)
            except OSError:
                pass

        overrides = list(trial.spec.overrides)
        if trial.generation == 0 and trial.spec.chaos_overrides:
            # transient environmental faults belong to generation 0 only: a
            # resown generation is rescheduled weather-free (and the ChaosEnv
            # step counter restarting at 0 in a new process would otherwise
            # re-fire the fault window every generation)
            overrides += trial.spec.chaos_overrides
        overrides += [f"{k}={v}" for k, v in trial.hyperparams.items()]
        overrides.append(f"run_name={run_name}")
        if trial.resume_ckpt:
            overrides.append(f"checkpoint.resume_from={trial.resume_ckpt}")
            # the sidecar merge takes the OLD config wholesale; these dotted
            # keys keep the NEW invocation's values — the perturbed
            # hyperparameters, and the wrapper stack composed from THIS
            # generation's overrides (a resow from a chaos-gen-0 peer must not
            # inherit the peer's fault injection)
            preserve = sorted(set(list(trial.hyperparams) + ["env.wrapper"]))
            overrides.append("checkpoint.resume_preserve=[" + ",".join(preserve) + "]")

        kind = {T.PENDING: "seed", T.RESUMED: "resume", T.RESOWN: "resow"}.get(trial.state, "seed")
        log_path = os.path.join(tdir, f"{run_name}.log")
        log_f = open(log_path, "ab")
        env = dict(
            os.environ,
            JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
            **{
                READY_FILE_ENV_VAR: self._ready_file(trial.key),
                FLAG_FILE_ENV_VAR: self._flag_file(trial.key),
            },
        )
        proc = subprocess.Popen(
            [sys.executable, _entry_point()] + overrides,
            cwd=tdir,
            env=env,
            stdout=log_f,
            stderr=subprocess.STDOUT,
        )
        self._procs[trial.key] = proc
        self._logs[trial.key] = log_f
        self._run_names[trial.key] = run_name
        trial.pid = proc.pid
        if self.guard is not None:
            self.guard.register_child(proc.pid)

        if trial.state == T.RESUMED and trial.key in self._preempted_at:
            self.counters["preempt_recoveries"].append(
                {"trial": trial.key, "latency_s": round(now - self._preempted_at.pop(trial.key), 3)}
            )
        if trial.state == T.RESOWN and trial.key in self._diverged_at:
            self.counters["resow_walls"].append(
                {"trial": trial.key, "wall_s": round(now - self._diverged_at.pop(trial.key), 3)}
            )
        trial.to(T.RUNNING, pid=proc.pid, run_name=run_name, kind=kind)
        trace.instant(
            "orchestrate/spawn", trial=trial.key, gen=trial.generation, kind=kind, pid=proc.pid
        )
        self.lineage.record(
            kind,
            trial.key,
            trial.generation,
            parent=trial.parent if kind == "resow" else None,
            ckpt=trial.resume_ckpt,
            hyperparams=trial.hyperparams,
            run_name=run_name,
        )
        self._log(
            f"spawn {trial.key} gen={trial.generation} kind={kind} pid={proc.pid} "
            f"resume={'yes' if trial.resume_ckpt else 'no'}"
        )
        self._save()

    # -- exit classification ----------------------------------------------------- #

    def _reap(self, key: str) -> None:
        proc = self._procs.pop(key, None)
        if proc is not None and self.guard is not None:
            self.guard.unregister_child(proc.pid)
        log_f = self._logs.pop(key, None)
        if log_f is not None:
            try:
                log_f.close()
            except OSError:
                pass
        self._run_names.pop(key, None)
        self._trial(key).pid = None

    def _classify_exit(self, trial: Trial, rc: int, now: float) -> None:
        key = trial.key
        intent = self._intents.pop(key, None)
        flagged = os.path.exists(self._flag_file(key))
        self._reap(key)
        if intent in ("diverged", "exploit"):
            trial.to(T.DIVERGED, rc=rc, cause=intent)
            self._diverged_at.setdefault(key, now)
            self._log(f"exit {key}: diverged (cause={intent}, rc={rc})")
            self._try_resow(trial, now)
        elif intent == "preempt" or flagged:
            trial.to(T.PREEMPTED, rc=rc, injected=intent == "preempt")
            self._preempted_at[key] = now
            ckpt = _newest_ckpt(self.trial_dir(key))
            state = self.scheduler.requeue_preempted(trial, ckpt, now)
            self._log(f"exit {key}: preempted (rc={rc}) -> {state}")
        elif rc == 0:
            trial.to(T.COMPLETED, rc=0)
            self._log(f"exit {key}: completed")
        else:
            trial.resume_ckpt = _newest_ckpt(self.trial_dir(key))
            state = self.scheduler.requeue_failed(trial, f"rc={rc}", now)
            self._log(f"exit {key}: failed (rc={rc}) -> {state}")
        trace.instant("orchestrate/exit", trial=key, rc=rc, state=str(trial.state))
        self._save()

    def _poll_exits(self, now: float) -> None:
        for key, proc in list(self._procs.items()):
            rc = proc.poll()
            if rc is None:
                continue
            self._classify_exit(self._trial(key), rc, now)

    # -- divergence watch --------------------------------------------------------- #

    def _events_files(self, key: str) -> List[str]:
        """The CURRENT incarnation's health event files only. Earlier
        incarnations' files stay on disk; re-reading them after a controller
        restart must not re-condemn a healthy resown generation."""
        run_name = self._run_names.get(key)
        if not run_name:
            return []
        found = []
        for base, _, files in os.walk(self.trial_dir(key)):
            if EVENTS_FILENAME in files and run_name in base:
                found.append(os.path.join(base, EVENTS_FILENAME))
        return sorted(found)

    def _watch_health(self, now: float) -> None:
        for trial in self.trials:
            if trial.state != T.RUNNING or trial.key in self._intents:
                continue
            for path in self._events_files(trial.key):
                events, offset = read_events(path, self._event_offsets.get(path, 0))
                self._event_offsets[path] = offset
                verdict = next(
                    (
                        e
                        for e in events
                        if e.get("event") in DIVERGENCE_EVENT_KINDS
                        and "divergence" in str(e.get("reason", ""))
                    ),
                    None,
                )
                if verdict is None:
                    continue
                self._intents[trial.key] = "diverged"
                self._diverged_at[trial.key] = now
                self._log(
                    f"divergence verdict for {trial.key} at step {verdict.get('step')}: "
                    f"{verdict.get('reason')} -> SIGTERM"
                )
                self._signal(trial.key, signal.SIGTERM)
                break

    def _signal(self, key: str, signum: int) -> None:
        proc = self._procs.get(key)
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signum)
            except (ProcessLookupError, OSError):
                pass

    # -- exploit/explore ----------------------------------------------------------- #

    def _try_resow(self, trial: Trial, now: float) -> None:
        rcfg = self.cfg.resow
        if not rcfg.enabled or trial.resows >= int(rcfg.max_per_trial):
            trial.to(T.FAILED, reason=f"resow budget exhausted ({trial.resows}/{rcfg.max_per_trial})")
            self._resow_deadline.pop(trial.key, None)
            self._log(f"{trial.key}: resow budget exhausted -> failed")
            return
        exclude = [trial.key] + [t.key for t in self.trials if t.state == T.DIVERGED]
        dirs = {t.key: self.trial_dir(t.key) for t in self.trials if t.key != trial.key}
        parent = select_parent(dirs, exclude=exclude)
        if parent is not None:
            pkey, ckpt, step = parent
            trial.resows += 1
            trial.generation += 1
            trial.parent = pkey
            trial.hyperparams = perturb(
                trial.hyperparams, list(rcfg.perturb.keys or []), list(rcfg.perturb.factors or [])
            )
            trial.resume_ckpt = ckpt
            trial.next_eligible = now
            trial.to(T.RESOWN, parent=pkey, ckpt=ckpt, parent_step=step)
            self._resow_deadline.pop(trial.key, None)
            self._log(
                f"resow {trial.key} gen={trial.generation} from {pkey}'s certified step-{step} "
                f"checkpoint, hyperparams={trial.hyperparams}"
            )
            return
        deadline = self._resow_deadline.setdefault(trial.key, now + float(rcfg.parent_wait_s))
        if now < deadline:
            return  # stay DIVERGED; retried every poll until a peer certifies
        # no peer certified anything within the window: from-scratch requeue,
        # counted against the failure budget (matches configs/orchestrate)
        self._resow_deadline.pop(trial.key, None)
        trial.failures += 1
        if trial.failures > self.scheduler.max_failures:
            trial.to(T.FAILED, reason="no resow parent and failure budget exhausted")
            self._log(f"{trial.key}: no resow parent, budget exhausted -> failed")
            return
        trial.generation += 1
        trial.parent = None
        trial.resume_ckpt = None
        trial.next_eligible = now
        trial.to(T.RESOWN, parent=None, ckpt=None, fallback="scratch")
        self._log(f"{trial.key}: no certified peer within parent_wait_s, resowing from scratch")

    def _retry_diverged(self, now: float) -> None:
        for trial in self.trials:
            if trial.state == T.DIVERGED:
                self._try_resow(trial, now)
                self._save()

    def _maybe_exploit(self, now: float) -> None:
        ecfg = self.cfg.exploit
        interval = float(ecfg.interval_s)
        if interval <= 0 or now - self._last_exploit < interval:
            return
        self._last_exploit = now
        fits: Dict[str, int] = {}
        for t in self.trials:
            if t.terminal:
                continue
            fit = certified_fitness(self.trial_dir(t.key))
            if fit is not None:
                fits[t.key] = fit[1]
        if len(fits) < int(ecfg.min_peers):
            return
        from sheeprl_tpu.orchestrate.resow import bottom_quantile

        leader = max(fits.values())
        for key in bottom_quantile(fits, float(ecfg.quantile)):
            t = self._trial(key)
            if t.state != T.RUNNING or key in self._intents:
                continue
            if leader - fits[key] <= int(ecfg.min_lead):
                continue
            self._intents[key] = "exploit"
            self._log(f"exploit: {key} (step {fits[key]}) trails leader (step {leader}) -> SIGTERM")
            self._signal(key, signal.SIGTERM)
            break  # at most one exploit kill per tick keeps the fleet stable

    # -- chaos injection (drill knob) ----------------------------------------------- #

    def _maybe_inject(self, now: float) -> None:
        if self._inject_remaining <= 0:
            return
        if failpoints.has("orchestrate.inject"):
            # Deterministic drill clock: `orchestrate.inject:fire::every=N`
            # injects on every Nth eligible controller tick, independent of
            # wall-clock spacing (which races trial startup on loaded hosts).
            if failpoints.failpoint("orchestrate.inject", remaining=self._inject_remaining) is not True:
                return
        elif now - self._last_inject < self._inject_spacing_s:
            return
        candidates = [
            t
            for t in self.trials
            if t.state == T.RUNNING
            and t.key not in self._intents
            and os.path.exists(self._ready_file(t.key))  # guard armed: SIGTERM is survivable
            and _newest_ckpt(self.trial_dir(t.key))  # something to resume from
        ]
        if not candidates:
            return
        candidates.sort(key=lambda t: (self._injected.get(t.key, 0), t.key))
        victim = candidates[0]
        self._intents[victim.key] = "preempt"
        self._injected[victim.key] = self._injected.get(victim.key, 0) + 1
        self._inject_remaining -= 1
        self._last_inject = now
        self.counters["injections"] += 1
        self._log(f"injecting preemption into {victim.key} (pid {victim.pid})")
        self._signal(victim.key, signal.SIGTERM)

    # -- restart reconciliation ------------------------------------------------------ #

    def _reconcile(self, now: float) -> None:
        """Journal says RUNNING but this controller incarnation owns no such
        process: the previous controller died. A still-alive orphan is
        preempted (SIGTERM -> its guard checkpoints); either way the trial
        requeues from its newest checkpoint. Completion cannot be inferred
        without a returncode, and resuming an already-finished run is benign
        (total_steps reached -> immediate clean exit)."""
        for trial in self.trials:
            if trial.state != T.RUNNING or trial.key in self._procs:
                continue
            if _pid_alive(trial.pid):
                self._log(f"reconcile: orphan pid {trial.pid} of {trial.key} alive -> SIGTERM")
                try:
                    os.kill(int(trial.pid), signal.SIGTERM)
                except OSError:
                    pass
                deadline = time.time() + 30.0
                while _pid_alive(trial.pid) and time.time() < deadline:
                    time.sleep(0.2)
                if _pid_alive(trial.pid):
                    try:
                        os.kill(int(trial.pid), signal.SIGKILL)
                    except OSError:
                        pass
            trial.pid = None
            trial.to(T.PREEMPTED, reason="controller restart")
            self._preempted_at[trial.key] = now
            ckpt = _newest_ckpt(self.trial_dir(trial.key))
            self.scheduler.requeue_preempted(trial, ckpt, now)
            self._log(f"reconcile: {trial.key} requeued (resume={'yes' if ckpt else 'no'})")
        for trial in self.trials:
            if trial.state == T.DIVERGED:
                self._diverged_at.setdefault(trial.key, now)
        self._save()

    # -- shutdown ------------------------------------------------------------------- #

    def _drain(self, status: str, already_signalled: bool) -> str:
        """Forward SIGTERM (if the guard has not already), wait out the
        children's emergency checkpoints, classify every exit, journal."""
        if not already_signalled:
            for key in list(self._procs):
                self._signal(key, signal.SIGTERM)
        deadline = time.time() + float(self.cfg.shutdown.drain_timeout_s)
        while self._procs and time.time() < deadline:
            self._poll_exits(time.time())
            time.sleep(0.1)
        for key, proc in list(self._procs.items()):
            self._log(f"drain: {key} did not exit in time, killing")
            try:
                proc.kill()
                proc.wait(timeout=10)
            except Exception:
                pass
            trial = self._trial(key)
            self._reap(key)
            trial.to(T.PREEMPTED, reason="drain timeout kill")
            self.scheduler.requeue_preempted(trial, _newest_ckpt(self.trial_dir(key)), time.time())
        self._save()
        self._log(f"controller exiting: {status}")
        return status

    # -- main loop -------------------------------------------------------------------- #

    def run(self, max_runtime_s: Optional[float] = None) -> str:
        start = time.time()
        with PreemptionGuard(enabled=True, forward_to_children=True) as guard:
            self.guard = guard
            self._reconcile(time.time())
            while True:
                now = time.time()
                if guard.should_stop:
                    self._log(f"controller received {guard.describe()}; draining fleet")
                    # the guard already forwarded the signal to every child
                    return self._drain("preempted", already_signalled=True)
                if max_runtime_s is not None and now - start > max_runtime_s:
                    return self._drain("timeout", already_signalled=False)
                self._poll_exits(now)
                self._watch_health(now)
                self._retry_diverged(now)
                self._maybe_exploit(now)
                self._maybe_inject(now)
                for trial in self.scheduler.next_to_run(self.trials, now):
                    self._spawn(trial, now)
                if all(t.terminal for t in self.trials):
                    self._save()
                    self._log("all trials terminal")
                    return "done"
                time.sleep(float(self.cfg.poll_interval_s))

    def summary(self, status: str) -> Dict[str, Any]:
        return {
            "status": status,
            "trials": {t.key: {"state": t.state, "generation": t.generation} for t in self.trials},
            "counters": {
                k: v
                for k, v in self.counters.items()
                if k in ("spawn_seq", "preempt_recoveries", "resow_walls", "injections", "controller_incarnations")
            },
        }


def load_spec(path: str) -> Tuple[List[TrialSpec], Any]:
    """Population spec JSON: ``{"orchestrate": {...policy...}, "trials": [...]}``.
    Returns the trial specs and the raw dict (``resolve`` reads the group)."""
    with open(path) as f:
        spec = json.load(f)
    specs = [TrialSpec.from_dict(d) for d in spec.get("trials", [])]
    if not specs:
        raise SystemExit(f"population spec {path} declares no trials")
    return specs, spec


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--spec", required=True, help="population spec JSON")
    parser.add_argument("--state-dir", required=True, help="journal/lineage/trial-dir root")
    parser.add_argument(
        "--inject-preempt",
        type=int,
        default=0,
        help="drill knob: SIGTERM this many armed running trials, spaced out",
    )
    parser.add_argument("--inject-spacing-s", type=float, default=2.0)
    parser.add_argument("--max-runtime-s", type=float, default=None)
    cli = parser.parse_args(argv)
    # the fused backend dispatches BEFORE load_spec: a fused-population spec
    # hosts the whole fleet in one trainee and declares no per-trial specs
    # (load_spec treats an empty trial list as a config error)
    with open(cli.spec) as f:
        raw_spec = json.load(f)
    if str(resolve(raw_spec).population.backend).lower() == "fused":
        from sheeprl_tpu.orchestrate.fused import FusedPopulationController

        fused = FusedPopulationController(cli.spec, cli.state_dir, cfg=raw_spec)
        status = fused.run(max_runtime_s=cli.max_runtime_s)
        print("ORCHESTRATE_RESULT " + json.dumps(fused.summary(status)), flush=True)
        return 0 if status in ("done", "preempted") else 3
    specs, spec = load_spec(cli.spec)
    controller = PopulationController(
        specs,
        cli.state_dir,
        cfg=spec,
        inject_preempt=cli.inject_preempt,
        inject_spacing_s=cli.inject_spacing_s,
    )
    status = controller.run(max_runtime_s=cli.max_runtime_s)
    print("ORCHESTRATE_RESULT " + json.dumps(controller.summary(status)), flush=True)
    return 0 if status in ("done", "preempted") else 3


if __name__ == "__main__":
    raise SystemExit(main())
