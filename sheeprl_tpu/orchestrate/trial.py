"""Trial state machine for the elastic population controller.

A *trial* is one member of the population: a training run identity (overrides +
hyperparameters + seed) that survives preemption and divergence by moving
through *incarnations* (generations). The controller owns N of these and a pool
of preemptible slots; this module owns only the bookkeeping — which transitions
are legal, what history is recorded, and how a trial serializes into the
crash-safe journal.

State graph (ISSUE 6 / ROADMAP item 5)::

    pending ──► running ──► completed            (terminal)
                  │ ├─────► failed               (terminal)
                  │ └─────► preempted ──► resumed ──► running ...
                  └───────► diverged  ──► resown  ──► running ...
                                 │             (new generation, peer ckpt)
                                 └──────► failed

``resumed`` and ``resown`` are *queued* states: the scheduler treats them like
``pending`` (eligible for a slot once their backoff elapses), but they carry
the resume checkpoint — the trial's own newest for ``resumed``, a healthy
peer's newest **certified** checkpoint for ``resown``.

Keep this module import-light (no jax): the journal loads it in the controller
process and in tests without touching an accelerator.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

# -- states ------------------------------------------------------------------ #

PENDING = "pending"
RUNNING = "running"
PREEMPTED = "preempted"
DIVERGED = "diverged"
RESUMED = "resumed"
RESOWN = "resown"
COMPLETED = "completed"
FAILED = "failed"

QUEUED_STATES = (PENDING, RESUMED, RESOWN)
TERMINAL_STATES = (COMPLETED, FAILED)

_TRANSITIONS: Dict[str, tuple] = {
    PENDING: (RUNNING,),
    RESUMED: (RUNNING,),
    RESOWN: (RUNNING,),
    RUNNING: (COMPLETED, PREEMPTED, DIVERGED, FAILED),
    PREEMPTED: (RESUMED, FAILED),
    DIVERGED: (RESOWN, FAILED),
    COMPLETED: (),
    FAILED: (),
}


class IllegalTransition(RuntimeError):
    """A state change the trial graph does not allow — always a controller bug,
    never weather; raised loudly instead of silently corrupting the journal."""


class TrialSpec:
    """Immutable identity of a population member.

    ``overrides`` are the Hydra-style dotlist every incarnation runs with;
    ``chaos_overrides`` ride along ONLY on generation 0 (they model transient
    environmental faults injected by the chaos drills — a resown generation is
    rescheduled 'weather-free', exactly like a trial migrated off a bad host).
    ``hyperparams`` maps dotted config keys to values; the exploit/explore step
    perturbs these, and on resume they are pushed through
    ``checkpoint.resume_preserve`` so the sidecar merge cannot swallow them.
    """

    def __init__(
        self,
        key: str,
        overrides: List[str],
        hyperparams: Optional[Dict[str, Any]] = None,
        chaos_overrides: Optional[List[str]] = None,
    ):
        self.key = str(key)
        self.overrides = list(overrides)
        self.hyperparams = dict(hyperparams or {})
        self.chaos_overrides = list(chaos_overrides or [])

    def to_dict(self) -> Dict[str, Any]:
        return {
            "key": self.key,
            "overrides": self.overrides,
            "hyperparams": self.hyperparams,
            "chaos_overrides": self.chaos_overrides,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrialSpec":
        return cls(
            key=d["key"],
            overrides=d.get("overrides", []),
            hyperparams=d.get("hyperparams"),
            chaos_overrides=d.get("chaos_overrides"),
        )


class Trial:
    """One population member's mutable runtime state.

    Everything here must survive a controller kill: the journal serializes the
    full object (``to_dict``/``from_dict``) on every transition, and the
    restarted controller reconciles ``running`` trials against what it finds on
    disk (markers, checkpoints, live pids).
    """

    def __init__(self, spec: TrialSpec):
        self.spec = spec
        self.state = PENDING
        self.generation = 0
        self.hyperparams = dict(spec.hyperparams)
        self.preemptions = 0
        self.failures = 0
        self.resows = 0
        self.resume_ckpt: Optional[str] = None
        self.parent: Optional[str] = None  # trial key a resow seeded from
        self.pid: Optional[int] = None
        self.next_eligible: float = 0.0  # monotonic-free: wall clock is fine here
        self.history: List[Dict[str, Any]] = []

    @property
    def key(self) -> str:
        return self.spec.key

    @property
    def queued(self) -> bool:
        return self.state in QUEUED_STATES

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL_STATES

    def to(self, state: str, **detail: Any) -> None:
        """Transition with validation; every transition is a history row."""
        allowed = _TRANSITIONS.get(self.state, ())
        if state not in allowed:
            raise IllegalTransition(
                f"trial {self.key}: {self.state} -> {state} is not a legal transition "
                f"(allowed: {list(allowed)})"
            )
        self.state = state
        self.history.append(
            {"state": state, "generation": self.generation, "time": time.time(), **detail}
        )

    # -- serialization -------------------------------------------------------- #

    def to_dict(self) -> Dict[str, Any]:
        return {
            "spec": self.spec.to_dict(),
            "state": self.state,
            "generation": self.generation,
            "hyperparams": self.hyperparams,
            "preemptions": self.preemptions,
            "failures": self.failures,
            "resows": self.resows,
            "resume_ckpt": self.resume_ckpt,
            "parent": self.parent,
            "pid": self.pid,
            "next_eligible": self.next_eligible,
            "history": self.history,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Trial":
        trial = cls(TrialSpec.from_dict(d["spec"]))
        trial.state = d.get("state", PENDING)
        trial.generation = int(d.get("generation", 0))
        trial.hyperparams = dict(d.get("hyperparams", {}))
        trial.preemptions = int(d.get("preemptions", 0))
        trial.failures = int(d.get("failures", 0))
        trial.resows = int(d.get("resows", 0))
        trial.resume_ckpt = d.get("resume_ckpt")
        trial.parent = d.get("parent")
        trial.pid = d.get("pid")
        trial.next_eligible = float(d.get("next_eligible", 0.0))
        trial.history = list(d.get("history", []))
        return trial
