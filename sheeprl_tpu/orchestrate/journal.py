"""Crash-safe controller state: an fsync'd, atomically-replaced JSON journal.

The population controller must itself be preemptible: SIGTERM (or kill -9) at
any instant, then a restart with the same ``--state-dir``, must resume the
fleet with no duplicated or lost trials. The journal is therefore written with
the same durability discipline as checkpoints (``utils/checkpoint.save_state``):
temp file -> fsync -> ``os.replace`` -> directory fsync, so the file under the
final name is always either the previous snapshot or the complete new one.

A snapshot (not an event log) keeps recovery trivial — ``Journal.load`` is the
whole story — while the append-only *lineage* record lives separately in
``lineage.jsonl`` (see :mod:`sheeprl_tpu.orchestrate.lineage`).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from sheeprl_tpu.orchestrate.trial import Trial

JOURNAL_VERSION = 1


def _fsync_dir(dirname: str) -> None:
    try:
        dfd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(dfd)
    except OSError:
        pass
    finally:
        os.close(dfd)


class Journal:
    """Snapshot store for the controller's full mutable state."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    def save(self, trials: List[Trial], counters: Optional[Dict[str, Any]] = None) -> None:
        payload = {
            "version": JOURNAL_VERSION,
            "updated": time.time(),
            "trials": [t.to_dict() for t in trials],
            "counters": dict(counters or {}),
        }
        parent = os.path.dirname(self.path)
        os.makedirs(parent, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        _fsync_dir(parent)

    def load(self) -> Optional[Dict[str, Any]]:
        """The raw snapshot dict, or None when no journal exists yet. A torn or
        unparseable file is impossible by construction (atomic replace), so a
        parse error here is real corruption and should surface, not be eaten."""
        try:
            with open(self.path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def load_trials(self) -> List[Trial]:
        snap = self.load()
        if not snap:
            return []
        return [Trial.from_dict(d) for d in snap.get("trials", [])]
