"""One supervised process hosting the WHOLE in-graph PBT population.

The ``population.backend=fused`` counterpart of the subprocess-per-trial
fleet: instead of N ``sheeprl.py`` children (N jax imports, N compiles, N host
loops), the :class:`~sheeprl_tpu.envs.ingraph.population.PopulationTrainer`
trains all N members as ONE compiled vmapped program, and this process is the
single supervised trainee the :class:`FusedPopulationController` drives. The
orchestrate contract is preserved at the fleet level:

- **journal/lineage rows per member**: every epoch appends the ``[N]``
  fitness/nonfinite vectors (the only steady-state host pull) to
  ``population/fitness.jsonl``; every exploit swap lands in
  ``lineage.jsonl`` as a ``resow`` row (member ``m03`` cloned from ``m01``
  with these perturb factors) — the same file ``orchestrate/lineage.py``
  reads, so ancestry reconstruction works unchanged;
- **certified per-member checkpoint slices**: every ``checkpoint_every``
  epochs each member's params/opt-state slice is saved + certified through
  ``utils/checkpoint.py`` (the rolling-deploy / resow medium elsewhere);
- **health sentinel on the fitness vector**: the
  :class:`~sheeprl_tpu.envs.ingraph.population.PopulationSentinel` classifies
  members from the already-pulled vectors, adding zero device traffic;
- **chaos seams**: ``population.exploit`` fires before every in-graph exploit
  and ``population.member_sync`` before every member checkpoint slice — a
  ``fire`` action on the latter poisons the member's params (NaN), which the
  nonfinite counter flags and the next exploit heals (drilled by
  ``scripts/population_fused_smoke.py``);
- **preemption**: the process runs under ``PreemptionGuard`` with the
  controller's READY/FLAG files, so SIGTERM drains exactly like any trial.

Per-member episode-metric pulls are gated to ``metric.log_every`` drains
(the PR 11 pattern): between drains an epoch's host traffic is the ``[N]``
vectors, nothing else.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.core import compile as jax_compile
from sheeprl_tpu.core import failpoints
from sheeprl_tpu.core.resilience import PreemptionGuard
from sheeprl_tpu.core.runtime import build_runtime
from sheeprl_tpu.config import instantiate, load_config
from sheeprl_tpu.envs import ingraph as ig
from sheeprl_tpu.orchestrate import resolve
from sheeprl_tpu.orchestrate.lineage import LineageLog
from sheeprl_tpu.utils.checkpoint import certify
from sheeprl_tpu.utils.ckpt_sharded import ShardedCheckpointer
from sheeprl_tpu.utils.optim import with_clipping

RESULT_TAG = "POPULATION_FUSED "


def _append_jsonl(path: str, row: Dict[str, Any]) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(row) + "\n")
        f.flush()


def _poison_member(state: ig.PopulationState, member: int) -> ig.PopulationState:
    """Chaos drill payload for ``population.member_sync:fire``: NaN the
    member's param slice. The in-graph nonfinite counter flags it on the next
    epoch and exploit replaces it from a healthy peer — the fused analogue of
    the subprocess fleet's divergence -> resow path."""
    poisoned = jax.tree_util.tree_map(
        lambda x: x.at[member].set(jnp.nan) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        state.params,
    )
    return state._replace(params=poisoned)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--spec", required=True, help="population spec JSON")
    parser.add_argument("--state-dir", required=True, help="journal/lineage/checkpoint root")
    parser.add_argument("--max-runtime-s", type=float, default=None)
    cli = parser.parse_args(argv)

    with open(cli.spec) as f:
        raw = json.load(f)
    pcfg = resolve(raw).population
    state_dir = os.path.abspath(cli.state_dir)
    os.makedirs(state_dir, exist_ok=True)

    members = int(pcfg.members)
    envs_per_member = int(pcfg.envs_per_member)
    epochs = int(pcfg.epochs)
    devices = int(pcfg.devices)

    overrides = list(pcfg.overrides or []) + [
        f"env.num_envs={envs_per_member}",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
    ]
    if devices > 1:
        overrides.append(f"fabric.devices={devices}")
    cfg = load_config(overrides=overrides)
    if ig.env_backend(cfg) != "ingraph":
        raise SystemExit("population.backend=fused requires an env.backend=ingraph config")

    runtime = build_runtime(cfg.fabric)
    world_size = int(runtime.world_size)
    mesh = runtime.mesh if world_size > 1 else None
    if members % max(world_size, 1) != 0:
        raise SystemExit(f"population.members={members} must divide by devices={world_size}")

    # ----- single-member stack: same builders as the fused single-member loop
    import gymnasium as gym

    from sheeprl_tpu.algos.ppo.agent import build_agent

    venv = ig.make_vector_env(cfg, envs_per_member, int(cfg.seed), device=runtime.device)
    space = venv.single_action_space
    is_continuous = isinstance(space, gym.spaces.Box)
    actions_dim = tuple(space.shape) if is_continuous else (int(space.n),)
    agent, params, player = build_agent(
        runtime, actions_dim, is_continuous, cfg, venv.single_observation_space, None
    )
    player.params = jax.device_put(player.params, runtime.device)
    venv.reset(seed=int(cfg.seed))
    collector = ig.InGraphRolloutCollector(
        venv,
        player,
        rollout_steps=int(cfg.algo.rollout_steps),
        gamma=float(cfg.algo.gamma),
        clip_rewards=bool(cfg.env.clip_rewards),
        store_logprobs=True,
        name="population",
    )
    tx = with_clipping(instantiate(dict(cfg.algo.optimizer))(), cfg.algo.max_grad_norm)
    opt_state = tx.init(params)
    n_data = envs_per_member * int(cfg.algo.rollout_steps)

    algo_name = str(cfg.algo.name).lower()
    if algo_name.startswith("a2c"):
        from sheeprl_tpu.algos.a2c.a2c import make_update_impl

        update_impl = make_update_impl(
            agent, tx, cfg, runtime, n_data, ["state"], None,
            constrain_data=False, batch_size=int(cfg.algo.per_rank_batch_size),
        )
        base_hypers = (1.0,)
    else:
        from sheeprl_tpu.algos.ppo.ppo import make_update_impl

        # batch_size pins the PER-MEMBER batch: the mesh (when any) shards
        # members, so the data-parallel world_size scaling must not apply
        update_impl = make_update_impl(
            agent, tx, cfg, runtime, n_data, ["state"], [], None,
            constrain_data=False, batch_size=int(cfg.algo.per_rank_batch_size),
        )
        base_hypers = (float(cfg.algo.clip_coef), float(cfg.algo.ent_coef), 1.0)

    trainer = ig.PopulationTrainer(
        collector,
        update_impl,
        n_hypers=len(base_hypers),
        iters_per_epoch=int(pcfg.iters_per_epoch),
        fitness_alpha=float(pcfg.fitness_alpha),
        quantile=float(pcfg.quantile),
        factors=tuple(pcfg.factors or (0.8, 1.25)),
        perturb_mask=pcfg.perturb_mask,
        mesh=mesh,
        name="population",
    )

    # ----- domain randomization (envs/ingraph/domainrand.py): None disables;
    # True/"default" uses the env's default ranges; a dict overrides them
    key = jax.random.PRNGKey(int(cfg.seed))
    dr = pcfg.domain_rand
    env_overrides = None
    ranges: Dict[str, Any] = {}
    if dr:
        ranges = ig.resolve_ranges(
            venv.env_params, cfg.env.id, None if dr in (True, "default") else dict(dr)
        )
        env_overrides = ig.sample_overrides(jax.random.fold_in(key, 17), members, ranges)
        env_overrides = trainer.commit_env_overrides(env_overrides)

    # ----- background AOT warmup from SINGLE-member specs (stacked_specs):
    # the epoch/exploit executables compile while init_population stacks N
    # copies of the model on the main thread
    warmup = jax_compile.AOTWarmup(enabled=jax_compile.aot_enabled(cfg))
    if warmup.enabled:
        warmup.add(
            trainer.epoch_fn,
            *trainer.stacked_warmup_specs(params, opt_state, base_hypers, members, env_overrides),
        )
        warmup.add(
            trainer.exploit_fn,
            *trainer.stacked_exploit_specs(params, opt_state, base_hypers, members),
        )
        warmup.start()

    state = trainer.init_population(
        params, opt_state, jax.random.fold_in(key, 23), members, base_hypers, env_overrides
    )

    lineage = LineageLog(os.path.join(state_dir, "lineage.jsonl"))
    fitness_log = os.path.join(state_dir, "population", "fitness.jsonl")
    sentinel = ig.PopulationSentinel()
    generations = [0] * members
    hyper_names = (
        ("algo.clip_coef", "algo.ent_coef", "lr_scale")
        if len(base_hypers) == 3
        else ("lr_scale",)
    )
    for i in range(members):
        lineage.record(
            "seed",
            f"m{i:02d}",
            0,
            hyperparams=dict(zip(hyper_names, [float(h) for h in base_hypers])),
            backend="fused",
        )

    env_steps_per_epoch = members * envs_per_member * int(cfg.algo.rollout_steps) * int(
        pcfg.iters_per_epoch
    )
    policy_step, last_log = 0, 0
    log_every = int(cfg.metric.log_every)
    log_level = int(cfg.metric.log_level)
    exploits = swaps = 0
    epochs_done = 0
    status = "done"
    warmup.wait()
    jax_compile.mark_steady()
    # async writer for the per-member certified slices (single-process world:
    # commit needs no barrier; the win is moving pickle+fsync off the loop)
    checkpointer = ShardedCheckpointer(process_index=0, world=1)
    t_train0 = time.perf_counter()

    with PreemptionGuard(enabled=True) as guard:
        for ep in range(epochs):
            state, last_roll, train_ms = trainer.run_epoch(
                state, env_overrides, jax.random.fold_in(key, 1000 + ep)
            )
            policy_step += env_steps_per_epoch
            fitness = np.asarray(state.fitness)
            nonfinite = np.asarray(state.nonfinite)
            report = sentinel.check(fitness, nonfinite, ep)

            # episode/loss pulls gated to log_every drains (PR 11 pattern): a
            # steady-state epoch's host traffic is the two [N] vectors above
            if log_level > 0 and (
                policy_step - last_log >= log_every or ep == epochs - 1
            ):
                last_log = policy_step
                losses = {
                    k: np.nanmean(np.asarray(v), axis=0).tolist()
                    for k, v in train_ms.items()
                    if k.startswith("Loss/")
                }
                ep_counts = [
                    sum(1 for _ in ig.iter_finished_episodes(
                        {mk: np.asarray(mv)[i] for mk, mv in last_roll.items()}
                    ))
                    for i in range(members)
                ]
                print(
                    f"[population] epoch {ep}: policy_step={policy_step} "
                    f"fitness={np.round(fitness, 3).tolist()} episodes={ep_counts}",
                    flush=True,
                )
                _append_jsonl(
                    fitness_log,
                    {
                        "epoch": ep,
                        "policy_step": policy_step,
                        "losses": losses,
                        "episodes": ep_counts,
                        "kind": "drain",
                    },
                )

            # ----- in-graph exploit/explore at the epoch boundary
            failpoints.failpoint("population.exploit", epoch=ep)
            state, member_src, factor = trainer.exploit(
                state, jax.random.fold_in(key, 2000 + ep)
            )
            exploits += 1
            src = np.asarray(member_src)
            fac = np.asarray(factor)
            hypers_now = [np.asarray(h) for h in state.hypers]
            for i in range(members):
                if int(src[i]) == i:
                    continue
                swaps += 1
                generations[i] += 1
                lineage.record(
                    "resow",
                    f"m{i:02d}",
                    generations[i],
                    parent=f"m{int(src[i]):02d}",
                    hyperparams={
                        name: float(hypers_now[j][i]) for j, name in enumerate(hyper_names)
                    },
                    factors=[float(x) for x in fac[i]],
                    backend="fused",
                )
            _append_jsonl(
                fitness_log,
                {
                    "epoch": ep,
                    "fitness": [float(x) for x in fitness],
                    "nonfinite": [int(x) for x in nonfinite],
                    "member_src": [int(x) for x in src],
                    "bad_members": report["bad_members"],
                    "kind": "epoch",
                },
            )

            # ----- certified per-member checkpoint slices
            # The async sharded writer keeps the epoch loop paying only the
            # per-member D2H snapshot; serialization, fsync, commit, and
            # certification all land on its background thread. Saves are
            # strictly ordered, so the per-member drill semantics are intact.
            if (ep + 1) % max(int(pcfg.checkpoint_every), 1) == 0:
                for i in range(members):
                    fired = failpoints.failpoint(
                        "population.member_sync", member=i, epoch=ep
                    )
                    if fired is True:
                        # drill: the sync "corrupting" this member stands in
                        # for any per-member fault — poison it and let the
                        # nonfinite counter + exploit heal it in-graph
                        state = _poison_member(state, i)
                        print(f"[population] member_sync drill poisoned m{i:02d}", flush=True)
                        continue
                    mdir = os.path.join(state_dir, "members", f"m{i:02d}")
                    path = os.path.join(mdir, f"ckpt_ep{ep:04d}.ckpt")
                    member_state = {
                        # device-side row slices: the checkpointer's snapshot
                        # copies exactly one member's rows to host, not the
                        # whole fleet's stacked params twice over
                        "agent": jax.tree_util.tree_map(lambda x: x[i], state.params),
                        "optimizer": jax.tree_util.tree_map(lambda x: x[i], state.opt_state),
                        "hypers": [float(h[i]) for h in hypers_now],
                        "fitness": float(fitness[i]),
                        "epoch": ep,
                        "member": i,
                    }

                    def _certify_member(
                        p: str, _result: Dict[str, Any], _i: int = i, _ep: int = ep, _ps: int = policy_step
                    ) -> None:
                        certify(p, member=_i, epoch=_ep, policy_step=_ps)

                    checkpointer.save(path, member_state, finalize=_certify_member)

            epochs_done = ep + 1
            if guard.should_stop:
                status = "preempted"
                break
            if cli.max_runtime_s is not None and time.perf_counter() - t_train0 > cli.max_runtime_s:
                status = "timeout"
                break

    # drain in-flight member-slice writes: the controller reads the certified
    # slices the moment this process reports, so they must be durable first
    checkpointer.close()
    train_wall_s = time.perf_counter() - t_train0
    total_env_steps = epochs_done * env_steps_per_epoch
    summary = {
        "status": status,
        "backend": "fused",
        "members": members,
        "envs_per_member": envs_per_member,
        "world_size": world_size,
        "epochs_done": epochs_done,
        "env_steps": total_env_steps,
        "train_wall_s": round(train_wall_s, 3),
        "agg_env_steps_per_s": round(total_env_steps / max(train_wall_s, 1e-9), 1),
        "exploits": exploits,
        "swaps": swaps,
        "retraces": int(trainer.epoch_fn.retraces + trainer.exploit_fn.retraces),
        "fitness": [float(x) for x in np.asarray(state.fitness)],
        "domain_rand": sorted(ranges),
        "sentinel_events": len(sentinel.events),
    }
    print(RESULT_TAG + json.dumps(summary), flush=True)
    venv.close()
    return 0 if status in ("done", "preempted") else 3


if __name__ == "__main__":
    sys.exit(main())
