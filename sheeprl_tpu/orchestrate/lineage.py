"""Append-only lineage log: every seed/resume/resow edge of the population.

One JSON line per scheduling event, so the best trial's ancestry — which peer
checkpoint it was resown from, with which perturbed hyperparameters, how many
preemptions each generation survived — is reconstructable after the fact. PBT
papers call this the "lineage" of the winning member; operationally it is the
audit trail that turns "trial 2 won" into "trial 2 is trial 1's step-48
certified checkpoint with lr x1.25, resown after trial 2's original weights
diverged under a reward spike".

Edge kinds:

- ``seed``    — generation 0 starts from scratch;
- ``resume``  — the same generation continues from its OWN newest checkpoint
  after a preemption;
- ``resow``   — a new generation starts from a *peer's* certified checkpoint
  with perturbed hyperparameters (the exploit/explore step).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional


class LineageLog:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        os.makedirs(os.path.dirname(self.path), exist_ok=True)

    def record(
        self,
        kind: str,
        trial: str,
        generation: int,
        parent: Optional[str] = None,
        ckpt: Optional[str] = None,
        hyperparams: Optional[Dict[str, Any]] = None,
        **extra: Any,
    ) -> None:
        row = {
            "kind": kind,
            "trial": trial,
            "generation": int(generation),
            "parent": parent,
            "ckpt": ckpt,
            "hyperparams": dict(hyperparams or {}),
            "time": time.time(),
            **extra,
        }
        with open(self.path, "a") as f:
            f.write(json.dumps(row) + "\n")
            f.flush()
            os.fsync(f.fileno())


def read_lineage(path: str) -> List[Dict[str, Any]]:
    try:
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]
    except FileNotFoundError:
        return []


def ancestry(path: str, trial: str) -> List[Dict[str, Any]]:
    """The edge chain that produced ``trial``, oldest first.

    Walks resow edges backwards across trials: a trial resown from a peer
    inherits the peer's history *up to the resow point* (later peer edges did
    not contribute to the child's weights). Bounded by the edge count, so a
    (journal-corruption) cycle cannot loop forever.
    """
    edges = read_lineage(path)

    def _chain(key: str, before: float, hops: int) -> List[Dict[str, Any]]:
        if hops > len(edges):
            return []
        own = [e for e in edges if e.get("trial") == key and e.get("time", 0.0) <= before]
        resows = [e for e in own if e.get("parent") and e.get("parent") != key]
        if not resows:
            return own
        last = resows[-1]
        return _chain(last["parent"], last.get("time", before), hops + 1) + own

    return _chain(trial, float("inf"), 0)
