"""Elastic population orchestration on preemptible fleets (ROADMAP item 5).

PRs 2 and 5 made a *single* run survive preemption, worker death, and
divergence (``core/resilience.py``, ``core/health.py``, certified checkpoint
sidecars). This package composes those guarantees at the *fleet* level: a
controller runs N concurrent trials — PBT-style hyperparameter populations, or
one agent across a scenario matrix — as supervised subprocesses on a pool of
preemptible slots, treating preemption and divergence as routine scheduling
events:

- a preempted slot's trial checkpoints (its own ``PreemptionGuard``) and is
  requeued with jittered bounded backoff, resuming from its newest checkpoint;
- a diverged trial (verdict read from its ``HealthSentinel``'s
  ``health/events.jsonl``) is killed and *resown* from a healthy peer's newest
  **certified** checkpoint with perturbed hyperparameters (exploit/explore);
- the controller itself is preemptible: crash-safe fsync'd JSON journal
  (:mod:`.journal`), SIGTERM forwarded to children
  (``PreemptionGuard(forward_to_children=True)``), restart resumes the fleet
  with no duplicated or lost trials;
- every seed/resume/resow edge lands in ``orchestrate/lineage.jsonl``
  (:mod:`.lineage`) so the best trial's ancestry is reconstructable.

Config lives in the ``orchestrate`` Hydra group; every read goes through
:func:`resolve` so specs and sidecars without the group still work.
"""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.orchestrate.journal import Journal
from sheeprl_tpu.orchestrate.lineage import LineageLog, ancestry, read_lineage
from sheeprl_tpu.orchestrate.trial import Trial, TrialSpec

_DEFAULTS: Dict[str, Any] = {
    "slots": 2,
    "poll_interval_s": 0.25,
    "trial": {
        "max_preemptions": 8,
        "max_failures": 2,
        "requeue_backoff_base_s": 0.5,
        "requeue_backoff_max_s": 30.0,
    },
    "resow": {
        "enabled": True,
        "max_per_trial": 2,
        "parent_wait_s": 120.0,
        "perturb": {"keys": [], "factors": [0.8, 1.25]},
    },
    "exploit": {"interval_s": 0.0, "quantile": 0.25, "min_peers": 3, "min_lead": 1},
    "shutdown": {"drain_timeout_s": 60.0},
    # device-resident vmapped population training (envs/ingraph/population.py):
    # backend=fused runs the WHOLE population as one supervised trainee process
    # hosting one compiled program; backend=subprocess is the classic
    # process-per-trial fleet above. Open-ended sub-dicts (overrides,
    # domain_rand ranges, perturb hyper list) default to None — _merge only
    # keeps keys present in a dict default, so a {} default would drop them.
    "population": {
        "backend": "subprocess",
        "members": 4,
        "envs_per_member": 16,
        "epochs": 4,
        "iters_per_epoch": 8,
        "fitness_alpha": 0.3,
        "quantile": 0.25,
        "factors": [0.8, 1.25],
        "perturb_mask": None,
        "checkpoint_every": 1,
        "devices": 1,
        "max_failures": 2,
        "domain_rand": None,
        "overrides": None,
    },
}


class _View:
    """Attribute view over a plain dict (mirrors ``resilience._View``)."""

    def __init__(self, d: Dict[str, Any]):
        self._d = d

    def __getattr__(self, name: str) -> Any:
        try:
            v = self._d[name]
        except KeyError:
            raise AttributeError(name) from None
        return _View(v) if isinstance(v, dict) else v


def _merge(defaults: Any, got: Any) -> Any:
    if not isinstance(defaults, dict):
        return defaults if got is None else got
    out = {}
    for k, dv in defaults.items():
        gv = None
        if got is not None:
            gv = got.get(k) if hasattr(got, "get") else getattr(got, k, None)
        out[k] = _merge(dv, gv)
    return out


def resolve(cfg: Any) -> _View:
    """Defaults-filled view of the ``orchestrate`` group.

    Accepts a full run config (reads ``cfg.orchestrate``), a bare group dict,
    or None. Missing keys fall back to the defaults above (which mirror
    ``configs/orchestrate/default.yaml``)."""
    group = None
    if cfg is not None:
        try:
            group = cfg.get("orchestrate") if hasattr(cfg, "get") else None
        except Exception:
            group = None
        if group is None and hasattr(cfg, "get"):
            # a bare orchestrate-group dict (the population spec embeds one)
            if any(k in cfg for k in _DEFAULTS):
                group = cfg
    return _View(_merge(_DEFAULTS, group))


__all__ = [
    "Journal",
    "LineageLog",
    "Trial",
    "TrialSpec",
    "ancestry",
    "read_lineage",
    "resolve",
]
