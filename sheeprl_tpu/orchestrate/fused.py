"""Supervisor for the fused population backend: ONE trainee, whole fleet.

``population.backend=fused`` collapses the N-trial subprocess fleet into a
single supervised child — :mod:`sheeprl_tpu.orchestrate.fused_trainee` — that
hosts the entire vmapped population in one compiled program. This controller
keeps the orchestrate supervision contract around it:

- the trainee runs under the same READY/FLAG preemption-guard file protocol
  every trial child uses, so SIGTERM drains (emergency state, clean exit 0)
  and a real preemption is distinguishable from completion;
- exits are classified with the same precedence as
  :class:`~sheeprl_tpu.orchestrate.controller.PopulationController`
  (controller kill intent > preemption flag > returncode), and crash exits
  are restarted up to ``population.max_failures`` times;
- the trainee's own journal surface (``population/fitness.jsonl``,
  ``lineage.jsonl``, certified per-member checkpoint slices) lives under the
  shared ``--state-dir`` layout.

The XLA device count for a multi-device population mesh must be forced
BEFORE jax initializes in the child, so the supervisor owns the
``xla_force_host_platform_device_count`` flag (``population.devices``).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from sheeprl_tpu.core.resilience import FLAG_FILE_ENV_VAR, READY_FILE_ENV_VAR, PreemptionGuard
from sheeprl_tpu.orchestrate import resolve
from sheeprl_tpu.orchestrate.fused_trainee import RESULT_TAG

READY_FILENAME = ".guard_ready"
FLAG_FILENAME = ".preempt_flag"


class FusedPopulationController:
    """Spawn/supervise/restart the single fused-population trainee."""

    def __init__(self, spec_path: str, state_dir: str, cfg: Any = None):
        self.spec_path = os.path.abspath(spec_path)
        self.state_dir = os.path.abspath(state_dir)
        os.makedirs(self.state_dir, exist_ok=True)
        self.pcfg = resolve(cfg).population
        self._proc: Optional[subprocess.Popen] = None
        self._log_f: Any = None
        self._intent: Optional[str] = None
        self.failures = 0
        self.incarnations = 0
        self.result: Optional[Dict[str, Any]] = None
        self.guard: Optional[PreemptionGuard] = None

    # -- paths ----------------------------------------------------------------- #

    def _ready_file(self) -> str:
        return os.path.join(self.state_dir, READY_FILENAME)

    def _flag_file(self) -> str:
        return os.path.join(self.state_dir, FLAG_FILENAME)

    def _log(self, msg: str) -> None:
        print(f"[orchestrate.fused] {msg}", flush=True)

    # -- child lifecycle -------------------------------------------------------- #

    def _spawn(self, max_runtime_s: Optional[float]) -> None:
        for path in (self._ready_file(), self._flag_file()):
            try:
                os.remove(path)
            except OSError:
                pass
        self.incarnations += 1
        log_path = os.path.join(self.state_dir, f"trainee_inc{self.incarnations:02d}.log")
        self._log_f = open(log_path, "ab")
        env = dict(
            os.environ,
            JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
            **{
                READY_FILE_ENV_VAR: self._ready_file(),
                FLAG_FILE_ENV_VAR: self._flag_file(),
            },
        )
        devices = int(self.pcfg.devices)
        if devices > 1:
            flags = env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                env["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count={devices}".strip()
                )
        argv = [
            sys.executable,
            "-m",
            "sheeprl_tpu.orchestrate.fused_trainee",
            "--spec",
            self.spec_path,
            "--state-dir",
            self.state_dir,
        ]
        if max_runtime_s is not None:
            argv += ["--max-runtime-s", str(max_runtime_s)]
        self._proc = subprocess.Popen(
            argv, env=env, stdout=subprocess.PIPE, stderr=self._log_f, text=True
        )
        if self.guard is not None:
            self.guard.register_child(self._proc.pid)
        self._log(
            f"spawned fused trainee inc={self.incarnations} pid={self._proc.pid} "
            f"members={self.pcfg.members} devices={devices}"
        )

    def _reap(self) -> int:
        assert self._proc is not None
        out, _ = self._proc.communicate()
        rc = self._proc.returncode
        if self.guard is not None:
            self.guard.unregister_child(self._proc.pid)
        for line in (out or "").splitlines():
            if line.startswith(RESULT_TAG):
                try:
                    self.result = json.loads(line[len(RESULT_TAG) :])
                except json.JSONDecodeError:
                    pass
            else:
                print(line, flush=True)
        if self._log_f is not None:
            try:
                self._log_f.close()
            except OSError:
                pass
        self._proc = None
        return rc

    # -- main loop --------------------------------------------------------------- #

    def run(self, max_runtime_s: Optional[float] = None) -> str:
        start = time.time()
        max_failures = int(self.pcfg.max_failures)
        with PreemptionGuard(enabled=True, forward_to_children=True) as guard:
            self.guard = guard
            while True:
                budget = None
                if max_runtime_s is not None:
                    budget = max(max_runtime_s - (time.time() - start), 1.0)
                self._spawn(budget)
                while self._proc.poll() is None:
                    if guard.should_stop and self._intent is None:
                        # the guard already forwarded the signal; remember why
                        self._intent = "preempt"
                    if (
                        max_runtime_s is not None
                        and time.time() - start > max_runtime_s
                        and self._intent is None
                    ):
                        self._intent = "timeout"
                        try:
                            self._proc.send_signal(signal.SIGTERM)
                        except (ProcessLookupError, OSError):
                            pass
                    time.sleep(0.1)
                rc = self._reap()
                intent, self._intent = self._intent, None
                flagged = os.path.exists(self._flag_file())
                if intent == "preempt" or (flagged and intent is None):
                    self._log(f"trainee preempted (rc={rc})")
                    return "preempted"
                if intent == "timeout":
                    self._log(f"trainee stopped at the runtime budget (rc={rc})")
                    return "timeout"
                if rc == 0:
                    self._log("trainee completed")
                    return "done"
                self.failures += 1
                self._log(f"trainee crashed (rc={rc}), failures={self.failures}/{max_failures}")
                if self.failures > max_failures:
                    return "failed"

    def summary(self, status: str) -> Dict[str, Any]:
        return {
            "status": status,
            "backend": "fused",
            "incarnations": self.incarnations,
            "failures": self.failures,
            "trainee": self.result or {},
        }
