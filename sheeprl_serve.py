"""Thin shim: `python sheeprl_serve.py checkpoint_path=...` or
`python sheeprl_serve.py model_name=<registered model>` (mirrors sheeprl_eval.py)."""

from sheeprl_tpu.cli import serve

if __name__ == "__main__":
    serve()
