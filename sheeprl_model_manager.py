"""Thin shim: `python sheeprl_model_manager.py checkpoint_path=...`
(reference: sheeprl_model_manager.py)."""

from sheeprl_tpu.cli import registration

if __name__ == "__main__":
    registration()
