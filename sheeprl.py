"""Thin shim: `python sheeprl.py exp=... ` (reference: sheeprl.py)."""

from sheeprl_tpu.cli import run

if __name__ == "__main__":
    run()
