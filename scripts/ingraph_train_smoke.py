#!/usr/bin/env python
"""Fused in-graph TRAINING smoke: whole-iteration fused PPO, single + sharded.

Two fresh interpreters each train PPO on the in-graph CartPole with the
whole-iteration fused step (``envs/ingraph/fused.py``: rollout scan + GAE +
update epochs in ONE donated-carry program):

- ``fused``:   single device, three iterations (warmup + two steady-state);
- ``sharded``: the ``shard_map`` variant on a 2-device virtual CPU mesh
  (``--xla_force_host_platform_device_count=2`` + ``fabric.devices=2``), env
  batch sharded on the ``data`` axis, grads pmean'd in-graph.

Each child must finish with ZERO retraces — the fused entry point, its AOT
warmup spec, and the mesh placements all agree on one abstract signature, or
the fused wiring (envs/ingraph/ + algos/ppo + core/compile.py) has drifted —
and must then play finite-return episodes through the debug step path (the
cheap "training left a working policy/env behind" signal).

Run directly (``python scripts/ingraph_train_smoke.py``) or through the
registered tier-1 test (tests/test_utils/test_ingraph_train_smoke.py).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import subprocess
import sys
import tempfile

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import contextlib, json, os, sys
import numpy as np
from sheeprl_tpu.cli import run
from sheeprl_tpu.core import compile as jax_compile

overrides = json.loads(os.environ["_SHEEPRL_INGRAPH_TRAIN_SMOKE_OVERRIDES"])
with contextlib.redirect_stdout(sys.stderr):
    run(overrides=overrides)
stats = jax_compile.process_stats()
fused_stats = {
    name: s for name, s in stats["functions"].items()
    if name.endswith(".ingraph_train")
}

# random-policy drive through the debug step path: episodes must finish with
# finite returns (auto-reset keeps every env alive the whole time)
from sheeprl_tpu.config import load_config
from sheeprl_tpu.envs import ingraph as ig

with contextlib.redirect_stdout(sys.stderr):
    cfg = load_config(overrides=overrides)
    venv = ig.make_vector_env(cfg, 8, 123)
    venv.reset(seed=123)
    rng = np.random.default_rng(0)
    returns = []
    for _ in range(64):
        _obs, _rew, term, trunc, info = venv.step(rng.integers(0, 2, size=(8,)))
        done = np.logical_or(term, trunc)
        returns.extend(float(r) for r in info["episode_returns"][done])

print("INGRAPH_TRAIN_SMOKE " + json.dumps({
    "retraces": stats["retraces"],
    "traces": stats["traces"],
    "aot_compiles": stats["aot_compiles"],
    "fused_calls": sum(s["calls"] for s in fused_stats.values()),
    "n_episodes": len(returns),
    "mean_return": (sum(returns) / len(returns)) if returns else None,
}), flush=True)
"""

_BASE_OVERRIDES = [
    "exp=ppo",
    "env=jax_cartpole",
    "env.fused=True",
    "env.num_envs=16",
    "algo.rollout_steps=16",
    "algo.per_rank_batch_size=128",
    "algo.update_epochs=1",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.run_test=False",
    "metric.log_level=0",
    "metric.disable_timer=True",
    "checkpoint.every=999999999",
    "checkpoint.save_last=False",
    "buffer.memmap=False",
]

# 3 iterations each: warmup + two steady-state (the retrace check needs >= 2
# post-warmup calls to catch a signature that only stabilizes after the first)
VARIANTS = {
    "fused": {
        "overrides": _BASE_OVERRIDES + ["fabric.devices=1", "algo.total_steps=768"],
        "devices": 1,
    },
    "sharded": {
        # world_size=2 doubles the driven env batch (n_envs = num_envs * world)
        "overrides": _BASE_OVERRIDES + ["fabric.devices=2", "algo.total_steps=1536"],
        "devices": 2,
    },
}


def _run_variant(name: str, spec: dict, workdir: str, timeout: float) -> dict:
    xla_flags = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    xla_flags.append(f"--xla_force_host_platform_device_count={spec['devices']}")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=" ".join(xla_flags),
        PYTHONPATH=REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
        SHEEPRL_TPU_COMP_CACHE_DIR=os.path.join(workdir, "xla_cache"),
        _SHEEPRL_INGRAPH_TRAIN_SMOKE_OVERRIDES=json.dumps(spec["overrides"]),
    )
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD],
        cwd=workdir,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    tag = "INGRAPH_TRAIN_SMOKE "
    line = next((ln for ln in proc.stdout.splitlines() if ln.startswith(tag)), None)
    if proc.returncode != 0 or line is None:
        raise SystemExit(
            f"'{name}' child failed (rc={proc.returncode});\nstdout tail:\n{proc.stdout[-1000:]}"
            f"\nstderr tail:\n{proc.stderr[-3000:]}"
        )
    stats = json.loads(line[len(tag):])

    if stats["retraces"] != 0:
        raise SystemExit(f"'{name}': retraces during the fused train smoke: {stats['retraces']}")
    if stats["fused_calls"] < 3:
        raise SystemExit(f"'{name}': fused entry point ran {stats['fused_calls']} times, expected >= 3")
    if stats["n_episodes"] <= 0:
        raise SystemExit(f"'{name}': no episode finished in 64 random-policy steps x 8 envs")
    if stats["mean_return"] is None or not math.isfinite(stats["mean_return"]):
        raise SystemExit(f"'{name}': non-finite mean episode return: {stats['mean_return']}")
    return stats


def main(workdir: str | None = None, timeout: float = 480.0) -> dict:
    workdir = workdir or tempfile.mkdtemp(prefix="ingraph_train_smoke_")
    os.makedirs(workdir, exist_ok=True)
    results = {
        name: _run_variant(name, spec, workdir, timeout) for name, spec in VARIANTS.items()
    }
    print(f"ingraph train smoke OK: {json.dumps(results)}")
    return results


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workdir", default=None, help="scratch dir (default: a fresh tempdir)")
    parser.add_argument("--timeout", type=float, default=480.0, help="per-child timeout in seconds")
    cli = parser.parse_args()
    main(cli.workdir, cli.timeout)
