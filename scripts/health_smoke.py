#!/usr/bin/env python
"""Health smoke: inject a bounded reward-spike fault into a tiny PPO run and
prove the self-healing runtime end-to-end:

1. launch a CPU PPO run whose chaos env multiplies rewards by 1e6 for env
   steps [60, 80) — the spike flows through GAE into Loss/value_loss, which
   the health sentinel's z-score divergence detector must catch;
2. tuned so the graded ladder climbs warn -> backoff -> rollback inside the
   fault window, and ``checkpoint.every`` lands certified (``last_good``)
   checkpoints BEFORE the fault so there is something safe to roll back to;
3. assert the process exits 0 (detection + rollback + grace + recovery, then
   the run simply completes), that certified sidecars were written, and that
   ``<log_dir>/health/events.jsonl`` records the full warn/backoff/rollback
   sequence with a flight-recorder dump per detection.

Run directly (``python scripts/health_smoke.py``) or through the registered
tier-1 test (tests/test_utils/test_health_smoke.py). ``bench.py --target
health`` reuses :func:`main` and reports the detection latency and rollback
wall clock parsed from the event log.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One fault, bounded in env steps (the ChaosEnv counter is cumulative across
# resets, so the window closes at absolute step 80 even after the rollback
# reseeds the vector env). With rollout_steps=4 and one sync env the spiked
# iterations are ~15-20; certified checkpoints land at policy steps 16/32/48,
# and the step-64 checkpoint is written AFTER the first detection so it must
# stay uncertified — the rollback target is the step-48 state.
OVERRIDES = [
    "exp=ppo",
    "env=dummy",
    "env.id=discrete_dummy",
    "env.num_envs=1",
    "env.sync_env=True",
    "env.capture_video=False",
    "fabric.devices=1",
    "metric.log_level=0",
    "seed=7",
    "algo.rollout_steps=4",
    "algo.per_rank_batch_size=2",
    "algo.update_epochs=1",
    "algo.total_steps=160",
    "algo.mlp_keys.encoder=[state]",
    "algo.cnn_keys.encoder=[]",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.run_test=False",
    "buffer.memmap=False",
    "checkpoint.every=16",
    "checkpoint.save_last=False",
    "env.wrapper._target_=sheeprl_tpu.envs.chaos.chaos_dummy_env",
    "env.wrapper.chaos.reward_scale_from=60",
    "env.wrapper.chaos.reward_scale_until=80",
    "env.wrapper.chaos.reward_scale=1e6",
    "health.enabled=True",
    "health.check_every=1",
    "health.divergence.warmup=4",
    "health.divergence.streak=1",
    # early-training drift on a 4-sample warmup reaches z~10; the injected
    # spike reaches z~1e6..1e12, so 50 separates them with orders to spare
    "health.divergence.z_threshold=50.0",
    "health.divergence.z_clear=20.0",
    # CPU CI timing is too noisy for the SPS detector; divergence is the fault
    "health.stall.enabled=False",
    "health.response.grace_iters=3",
    "health.response.recover_iters=4",
    "health.response.rollback_budget=2",
]


def _find(root: str, predicate) -> list:
    found = []
    for base, _, files in os.walk(root):
        found += [os.path.join(base, f) for f in files if predicate(f)]
    return sorted(found)


def main(workdir: str | None = None, timeout: float = 540.0) -> dict:
    workdir = workdir or tempfile.mkdtemp(prefix="health_smoke_")
    os.makedirs(workdir, exist_ok=True)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "sheeprl.py")] + OVERRIDES,
        cwd=workdir,
        env=dict(os.environ, JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu")),
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    if proc.returncode != 0:
        raise SystemExit(
            f"faulted run exited rc={proc.returncode} (the sentinel should have "
            f"ridden it out); stderr tail:\n{proc.stderr[-2000:]}"
        )

    logs = os.path.join(workdir, "logs")
    event_files = _find(logs, lambda f: f == "events.jsonl")
    if len(event_files) != 1:
        raise SystemExit(f"expected exactly one health/events.jsonl, got {event_files}")
    with open(event_files[0]) as f:
        events = [json.loads(line) for line in f if line.strip()]
    kinds = [e["event"] for e in events]
    for expected in ("warn", "backoff", "rollback_requested", "rollback"):
        if expected not in kinds:
            raise SystemExit(f"no '{expected}' event recorded; got kinds={kinds}")

    sidecars = _find(logs, lambda f: f.endswith(".certified.json"))
    if not sidecars:
        raise SystemExit("no certified (last_good) checkpoint sidecar on disk")
    flights = _find(logs, lambda f: f.startswith("flight_") and f.endswith(".jsonl"))
    if not flights:
        raise SystemExit("no flight-recorder dump written on detection")

    rollback = next(e for e in events if e["event"] == "rollback")
    return {
        "workdir": workdir,
        "events": event_files[0],
        "event_kinds": kinds,
        "rollbacks": kinds.count("rollback"),
        "certified_sidecars": len(sidecars),
        "flight_dumps": len(flights),
        "detection_latency_s": rollback.get("detection_latency_s"),
        "detection_latency_steps": rollback.get("detection_latency_steps"),
        "rollback_wall_s": rollback.get("wall_s"),
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workdir", default=None, help="run directory (default: fresh tempdir)")
    parser.add_argument("--timeout", type=float, default=540.0, help="run timeout in seconds")
    cli = parser.parse_args()
    result = main(cli.workdir, cli.timeout)
    print(
        "health smoke OK: divergence detected "
        f"(latency {result['detection_latency_s']}s / {result['detection_latency_steps']} steps), "
        f"rolled back to a certified checkpoint in {result['rollback_wall_s']}s, run completed"
    )
