#!/usr/bin/env python
"""Sharded-checkpoint smoke: two-host elastic checkpointing + recovery drill.

Proves the sharded checkpoint subsystem (sheeprl_tpu/utils/ckpt_sharded.py)
keeps its durability contract while the failpoint registry
(core/failpoints.py) kills hosts at the nastiest instants:

1. a parent process runs a :class:`KVServer` and spawns two jax-free "host"
   children (ranks 0/1 of a world-2 fleet) that each write ONLY their own
   shard windows into a shared ``*.ckpt`` directory, rendezvous through the
   control plane, and two-phase-commit the generation;
2. **healthy generation**: both hosts save; the parent audits the committed
   layout, loads the FULL state through the ordinary ``load_state`` dispatch
   (a world-1 reader — the topology-elastic restore), and certifies it;
3. **host killed between shard write and commit** (``ckpt.commit:kill``): the
   commit marker never appears, ``latest_certified`` still points at the
   previous generation, and ``load_state`` on the torn generation falls back
   to it — the fleet resumes from the previous certified checkpoint;
4. **zombie commit fence**: a commit attempt stamped with the dead
   incarnation's epoch raises ``StaleEpochError`` before the marker rename;
5. **host killed mid shard write** (``ckpt.shard_write:kill``): the surviving
   rank's commit barrier times out, so no partial-shard generation can ever
   become visible;
6. **recovery + GC**: the restarted fleet commits a new generation and the
   orphan sweep removes the two abandoned uncommitted directories;
7. **peer-RAM emergency recovery**: host 0 replicates its state into host 1's
   RAM over the epoch-fenced chunk transport (``ckpt.replicate`` failpoint
   kills it mid-epoch on the third push); a restarted host 0 restores from
   the peer copy with ZERO persistent-storage reads (``READ_OPENS == 0``)
   and bit-identical state.

Run directly (``python scripts/ckpt_sharded_smoke.py``) or through the
registered tier-1 test (tests/test_utils/test_ckpt_sharded_smoke.py).
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from sheeprl_tpu.core import failpoints  # noqa: E402
from sheeprl_tpu.parallel.control import (  # noqa: E402
    ControlPlane,
    ControlPlaneTimeoutError,
    SocketKV,
    StaleEpochError,
)
from sheeprl_tpu.utils import ckpt_sharded as cs  # noqa: E402

SCOPE = "ckpt_smoke"
WORLD = 2
FENCE_ROLE = "ckpt_writer"
REP_ROLE = "host0_replicator"


def _drill_state(gen: int) -> dict:
    """Deterministic world-2 state: axis-0-splittable array leaves (rows 0-3
    belong to rank 0, rows 4-7 to rank 1) plus inline scalar leaves."""
    return {
        "params": {
            "w": (np.arange(64, dtype=np.float64).reshape(8, 8) + gen),
            "b": (np.arange(8, dtype=np.float32) * gen),
        },
        "odd": np.arange(7, dtype=np.int64) + gen,  # indivisible: rank 0 owns it whole
        "step": int(gen),
    }


def _state_equal(a: dict, b: dict) -> bool:
    return (
        np.array_equal(a["params"]["w"], b["params"]["w"])
        and np.array_equal(a["params"]["b"], b["params"]["b"])
        and np.array_equal(a["odd"], b["odd"])
        and a["step"] == b["step"]
    )


def _gen_path(ckpt_dir: str, gen: int) -> str:
    return os.path.join(ckpt_dir, f"ckpt_{gen}_0.ckpt")


# --------------------------------------------------------------------------- children
def run_host(addr: str, rank: int, ckpt_dir: str, gens: list, barrier_ms: int) -> None:
    kv = SocketKV(addr)
    plane = ControlPlane(kv, rank=rank, world=WORLD, scope=SCOPE, timeout_ms=30_000)
    epoch = plane.begin_session(FENCE_ROLE) if rank == 0 else plane.adopt_epoch(FENCE_ROLE)
    saved, failures = [], []
    for gen in gens:
        path = _gen_path(ckpt_dir, gen)
        try:
            cs.save_sharded(
                path,
                _drill_state(gen),
                process_index=rank,
                world=WORLD,
                plane=plane,
                epoch=epoch,
                fence_role=FENCE_ROLE,
                barrier_timeout_ms=barrier_ms,
            )
            saved.append(gen)
        except (ControlPlaneTimeoutError, StaleEpochError) as e:
            # a dead/fenced peer: the generation must stay uncommitted, the
            # host reports the failure and carries on (the fleet supervisor's
            # reaction, not the drill's concern here)
            failures.append({"gen": gen, "err": type(e).__name__})
    print(json.dumps({"role": "host", "rank": rank, "epoch": epoch, "saved": saved, "failures": failures}))


def run_peer(addr: str) -> None:
    """Host 1's replica store: keeps host 0's latest pushed state in RAM and
    answers its restarted incarnation's fetch — no storage anywhere."""
    kv = SocketKV(addr)
    plane = ControlPlane(kv, rank=1, world=WORLD, scope=SCOPE, timeout_ms=30_000)
    store = cs.PeerReplicaStore(plane, src_rank=0, poll_ms=100, fence_role=REP_ROLE)
    store.start()
    stop_key = plane._key("drill", "peer_stop")
    while kv.try_get(stop_key, timeout_ms=100) is None:
        time.sleep(0.05)
    store.stop()
    store.join(timeout=5.0)
    held = store.snapshots_held
    latest_gen = store.latest[0] if store.latest is not None else None
    print(json.dumps({"role": "peer", "snapshots_held": held, "latest_gen": latest_gen}))


def run_worker_push(addr: str, pushes: int) -> None:
    """Host 0 pushing state snapshots to its peer; the ``ckpt.replicate``
    failpoint SIGKILLs it mid-epoch on the final attempt."""
    kv = SocketKV(addr)
    plane = ControlPlane(kv, rank=0, world=WORLD, scope=SCOPE, timeout_ms=30_000)
    plane.begin_session(REP_ROLE)
    for gen in range(1, pushes + 1):
        payload = pickle.dumps(_drill_state(gen), protocol=pickle.HIGHEST_PROTOCOL)
        cs.replicate_to_peer(plane, payload, generation=gen, timeout_ms=30_000)
    print(json.dumps({"role": "worker_push", "pushes": pushes}))


def run_worker_restore(addr: str) -> None:
    """Host 0's restarted incarnation: restore from peer RAM, prove zero
    persistent-storage reads happened on the way."""
    kv = SocketKV(addr)
    plane = ControlPlane(kv, rank=0, world=WORLD, scope=SCOPE, timeout_ms=30_000)
    got = cs.fetch_from_peer(plane, timeout_ms=30_000)
    if got is None:
        print(json.dumps({"role": "worker_restore", "ok": False, "err": "no peer answer"}))
        return
    gen, payload = got
    state = pickle.loads(payload)
    print(
        json.dumps(
            {
                "role": "worker_restore",
                "ok": bool(_state_equal(state, _drill_state(gen))),
                "gen": gen,
                "read_opens": cs.READ_OPENS,  # sharded-load file opens in THIS process
                "payload_bytes": len(payload),
            }
        )
    )


# --------------------------------------------------------------------------- parent
def _spawn(args: list, failpoints_spec: str = "") -> subprocess.Popen:
    env = dict(os.environ)
    env.pop("SHEEPRL_TPU_FAILPOINTS", None)
    if failpoints_spec:
        env["SHEEPRL_TPU_FAILPOINTS"] = failpoints_spec
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)] + args,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )


def _result(proc: subprocess.Popen, label: str, timeout: float) -> dict:
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, err = proc.communicate()
        raise SystemExit(f"{label} hung; stdout:\n{out[-2000:]}\nstderr:\n{err[-2000:]}")
    if proc.returncode != 0:
        raise SystemExit(f"{label} exited rc={proc.returncode}; stderr tail:\n{err[-2000:]}")
    last = out.strip().splitlines()[-1] if out.strip() else ""
    try:
        return json.loads(last)
    except ValueError:
        raise SystemExit(f"{label} printed no JSON result; stdout tail:\n{out[-2000:]}")


def _expect_kill(proc: subprocess.Popen, label: str, timeout: float) -> None:
    out, err = proc.communicate(timeout=timeout)
    if proc.returncode != 9:
        raise SystemExit(
            f"{label} should die by its kill failpoint (rc 9), got rc={proc.returncode}; "
            f"stderr tail:\n{err[-2000:]}\nstdout:\n{out[-500:]}"
        )


def _run_fleet_save(addr: str, ckpt_dir: str, gen: int, timeout: float, *, fp0: str = "", fp1: str = "",
                    barrier_ms: int = 30_000, expect_kill_rank=None) -> dict:
    hosts = [
        _spawn(
            ["--role", "host", "--addr", addr, "--rank", str(r), "--dir", ckpt_dir,
             "--gens", str(gen), "--barrier-ms", str(barrier_ms)],
            fp0 if r == 0 else fp1,
        )
        for r in range(WORLD)
    ]
    results = {}
    for r, proc in enumerate(hosts):
        if r == expect_kill_rank:
            _expect_kill(proc, f"gen-{gen} host {r}", timeout)
        else:
            results[r] = _result(proc, f"gen-{gen} host {r}", timeout)
    return results


def main(timeout: float = 300.0) -> dict:
    from sheeprl_tpu.parallel.control import KVServer
    from sheeprl_tpu.utils import checkpoint as ck  # jax import stays in the parent

    started = time.monotonic()
    server = KVServer()
    server.start()
    kv = SocketKV(server.address)
    plane = ControlPlane(kv, rank=99, world=WORLD, scope=SCOPE)  # parent's key helper only
    ckpt_dir = tempfile.mkdtemp(prefix="ckpt_sharded_smoke_")
    try:
        # ---- phase 1: healthy generation ------------------------------------
        _run_fleet_save(server.address, ckpt_dir, 100, timeout)
        g100 = _gen_path(ckpt_dir, 100)
        if not cs.is_committed(g100):
            raise SystemExit("phase 1: committed generation has no COMMIT marker")
        shard_files = sorted(n for n in os.listdir(g100) if n.startswith("shard_"))
        if shard_files != ["shard_00000.bin", "shard_00001.bin"]:
            raise SystemExit(f"phase 1: expected one shard per host, got {shard_files}")
        # topology-elastic read: this world-1 parent assembles the full state
        stats: dict = {}
        state = cs.load_sharded(g100, stats)
        if not _state_equal(state, _drill_state(100)):
            raise SystemExit("phase 1: world-1 restore of the world-2 checkpoint is not bit-identical")
        ck.certify(g100, policy_step=100)
        if ck.latest_certified(ckpt_dir) != g100:
            raise SystemExit("phase 1: certified generation not visible to latest_certified")

        # ---- phase 2: host 0 killed between shard write and commit ----------
        _run_fleet_save(
            server.address, ckpt_dir, 200, timeout,
            fp0=failpoints.spec_entry("ckpt.commit", "kill", "9", "hit=1"),
            expect_kill_rank=0,
        )
        g200 = _gen_path(ckpt_dir, 200)
        if cs.is_committed(g200):
            raise SystemExit("phase 2: generation committed despite the pre-commit kill")
        if ck.latest_certified(ckpt_dir) != g100:
            raise SystemExit("phase 2: latest_certified moved off the previous generation")
        resumed = ck.load_state(g200)  # must fall back to the previous certified sibling
        if resumed["step"] != 100:
            raise SystemExit(f"phase 2: resume landed on step {resumed['step']}, want 100")

        # ---- phase 3: zombie commit fence -----------------------------------
        dead_epoch = 1  # phase 1's incarnation; phase 2's restart bumped past it
        fenced = False
        try:
            cs.commit(g200, {0: {"file": "shard_00000.bin"}}, plane=plane, epoch=dead_epoch,
                      fence_role=FENCE_ROLE)
        except StaleEpochError:
            fenced = True
        if not fenced or cs.is_committed(g200):
            raise SystemExit("phase 3: a dead incarnation's commit was not fenced")

        # ---- phase 4: host 1 killed mid shard write -------------------------
        results = _run_fleet_save(
            server.address, ckpt_dir, 250, timeout,
            fp1=failpoints.spec_entry("ckpt.shard_write", "kill", "9", "hit=1"),
            barrier_ms=4_000,
            expect_kill_rank=1,
        )
        if results[0]["failures"] != [{"gen": 250, "err": "ControlPlaneTimeoutError"}]:
            raise SystemExit(f"phase 4: surviving host should time out its commit barrier, got {results[0]}")
        if cs.is_committed(_gen_path(ckpt_dir, 250)):
            raise SystemExit("phase 4: partial-shard generation became visible")

        # ---- phase 5: recovery + orphan GC ----------------------------------
        _run_fleet_save(server.address, ckpt_dir, 300, timeout)
        g300 = _gen_path(ckpt_dir, 300)
        ck.certify(g300, policy_step=300)
        if ck.latest_certified(ckpt_dir) != g300:
            raise SystemExit("phase 5: recovered fleet's generation not the newest certified")
        swept = sorted(os.path.basename(p) for p in cs.sweep_orphaned(ckpt_dir))
        if swept != ["ckpt_200_0.ckpt", "ckpt_250_0.ckpt"]:
            raise SystemExit(f"phase 5: orphan sweep removed {swept}, want the two abandoned generations")
        left = sorted(n for n in os.listdir(ckpt_dir) if n.endswith(".ckpt"))
        if left != ["ckpt_100_0.ckpt", "ckpt_300_0.ckpt"]:
            raise SystemExit(f"phase 5: surviving generations wrong: {left}")

        # ---- phase 6: peer-RAM emergency recovery ---------------------------
        peer = _spawn(["--role", "peer", "--addr", server.address])
        pusher = _spawn(
            ["--role", "worker-push", "--addr", server.address, "--pushes", "3"],
            # dies mid-epoch on its third replication push — after the peer
            # already holds generation 2 in RAM
            failpoints.spec_entry("ckpt.replicate", "kill", "9", "hit=3"),
        )
        _expect_kill(pusher, "phase 6 pusher", timeout)
        restorer = _spawn(["--role", "worker-restore", "--addr", server.address])
        restored = _result(restorer, "phase 6 restorer", timeout)
        kv.set(plane._key("drill", "peer_stop"), "1")
        peer_res = _result(peer, "phase 6 peer", timeout)
        if not restored.get("ok") or restored.get("gen") != 2:
            raise SystemExit(f"phase 6: peer-RAM restore wrong: {restored}")
        if restored.get("read_opens") != 0:
            raise SystemExit(
                f"phase 6: peer-RAM restore touched persistent storage "
                f"({restored['read_opens']} read opens, want 0)"
            )
        if peer_res.get("snapshots_held", 0) < 2 or peer_res.get("latest_gen") != 2:
            raise SystemExit(f"phase 6: peer store state wrong: {peer_res}")
    finally:
        server.stop()

    return {
        "generations_committed": [100, 300],
        "generations_discarded": [200, 250],
        "zombie_commit_fenced": True,
        "partial_reads_bytes": stats.get("bytes_read", 0),
        "peer_restore_gen": restored["gen"],
        "peer_restore_read_opens": restored["read_opens"],
        "wall_s": round(time.monotonic() - started, 2),
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--role",
        choices=["parent", "host", "peer", "worker-push", "worker-restore"],
        default="parent",
    )
    parser.add_argument("--addr", default=None, help="KV server address (child roles)")
    parser.add_argument("--rank", type=int, default=0, help="host: fleet rank")
    parser.add_argument("--dir", default=None, help="host: shared checkpoint dir")
    parser.add_argument("--gens", default="", help="host: comma-separated generation steps to save")
    parser.add_argument("--barrier-ms", type=int, default=30_000, help="host: commit barrier budget")
    parser.add_argument("--pushes", type=int, default=3, help="worker-push: replication attempts")
    parser.add_argument("--timeout", type=float, default=300.0, help="parent: per-child budget in seconds")
    cli = parser.parse_args()
    if cli.role == "host":
        run_host(cli.addr, cli.rank, cli.dir, [int(g) for g in cli.gens.split(",") if g], cli.barrier_ms)
    elif cli.role == "peer":
        run_peer(cli.addr)
    elif cli.role == "worker-push":
        run_worker_push(cli.addr, cli.pushes)
    elif cli.role == "worker-restore":
        run_worker_restore(cli.addr)
    else:
        result = main(cli.timeout)
        print(
            "ckpt sharded smoke OK: "
            f"generations {result['generations_committed']} committed, "
            f"{result['generations_discarded']} discarded (pre-commit kills + zombie fence), "
            f"peer-RAM restore of gen {result['peer_restore_gen']} with "
            f"{result['peer_restore_read_opens']} storage reads "
            f"({result['wall_s']}s)"
        )
