"""DDP vs FSDP DV3 train-step comparison on the 8-device virtual CPU mesh.

One real chip is available in this environment, so the absolute times are CPU
numbers; what this measures is the RELATIVE overhead the FSDP placement adds
(XLA-inserted weight all-gathers) and the per-device param-memory win — the
quantities that carry to a real multi-chip mesh.

Usage: python scripts/fsdp_bench.py [--preset S|M] [--iters 5]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
flags = [f for f in os.environ.get("XLA_FLAGS", "").split() if "host_platform_device_count" not in f]
flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gymnasium as gym  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="S", choices=("S", "M"))
    ap.add_argument("--iters", type=int, default=5)
    args = ap.parse_args()

    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_fn
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
    from sheeprl_tpu.config.loader import load_config
    from sheeprl_tpu.core.runtime import Runtime

    # sizes: big enough that kernels shard meaningfully over 8 devices, small
    # enough that the two CPU-mesh compiles stay in minutes on a 1-core host
    size_overrides = {
        "S": [
            "algo.dense_units=256",
            "algo.mlp_layers=2",
            "algo.world_model.encoder.cnn_channels_multiplier=16",
            "algo.world_model.recurrent_model.recurrent_state_size=512",
            "algo.world_model.transition_model.hidden_size=256",
            "algo.world_model.representation_model.hidden_size=256",
        ],
        "M": [],  # the real M preset, multi-core hosts only
    }[args.preset]
    cfg = load_config(
        overrides=[
            "exp=dreamer_v3",
            "algo=dreamer_v3_S" if args.preset == "S" else "algo=dreamer_v3_M",
            "env=dummy",
            "fabric.precision=32-true",
            "algo.per_rank_batch_size=16",
            "algo.per_rank_sequence_length=8",
            "algo.horizon=8",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.cnn_keys.decoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "algo.mlp_keys.decoder=[]",
            *size_overrides,
        ]
    )
    obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (3, 64, 64), np.uint8)})
    actions_dim = (6,)
    rng = np.random.default_rng(0)
    g, t, b, a = 1, 8, 16, 6
    batches = {
        "rgb": rng.integers(0, 255, (g, t, b, 3, 64, 64), dtype=np.uint8),
        "actions": rng.random((g, t, b, a), dtype=np.float32),
        "rewards": rng.random((g, t, b, 1), dtype=np.float32),
        "terminated": np.zeros((g, t, b, 1), dtype=np.float32),
        "truncated": np.zeros((g, t, b, 1), dtype=np.float32),
        "is_first": np.zeros((g, t, b, 1), dtype=np.float32),
    }
    key = jax.random.PRNGKey(0)

    result = {"preset": args.preset, "devices": jax.device_count()}
    for strategy in ("auto", "fsdp"):
        runtime = Runtime(accelerator="cpu", devices=8, strategy=strategy, precision="32-true")
        modules, params, _ = build_agent(runtime, actions_dim, False, cfg, obs_space)
        init_opt, train_fn = make_train_fn(modules, cfg, runtime, False, actions_dim)
        opt_states = runtime.place_params(init_opt(params))
        params = runtime.place_params(params)
        moments = init_moments()
        batch_sh = NamedSharding(runtime.mesh, P(None, None, "data"))
        dev_batches = {k: jax.device_put(jnp.asarray(v), batch_sh) for k, v in batches.items()}

        # per-device bytes actually held for params+opt (the FSDP memory win)
        def dev0_bytes(tree):
            total = 0
            for leaf in jax.tree_util.tree_leaves(tree):
                if hasattr(leaf, "addressable_shards"):
                    for sh in leaf.addressable_shards:
                        if sh.device == jax.devices()[0]:
                            total += sh.data.nbytes
            return total

        dev0_mb = round(dev0_bytes((params, opt_states)) / 1e6, 2)  # before donation
        counter = jnp.int32(0)
        # train_fn donates params/opt/moments: continue from the warmup outputs
        p, o, m, c, _flat, _metrics = train_fn(params, opt_states, moments, counter, dev_batches, key)
        jax.block_until_ready(p)  # compile + first step
        t0 = time.perf_counter()
        for _ in range(args.iters):
            p, o, m, c, _flat, _metrics = train_fn(p, o, m, c, dev_batches, key)
        jax.block_until_ready(p)
        dt = (time.perf_counter() - t0) / args.iters
        result[f"{strategy}_step_ms"] = round(dt * 1000, 1)
        result[f"{strategy}_dev0_param_opt_mb"] = dev0_mb

    result["fsdp_vs_ddp_time"] = round(result["fsdp_step_ms"] / result["auto_step_ms"], 3)
    result["fsdp_vs_ddp_mem"] = round(
        result["fsdp_dev0_param_opt_mb"] / result["auto_dev0_param_opt_mb"], 3
    )
    return result


if __name__ == "__main__":
    # agent-build banners etc. go to stderr; stdout carries exactly one JSON line
    with contextlib.redirect_stdout(sys.stderr):
        result = main()
    print(json.dumps(result))
