"""Dump TensorBoard scalar series from a run dir as CSV lines.

Used for post-hoc analysis of metrics the reward-curve scraper doesn't carry
(e.g. Dream-and-Ponder's ``State/expected_ponder_steps`` — the PonderNet
paper's own halting diagnostic).

Usage:
  python scripts/tb_scalars.py logs/runs/dream_and_ponder/.../version_0 State/expected_ponder_steps
  python scripts/tb_scalars.py <run_dir>            # list available tags
"""

from __future__ import annotations

import os
import sys


def main() -> None:
    if len(sys.argv) < 2:
        print(__doc__)
        raise SystemExit(2)
    run_dir = sys.argv[1]
    tags = sys.argv[2:]

    from tensorboard.backend.event_processing.event_accumulator import EventAccumulator

    acc = EventAccumulator(run_dir, size_guidance={"scalars": 0})
    acc.Reload()
    available = acc.Tags().get("scalars", [])
    if not tags:
        print("\n".join(sorted(available)))
        return
    for tag in tags:
        if tag not in available:
            print(f"# tag not found: {tag} (available: {sorted(available)})", file=sys.stderr)
            continue
        for ev in acc.Scalars(tag):
            print(f"{tag},{ev.step},{ev.value}")


if __name__ == "__main__":
    main()


def series(run_dir: str, tag: str):
    """Programmatic access: [(step, value), ...] for one scalar tag."""
    from tensorboard.backend.event_processing.event_accumulator import EventAccumulator

    acc = EventAccumulator(run_dir, size_guidance={"scalars": 0})
    acc.Reload()
    return [(ev.step, ev.value) for ev in acc.Scalars(tag)]
