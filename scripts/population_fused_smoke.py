#!/usr/bin/env python
"""Fused-population smoke: the chaos drill for ``population.backend=fused``.

Drives the device-resident vmapped PBT stack (envs/ingraph/population.py +
orchestrate/fused.py) through the REAL controller entry point and proves the
fleet contract end-to-end, in two phases:

1. **healthy run + member_sync drill** — a 4-member CartPole population with
   domain-randomized physics trains for 3 exploit epochs under one compiled
   program while the ``population.member_sync`` fire-failpoint poisons member
   m01's params (NaN) at its first checkpoint slice. The run must finish
   ``done`` with ZERO retraces, the sentinel must flag the poisoned member,
   the next in-graph exploit must resow it from a healthy peer (a ``resow``
   row in ``lineage.jsonl`` with a parent and perturb factors != 1 — the
   perturbed member's hypers diverge from the seed config), and every member
   must end with finite fitness and a certified checkpoint slice;
2. **exploit seam drill** — ``population.exploit:raise`` fires at the first
   epoch boundary; the trainee crashes, and the controller must classify the
   crash (not preemption, not completion) and report ``failed`` once
   ``population.max_failures=0`` is exhausted — the seam is live through the
   whole supervision stack.

Run directly (``python scripts/population_fused_smoke.py``) or through the
registered tier-1 test (tests/test_utils/test_population_fused_smoke.py).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from sheeprl_tpu.core import failpoints  # noqa: E402

_BASE_OVERRIDES = [
    "exp=ppo",
    "env=jax_cartpole",
    "algo.rollout_steps=4",
    "algo.per_rank_batch_size=16",
    "algo.update_epochs=1",
    "seed=7",
]

_SPEC = {
    "orchestrate": {
        "population": {
            "backend": "fused",
            "members": 4,
            "envs_per_member": 8,
            "epochs": 3,
            "iters_per_epoch": 2,
            "checkpoint_every": 1,
            "domain_rand": True,
            "overrides": _BASE_OVERRIDES,
        }
    }
}


def _run_controller(spec_path: str, state_dir: str, fp_spec: str | None, timeout: float):
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    env.pop("SHEEPRL_TPU_FAILPOINTS", None)
    if fp_spec:
        env["SHEEPRL_TPU_FAILPOINTS"] = fp_spec
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "sheeprl_tpu.orchestrate.controller",
            "--spec",
            spec_path,
            "--state-dir",
            state_dir,
        ],
        cwd=REPO_ROOT,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        raise SystemExit(f"controller did not finish within the timeout; tail:\n{out[-3000:]}")
    result_line = next(
        (l for l in reversed(out.splitlines()) if l.startswith("ORCHESTRATE_RESULT ")), None
    )
    if result_line is None:
        raise SystemExit(f"no ORCHESTRATE_RESULT line (rc={proc.returncode}); tail:\n{out[-3000:]}")
    return proc.returncode, json.loads(result_line.split("ORCHESTRATE_RESULT ", 1)[1]), out


def main(workdir: str | None = None, timeout: float = 600.0) -> dict:
    workdir = workdir or tempfile.mkdtemp(prefix="population_fused_smoke_")
    os.makedirs(workdir, exist_ok=True)
    spec_path = os.path.join(workdir, "population_fused.json")
    with open(spec_path, "w") as f:
        json.dump(_SPEC, f, indent=2)
    deadline = time.time() + timeout

    # ----- phase 1: healthy run with the member_sync poison drill.
    # hit=2 lands on the SECOND member_sync evaluation = member m01 at its
    # first checkpoint slice (epoch 0), AFTER epoch 0's exploit — so epoch 1
    # trains m01 on NaN params and epoch 1's exploit must heal it.
    state1 = os.path.join(workdir, "fused_healthy")
    rc, summary, out = _run_controller(
        spec_path,
        state1,
        failpoints.spec_entry("population.member_sync", "fire", trigger="hit=2"),
        max(deadline - time.time(), 60.0),
    )
    if rc != 0 or summary["status"] != "done":
        raise SystemExit(f"phase 1 rc={rc} summary={summary}; tail:\n{out[-3000:]}")
    trainee = summary["trainee"]
    if trainee["retraces"] != 0:
        raise SystemExit(f"fused population retraced: {trainee}")
    if trainee["exploits"] < 3 or trainee["swaps"] < 1:
        raise SystemExit(f"exploit never fired / never swapped: {trainee}")
    if trainee["sentinel_events"] < 1:
        raise SystemExit(f"sentinel missed the poisoned member: {trainee}")
    if "member_sync drill poisoned m01" not in out:
        raise SystemExit(f"member_sync drill did not fire; tail:\n{out[-3000:]}")
    if not all(x == x and abs(x) < 1e9 for x in trainee["fitness"]):
        raise SystemExit(f"population ended with nonfinite fitness: {trainee['fitness']}")

    with open(os.path.join(state1, "lineage.jsonl")) as f:
        edges = [json.loads(line) for line in f if line.strip()]
    seeds = [e for e in edges if e["kind"] == "seed"]
    resows = [e for e in edges if e["kind"] == "resow"]
    if len(seeds) != 4:
        raise SystemExit(f"expected 4 seed rows, got {len(seeds)}")
    if not resows:
        raise SystemExit(f"no resow row in lineage; kinds={[e['kind'] for e in edges]}")
    healed = [e for e in resows if e["trial"] == "m01" and e.get("parent")]
    if not healed:
        raise SystemExit(f"poisoned m01 was never resown from a peer: {resows}")
    # explore half: the perturbed member's hypers diverged from the seed config
    seed_hp = seeds[0]["hyperparams"]
    diverged = [
        e for e in resows
        if any(abs(v - seed_hp[k]) > 1e-9 for k, v in e["hyperparams"].items())
    ]
    if not diverged:
        raise SystemExit(f"no resown member's hyperparameters diverged: {resows}")

    # every member ends with a certified checkpoint slice
    for i in range(4):
        mdir = os.path.join(state1, "members", f"m{i:02d}")
        certs = [p for p in os.listdir(mdir) if p.endswith(".certified.json")]
        if not certs:
            raise SystemExit(f"member m{i:02d} has no certified checkpoint slice")

    # ----- phase 2: the exploit seam crashes the trainee; the controller
    # must classify it as a crash and give up at max_failures=0.
    spec2 = json.loads(json.dumps(_SPEC))
    spec2["orchestrate"]["population"]["max_failures"] = 0
    spec2_path = os.path.join(workdir, "population_fused_crash.json")
    with open(spec2_path, "w") as f:
        json.dump(spec2, f, indent=2)
    state2 = os.path.join(workdir, "fused_exploit_crash")
    rc2, summary2, out2 = _run_controller(
        spec2_path,
        state2,
        failpoints.spec_entry("population.exploit", "raise", "chaos-exploit", "hit=1"),
        max(deadline - time.time(), 60.0),
    )
    if rc2 == 0 or summary2["status"] != "failed":
        raise SystemExit(f"phase 2 should fail at max_failures=0: rc={rc2} {summary2}")
    if summary2["failures"] != 1 or summary2["incarnations"] != 1:
        raise SystemExit(f"unexpected crash accounting: {summary2}")

    return {
        "workdir": workdir,
        "healthy": trainee,
        "resow_edges": len(resows),
        "healed_member": healed[0]["trial"],
        "exploit_crash_status": summary2["status"],
        "lineage": os.path.join(state1, "lineage.jsonl"),
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workdir", default=None, help="drill directory (default: fresh tempdir)")
    parser.add_argument("--timeout", type=float, default=600.0, help="whole-drill timeout in seconds")
    cli = parser.parse_args()
    result = main(cli.workdir, cli.timeout)
    h = result["healthy"]
    print(
        "population fused smoke OK: "
        f"{h['members']} members x {h['envs_per_member']} envs, "
        f"{h['epochs_done']} epochs, {h['exploits']} exploits ({h['swaps']} swaps), "
        f"0 retraces, poisoned {result['healed_member']} healed in-graph, "
        f"exploit-seam crash classified '{result['exploit_crash_status']}', "
        f"lineage at {result['lineage']}"
    )
