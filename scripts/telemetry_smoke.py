#!/usr/bin/env python
"""Telemetry smoke: one trace id across every cross-plane record surface.

The drill proves the ISSUE's acceptance shape end-to-end with real processes:

1. reuse the serve-smoke fixture (tiny certified PPO checkpoint, no training)
   and launch ``sheeprl_serve.py`` with a pinned trace id in the
   ``SHEEPRL_TPU_TRACE`` env var — the shape a parent orchestrator uses to
   join children into its trace — plus a one-shot ``reload.canary:raise``
   failpoint;
2. drive infer requests over the TCP frontend (each records the
   admit->batch->infer->respond span lifecycle), then scrape the
   ``{"op": "metrics"}`` Prometheus exposition and check the trace id rides
   the ``sheeprl_run_info`` series;
3. certify a second checkpoint generation: the canary failpoint trips the
   reload, and the rollback must land in ``<run_dir>/health/events.jsonl``
   stamped with the SAME trace id (core/health.append_event); the retry then
   hot-reloads generation 2 for real;
4. SIGTERM: the final stats snapshot must carry ``trace_path``/``trace_id``,
   and the exported Chrome trace at that path must hold the same id in its
   metadata plus the serve/request and rollback-marked serve/reload spans.

One request tripping one failpoint is therefore visible — under one id — in
the Perfetto export, the Prometheus op, and the health event log. Run
directly (``python scripts/telemetry_smoke.py``) or through the registered
tier-1 test (tests/test_utils/test_telemetry_smoke.py).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import time
import uuid

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from sheeprl_tpu.core import failpoints  # noqa: E402
from scripts.serve_smoke import (  # noqa: E402
    _wait_until,
    build_fixture,
    launch_server,
    perturb,
    rpc,
    wait_ready,
    write_generation,
)


def _read_events(events_path: str) -> list:
    if not os.path.isfile(events_path):
        return []
    with open(events_path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def main(workdir: str | None = None, timeout: float = 300.0) -> dict:
    workdir = workdir or tempfile.mkdtemp(prefix="telemetry_smoke_")
    os.makedirs(workdir, exist_ok=True)
    started = time.monotonic()
    trace_id = uuid.uuid4().hex[:16]

    fixture = build_fixture(workdir)
    events_path = os.path.join(fixture["run_dir"], "health", "events.jsonl")
    ready_file = os.path.join(workdir, "ready.json")
    stats_file = os.path.join(workdir, "stats.json")
    log_file = os.path.join(workdir, "server.log")
    proc = launch_server(
        fixture,
        ready_file,
        stats_file,
        log_file,
        env_extra={
            # the parent-pins-the-id join: the server's tracer must adopt this
            # trace id at import instead of minting its own
            "SHEEPRL_TPU_TRACE": f"plane=serve;trace_id={trace_id}",
            "SHEEPRL_TPU_FAILPOINTS": failpoints.spec_entry(
                "reload.canary", "raise", "telemetry-drill", "hit=1"
            ),
        },
    )
    try:
        info = wait_ready(ready_file, proc, log_file, timeout=min(240.0, timeout))
        addr = (info["host"], info["port"])

        # -- surface 1: request lifecycle spans + the Prometheus op ----------
        for i in range(8):
            resp = rpc(addr, {"id": f"tel-{i}", "obs": fixture["obs"]})
            if resp.get("status") != "ok":
                raise SystemExit(f"infer request {i} not ok: {resp}")
        metrics = rpc(addr, {"op": "metrics"})
        if metrics.get("status") != "ok":
            raise SystemExit(f"metrics op failed: {metrics}")
        if metrics.get("trace_id") != trace_id:
            raise SystemExit(
                f"metrics op trace_id={metrics.get('trace_id')!r}, expected {trace_id!r}: "
                "the server did not join the parent's trace"
            )
        text = metrics["text"]
        run_info = f'sheeprl_run_info{{trace_id="{trace_id}"}} 1'
        if run_info not in text.splitlines():
            raise SystemExit(f"Prometheus exposition lacks {run_info!r}; got:\n{text[:1500]}")
        for series in ("sheeprl_serve_requests_total", "sheeprl_telemetry_spans_recorded"):
            if f"\n{series} " not in "\n" + text:
                raise SystemExit(f"Prometheus exposition lacks the {series} series:\n{text[:1500]}")

        # -- surface 2: the rollback event row carries the same id -----------
        write_generation(fixture["ckpt_dir"], perturb(fixture["state"]), step=200)
        _wait_until(
            lambda: any(e.get("event") == "serve_reload_rollback" for e in _read_events(events_path)),
            90,
            "the canary-tripped rollback to reach health/events.jsonl",
            log_file,
        )
        rollback = next(e for e in _read_events(events_path) if e["event"] == "serve_reload_rollback")
        if rollback.get("trace_id") != trace_id:
            raise SystemExit(f"rollback event trace_id={rollback.get('trace_id')!r} != {trace_id!r}: {rollback}")
        # the one-shot failpoint is spent: the retry must land generation 2
        _wait_until(
            lambda: rpc(addr, {"op": "health"}).get("gen", 0) >= 2,
            90,
            "the post-rollback retry to hot-reload generation 2",
            log_file,
        )

        # -- surface 3: shutdown exports the Perfetto trace at trace_path ----
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=90)
        if rc != 0:
            with open(log_file) as f:
                raise SystemExit(f"server exited rc={rc} on SIGTERM; log tail:\n{f.read()[-2000:]}")
    finally:
        if proc.poll() is None:
            proc.kill()

    with open(stats_file) as f:
        stats = json.load(f)
    if stats.get("trace_id") != trace_id:
        raise SystemExit(f"shutdown stats trace_id={stats.get('trace_id')!r} != {trace_id!r}")
    trace_path = stats.get("trace_path")
    if not trace_path or not os.path.isfile(trace_path):
        raise SystemExit(f"shutdown stats trace_path={trace_path!r} missing or not a file")
    with open(trace_path) as f:
        doc = json.load(f)
    if doc["metadata"]["trace_id"] != trace_id:
        raise SystemExit(f"exported trace metadata trace_id={doc['metadata']['trace_id']!r} != {trace_id!r}")
    names = [ev.get("name") for ev in doc["traceEvents"]]
    for required in ("serve/request", "serve/queue_wait", "serve/infer", "serve/reload"):
        if required not in names:
            raise SystemExit(f"exported trace lacks a {required!r} span; spans seen: {sorted(set(names))}")
    rollbacks = [
        ev
        for ev in doc["traceEvents"]
        if ev.get("name") == "serve/reload" and ev.get("args", {}).get("rollback")
    ]
    if not rollbacks:
        raise SystemExit("exported trace has no rollback-marked serve/reload span")
    if any(ev.get("args", {}).get("trace_id") not in (None, trace_id) for ev in doc["traceEvents"]):
        raise SystemExit("exported trace mixes foreign trace ids")

    return {
        "workdir": workdir,
        "wall_s": round(time.monotonic() - started, 2),
        "trace_id": trace_id,
        "trace_path": trace_path,
        "trace_spans": len(doc["traceEvents"]),
        "rollback_event": rollback,
        "serve_ok": stats.get("Serve/ok"),
        "spans_recorded": stats.get("Telemetry/spans_recorded"),
    }


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--workdir", default=None, help="drill directory (default: fresh tempdir)")
    parser.add_argument("--timeout", type=float, default=300.0, help="overall budget in seconds")
    cli = parser.parse_args()
    result = main(cli.workdir, cli.timeout)
    print(
        "telemetry smoke OK: "
        f"trace id {result['trace_id']} joined the Prometheus op, the rollback row in "
        f"health/events.jsonl, and the {result['trace_spans']}-event Perfetto export at "
        f"{result['trace_path']} ({result['wall_s']}s)"
    )
