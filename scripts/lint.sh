#!/usr/bin/env bash
# Static analysis over the default tree (sheeprl_tpu/ + scripts/).
# Exit 0 clean, 1 unsuppressed findings. See howto/static_analysis.md.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m sheeprl_tpu.analysis "$@"
